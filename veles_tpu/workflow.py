"""Workflow — the container unit holding and executing the unit graph.

TPU-native counterpart of reference veles/workflow.py:87.  Preserved
capabilities: dependency-ordered initialization with partial re-queue,
worklist-driven run loop delimited by StartPoint/EndPoint, aggregation of
the per-unit master-slave data contract in dependency order, per-method
run-time statistics, Graphviz graph generation, run-results gathering,
source checksum, and package export for the native inference runtime.

TPU-first difference: the run loop is a flat worklist (no recursion, no
reactor); the numeric hot path is expected to be fused by
veles_tpu.compiler into jitted step functions so that a whole training
iteration is one XLA dispatch rather than a chain of kernel launches.
"""

import hashlib
import inspect
import json
import sys
import threading
import time
from collections import deque

from veles_tpu.mutable import Bool
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.plumbing import EndPoint, StartPoint
from veles_tpu.units import Unit

__all__ = ["Workflow", "NoMoreJobs", "AcceleratedWorkflow",
           "restore_workflow"]


class NoMoreJobs(Exception):
    """Raised by a unit when the job stream is exhausted
    (reference: workflow.py:82)."""


def restore_workflow(path, launcher=None):
    """Restore a workflow from a (manifest-verified) snapshot and
    re-home it: attach it to ``launcher`` and mark it restored so
    initialize() applies the post-restore gate fixups.  The single
    bootstrap path behind ``-w`` / ``--resume`` and programmatic
    resumes."""
    from veles_tpu.snapshotter import SnapshotterBase
    workflow = SnapshotterBase.import_file(path)
    if not isinstance(workflow, Workflow):
        from veles_tpu.snapshotter import SnapshotError
        raise SnapshotError(
            "snapshot %s holds a %s, not a Workflow" %
            (path, type(workflow).__name__))
    if launcher is not None:
        workflow.workflow = launcher
    workflow.restored_from_snapshot_ = True
    return workflow


class Workflow(Unit):
    """Container unit; nests inside a Launcher or a parent Workflow."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self._units = []
        super(Workflow, self).__init__(workflow, **kwargs)
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self.negotiates_on_connect = True
        self._method_timers = {}
        self.result_file = kwargs.get("result_file")

    def init_unpickled(self):
        super(Workflow, self).init_unpickled()
        self._queue_lock_ = threading.Lock()
        self._worklist_ = deque()
        self._finished_ = threading.Event()
        self._running_ = False
        self._run_time_ = 0.0
        self._stop_requested_ = False
        self.restored_from_snapshot_ = False
        # stats as of the CURRENT run's start, so print_stats reports
        # per-run deltas instead of misattributing earlier runs' time
        self._stats_baseline_ = None

    # -- container behavior ------------------------------------------------

    def add_ref(self, unit):
        if unit not in self._units:
            self._units.append(unit)

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)

    @property
    def units(self):
        return list(self._units)

    @property
    def units_in_dependency_order(self):
        order = [u for u in self.start_point.dependent_units]
        rest = [u for u in self._units if u not in order]
        return order + rest

    def __getitem__(self, name):
        for unit in self._units:
            if unit.name == name:
                return unit
        raise KeyError(name)

    @property
    def workflow_mode(self):
        parent = self.workflow
        if parent is None:
            return "standalone"
        return getattr(parent, "workflow_mode", "standalone")

    @property
    def launcher(self):
        parent = self.workflow
        if isinstance(parent, Workflow):
            return parent.launcher
        return parent

    @property
    def is_running(self):
        return self._running_

    # -- initialization ----------------------------------------------------

    def initialize(self, device=None, **kwargs):
        """Initialize every unit in dependency order; units raising
        AttributeError (unsatisfied demands) get re-queued until no
        progress is made (reference: workflow.py:303,331-336)."""
        self.device = device
        if self.restored_from_snapshot_:
            # units must know they carry pickled state BEFORE their
            # initialize runs — e.g. a restored loader must NOT
            # re-shuffle (that would tear shuffled_indices away from
            # the pickled PRNG stream and break exact resume)
            for unit in self._units:
                if unit is not self:
                    unit.restored_from_snapshot = True
        queue = deque(self.units_in_dependency_order)
        deferred_errors = {}
        while queue:
            progressed = False
            requeue = deque()
            for unit in queue:
                if unit is self:
                    continue
                try:
                    unit.initialize(device=device, **kwargs)
                    progressed = True
                except AttributeError as exc:
                    requeue.append(unit)
                    deferred_errors[unit] = exc
            if not progressed and requeue:
                lines = "; ".join(
                    "%s: %s" % (u.name, deferred_errors.get(u))
                    for u in requeue)
                raise RuntimeError(
                    "workflow initialization deadlock - unsatisfied "
                    "demands: %s" % lines)
            queue = requeue
        if self.restored_from_snapshot_:
            # Units that don't remember gate state get their gates reset
            # (reference: workflow.py:338-340).
            for unit in self._units:
                if not getattr(unit, "remembers_gates", True):
                    unit.gate_block = Bool(False)
        self._is_initialized_ = True
        return True

    # -- scheduling / run loop ---------------------------------------------

    def schedule(self, dst, src):
        """Queue ``dst`` for a gate check triggered by ``src``."""
        with self._queue_lock_:
            self._worklist_.append((dst, src))

    @property
    def finished(self):
        return self._finished_.is_set()

    @property
    def stop_requested(self):
        return self._stop_requested_

    def run(self):
        """Execute the graph from start_point until end_point fires."""
        self._stopped <<= False
        self._stop_requested_ = False
        self._finished_.clear()
        self._running_ = True
        with self._queue_lock_:
            # Drop residue from a previous (stopped) run: stale worklist
            # entries and half-fired AND-gate flags would double-execute
            # units on the next run (e.g. per slave job via do_job).
            self._worklist_.clear()
        for unit in self._units:
            if unit is self:
                continue
            # a previous stop() set every unit's own stop flag; a new
            # run must clear them or the whole graph is silently
            # suppressed and the drained queue fakes a finished run
            # (non-restartable units keep it: their stop() tore down
            # resources a rerun cannot revive)
            if getattr(unit, "restartable", True):
                unit._stopped <<= False
            with unit._gate_lock_:
                for key in unit._links_from:
                    unit._links_from[key] = False
        # unit/method timers accumulate across runs; snapshot them so
        # print_stats can report THIS run (timers hold all keys a unit
        # accumulates, e.g. the input pipeline's per-stage times)
        self._stats_baseline_ = {
            "run_time": self._run_time_,
            "methods": dict(self._method_timers),
            "units": {id(u): (dict(u.timers), u.run_calls)
                      for u in self._units if u is not self},
        }
        # perf_counter, not time.time: wall-clock timers go backwards
        # under NTP adjustment and disagree with the perf_counter
        # deltas every other timer (units, pipeline stages) records
        start = time.perf_counter()
        self.event("run", "begin")
        try:
            self.start_point.run_dependent()
            while not self._finished_.is_set():
                with self._queue_lock_:
                    if not self._worklist_:
                        break
                    dst, src = self._worklist_.popleft()
                dst._check_gate_and_run(src)
            if not self._finished_.is_set():
                # Queue drained without reaching end_point: treat as
                # completion for open-ended graphs.
                self.on_workflow_finished()
        finally:
            self._running_ = False
            elapsed = time.perf_counter() - start
            self._run_time_ += elapsed
            if _tracer.active:
                _tracer.complete("%s.run" % self.name, start, elapsed,
                                 cat="workflow")
            self.event("run", "end")
        return True

    def on_workflow_finished(self):
        # per-unit end-of-run hook (e.g. the input pipeline joins its
        # prefetch worker so no thread outlives the run)
        for unit in self._units:
            if unit is self:
                continue
            hook = getattr(unit, "on_workflow_finish", None)
            if hook is not None:
                try:
                    hook()
                except Exception:
                    self.exception("on_workflow_finish failed for %s",
                                   unit)
        self._finished_.set()
        self._stopped <<= True
        launcher = self.launcher
        if launcher is not None and self.workflow is launcher:
            on_finished = getattr(launcher, "on_workflow_finished", None)
            if on_finished is not None:
                on_finished()

    def stop(self):
        self._stop_requested_ = True
        self._stopped <<= True
        self._finished_.set()
        for unit in self._units:
            if unit is not self:
                unit.stop()

    # -- master-slave contract (job level; see parallel/ for on-pod SPMD) --

    def _timed_method(self, name, fn, *args):
        start = time.perf_counter()
        try:
            return fn(*args)
        finally:
            elapsed = time.perf_counter() - start
            self._method_timers[name] = (
                self._method_timers.get(name, 0.0) + elapsed)
            if _tracer.active:
                _tracer.complete(name, start, elapsed, cat="distributed")

    def generate_data_for_master(self):
        return [self._timed_method(
            "generate_data_for_master", u.generate_data_for_master)
            for u in self._distributed_units()]

    def generate_data_for_slave(self, slave=None):
        data = []
        for unit in self._distributed_units():
            part = self._timed_method(
                "generate_data_for_slave", unit.generate_data_for_slave,
                slave)
            if part is False:
                return False  # not ready: sync point
            data.append(part)
        return data

    def apply_data_from_master(self, data):
        units = self._distributed_units()
        for unit, part in zip(units, data):
            if part is not None:
                self._timed_method(
                    "apply_data_from_master", unit.apply_data_from_master,
                    part)

    def apply_data_from_slave(self, data, slave=None):
        units = self._distributed_units()
        for unit, part in zip(units, data):
            if part is not None:
                self._timed_method(
                    "apply_data_from_slave", unit.apply_data_from_slave,
                    part, slave)
        return True

    #: how the Server validates update payloads (docs/distributed.md):
    #: "prewalk" — a standalone ``health.all_finite`` pass over the
    #: WHOLE update before any part applies (all-or-nothing; required
    #: while per-step parameter deltas ride the protocol, because a
    #: partially-applied update would break the exact-requeue
    #: guarantee); "inline" — single-traversal validate-during-apply
    #: below (the SPMD split sets this: updates are control records
    #: only, gradients ride ICI inside the compiled step).
    update_validation = "prewalk"

    def apply_update_validated(self, data, slave=None):
        """Single-traversal master update path: each unit's part is
        finiteness-validated immediately before ITS apply — one walk
        over the payload instead of the prewalk-then-apply double walk
        — raising :class:`veles_tpu.health.PoisonedUpdate` before the
        poisoned part mutates anything.

        Contract: only valid when updates carry CONTROL records
        (loader bookkeeping, decision metrics), i.e. when the SPMD
        data plane owns the gradients.  Parts applied before a later
        part's poison was found stay applied; with control-only
        payloads the server's drop + requeue recovers them exactly
        like a slave death mid-session, whereas per-step parameter
        deltas would need the all-or-nothing prewalk (see
        ``update_validation``)."""
        from veles_tpu import health
        units = self._distributed_units()
        for unit, part in zip(units, data):
            if part is None:
                continue
            if not health.all_finite(part):
                raise health.PoisonedUpdate(unit)
            self._timed_method(
                "apply_data_from_slave", unit.apply_data_from_slave,
                part, slave)
        return True

    def generate_initial_data_for_slave(self, slave=None):
        # The False "not ready" sentinel has no meaning at connect time;
        # normalise it to None so it is never applied as a payload.
        data = []
        for unit in self._distributed_units():
            if not getattr(unit, "negotiates_on_connect", False):
                continue
            part = unit.generate_data_for_slave(slave)
            data.append(None if part is False else part)
        return data

    def apply_initial_data_from_master(self, data):
        units = [u for u in self._distributed_units()
                 if getattr(u, "negotiates_on_connect", False)]
        for unit, part in zip(units, data):
            if part is not None and part is not False:
                unit.apply_data_from_master(part)

    def drop_slave(self, slave=None):
        for unit in self._distributed_units():
            unit.drop_slave(slave)

    def unserved_remainder(self):
        """Elastic resharding input (Server._reshard): how much of the
        current epoch's sample space is not yet applied.  Delegates to
        the first unit exposing the probe (the loader owns the
        class-window accounting); None = unknown."""
        for unit in self._distributed_units():
            probe = getattr(unit, "unserved_remainder", None)
            if probe is not None:
                return probe()
        return None

    def apply_reshard(self, info):
        """Slave-side reshard hook (docs/distributed.md, "Elasticity
        contract"): the master repartitioned the epoch's unserved
        remainder after a membership change.  Record the fleet view
        and forward to every unit that wants the hint (the loader
        keeps it next to its window bookkeeping).  Advisory: job
        payloads remain the authoritative work assignment."""
        self.fleet_info_ = dict(info)
        for unit in self._distributed_units():
            hook = getattr(unit, "apply_reshard", None)
            if hook is not None:
                hook(info)

    def _distributed_units(self):
        return [u for u in self.units_in_dependency_order if u is not self]

    def do_job(self, data, update, callback):
        """Slave-side job execution: apply job, merge own previous update,
        run the graph, return the new update (reference:
        workflow.py:558-574)."""
        self.apply_data_from_master(data)
        if update is not None:
            self.apply_data_from_slave(update, None)
        try:
            self.run()
        except NoMoreJobs:
            pass
        callback(self.generate_data_for_master())

    # -- introspection / reporting ----------------------------------------

    @property
    def checksum(self):
        """SHA1 of the defining source file (reference: workflow.py:851),
        used by the control plane handshake."""
        try:
            path = inspect.getsourcefile(type(self))
            with open(path, "rb") as fin:
                digest = hashlib.sha1(fin.read())
        except (TypeError, OSError):
            digest = hashlib.sha1()
        digest.update(type(self).__name__.encode())
        return digest.hexdigest()

    def generate_graph(self):
        """Return the control-flow graph as Graphviz dot text."""
        lines = ["digraph %s {" % type(self).__name__]
        index = {}
        for i, unit in enumerate(self._units):
            index[id(unit)] = "u%d" % i
            shape = "rect"
            if isinstance(unit, (StartPoint, EndPoint)):
                shape = "circle"
            lines.append('  u%d [label="%s", shape=%s];' %
                         (i, unit.name, shape))
        for unit in self._units:
            for dst in unit.links_to:
                if id(dst) in index and id(unit) in index:
                    lines.append("  %s -> %s;" %
                                 (index[id(unit)], index[id(dst)]))
        lines.append("}")
        return "\n".join(lines)

    def print_stats(self, top_number=5, out=None, cumulative=False):
        """Report where the LAST run's time went (per-run deltas
        against the snapshot taken at ``run()`` start; pass
        ``cumulative=True`` for lifetime totals)."""
        out = out or sys.stdout
        base = None if cumulative else self._stats_baseline_

        def base_unit(unit):
            if base is None:
                return {}, 0
            return base["units"].get(id(unit), ({}, 0))

        def unit_time(unit, key="run"):
            return unit.timers.get(key, 0.0) - \
                base_unit(unit)[0].get(key, 0.0)

        timed = sorted(((unit_time(u), u)
                        for u in self._units if u is not self),
                       key=lambda pair: -pair[0])
        total = sum(t for t, _ in timed) or 1e-12
        run_time = self._run_time_ - (base["run_time"] if base else 0.0)
        out.write("---- Workflow run time: %.3f s%s ----\n" % (
            run_time, "" if cumulative else " (this run)"))
        for elapsed, unit in timed[:top_number]:
            out.write("  %6.2f%%  %8.3f s  %s (%d runs)\n" % (
                100.0 * elapsed / total, elapsed, unit.name,
                unit.run_calls - base_unit(unit)[1]))
        for unit in self._units:
            # extra per-unit timer keys (e.g. the input pipeline's
            # pipeline_wait / pipeline_fill / pipeline_h2d stages)
            extra = [(k, unit_time(unit, k))
                     for k in sorted(unit.timers) if k != "run"]
            extra = [(k, v) for k, v in extra if v > 0.0]
            if extra:
                pipeline = getattr(unit, "_pipeline_", None)
                depth = ("depth %d, " % pipeline.depth
                         if pipeline is not None else "")
                out.write("  %s stage timers (%s):\n    %s\n" % (
                    unit.name, depth.rstrip(", ") or "per-run",
                    ", ".join("%s %.3f s" % (k, v)
                              for k, v in extra)))
        if self._method_timers:
            deltas = sorted(
                (name, elapsed - (base["methods"].get(name, 0.0)
                                  if base else 0.0))
                for name, elapsed in self._method_timers.items())
            deltas = [(n, e) for n, e in deltas if e > 0.0]
            if deltas:
                out.write("  distributed methods:\n")
                for name, elapsed in deltas:
                    out.write("    %8.3f s  %s\n" % (elapsed, name))

    def gather_results(self):
        """Collect metrics from every IResultProvider-like unit
        (reference: workflow.py:827-849)."""
        results = {}
        for unit in self._units:
            getter = getattr(unit, "get_metric_values", None)
            if getter is not None:
                try:
                    results.update(getter())
                except Exception:
                    self.exception("gather_results failed for %s", unit)
        return results

    def write_results(self, file=None):
        path = file or self.result_file
        if not path:
            return
        with open(path, "w") as fout:
            json.dump(self.gather_results(), fout, indent=1, default=repr,
                      sort_keys=True)

    def package_export(self, path, precision="float32"):
        """Export trained state for the native inference runtime
        (reference: workflow.py:868); see veles_tpu/package.py."""
        from veles_tpu.package import export_workflow
        return export_workflow(self, path, precision=precision)

    @property
    def computing_power(self):
        device = getattr(self, "device", None)
        return device.computing_power if device is not None else 0.0

    def __getstate__(self):
        state = super(Workflow, self).__getstate__()
        state["_workflow"] = None  # the launcher never pickles
        return state


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device (reference: accelerated_units.py:827)."""

    def __init__(self, workflow, **kwargs):
        super(AcceleratedWorkflow, self).__init__(workflow, **kwargs)
        self.device = None

    def initialize(self, device=None, **kwargs):
        if device is None:
            from veles_tpu.backends import Device
            # backend=None -> VELES_BACKEND / root.common.engine
            # resolution, same as the launcher
            device = Device(backend=None)
        return super(AcceleratedWorkflow, self).initialize(
            device=device, **kwargs)
