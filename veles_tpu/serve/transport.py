"""Binary frame transport: the serving hot path without JSON.

The committed closed-loop sweep (BENCH_serve.json) hits its knee on
CPU time in tornado+json at ~7 ms/request — base-10 text encode/decode
of every probability, per-element Python float boxing, HTTP header
parsing — while the engine itself dispatches in microseconds.  This
module is the fix: a persistent-connection listener speaking
``network_common``'s length-prefixed ``!IIB`` framing (JSON control
header + raw payload + optional HMAC-SHA256), with tensors as a fixed
**dtype/shape/raw-bytes codec** instead of the control plane's pickled
payloads.

Trust boundary (docs/serving.md): the serve port NEVER unpickles.  A
tensor frame's header carries ``{"dtype", "shape", "codec"}`` and the
payload is the C-order buffer; :func:`decode_tensor` admits only
numeric/bool dtypes and bounds the element count, so a hostile frame
can produce a ProtocolError or a numpy array — never code execution.
HMAC stays available (``VELES_TPU_SECRET`` / ``secret=``) and is
verified before the header is parsed, exactly like the control plane.

Wire format (one request-reply per in-flight frame, pipelined per
connection in order):

===========  ==========================================================
frame        JSON header + payload
===========  ==========================================================
hello  ->    ``{"op": "hello", "mid", "shm"?, "shm_reply"?,
             "trace"?: true}``
hello  <-    ``{"op": "hello", "mid", "digest", "dtype",
             "sample_shape", "max_batch", "shm_ok",
             "shm_reply_ok"}``
infer  ->    ``{"op": "infer", "id", "dtype", "shape", "codec",
             "shm"?: [off, len], "trace"?: str}`` + raw tensor bytes
             (inline or shm)
result <-    ``{"op": "result", "id", "dtype", "shape", "codec",
             "shm"?: [off, len], "trace"?, "segs"?}`` + raw tensor
             bytes
error  <-    ``{"op": "error", "id", "error", "transient"?,
             "retry_after"?}``
ping/bye     liveness / clean shutdown
===========  ==========================================================

Same-host clients hand payload bytes over :class:`ShmChannel`
shared-memory segments (one per direction; the strict in-order
request-reply discipline keeps the two-slot layout safe) — the socket
then carries only the ~100-byte control header.  The CLIENT creates
both segments and the server only attaches (size-bounded), acking
each road separately in the hello reply — so the server never
allocates at a peer's request and neither side ever commits to a
channel the other could not map.  A segment that goes stale or closed
mid-connection falls back to inline payloads instead of failing the
request; ``serve.transport.{socket,shm}_{rx,tx}_bytes`` counters
receipt which road the bytes took (tests/test_transport.py asserts
the bypass).

Fleet links (docs/serving.md "Multi-host tier"): a hello carrying
``"pipeline": true`` switches the connection into the router↔host
mode :mod:`veles_tpu.serve.fleet` speaks — many ``infer`` frames in
flight at once, each dispatched concurrently and answered by ``id``
(out of order), plus a best-effort ``{"op": "cancel", "id"}`` frame
that drops a hedged loser before (or instead of) its reply.  The
pipelined mode never negotiates shm (the two-slot layout NEEDS the
in-order discipline) and a cancelled request is answered with
*nothing* — the router already forgot the copy; exactly-once is the
router's accounting, the cancel only bounds wasted work.  When the
server was built with ``host_meta`` (a serve HOST in a fleet), the
hello reply carries a ``"host"`` block: the host id plus the pool's
compile receipt summary — how a rejoining host proves it re-warmed
from the persistent cache (``new_compiles == 0``) before re-entering
rotation.  Chaos points ``serve.host.stall`` (this request parks
``param`` seconds — the induced straggler the hedging A/B measures)
and ``serve.host.preempt`` (``kill`` = SIGKILL self, the subprocess
soak's mid-stream host death; any other action severs the
connection) fire per served frame.
"""

import asyncio
import os
import signal
import socket as _socketmod
import threading
import time

import numpy

from veles_tpu import chaos
from veles_tpu.logger import Logger
from veles_tpu.network_common import (
    ProtocolError, ShmChannel, default_secret, get_codec, machine_id,
    pack_frame, read_frame, read_frame_sync, write_frame)
from veles_tpu.observe import requests as reqtrace
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.serve import qos
from veles_tpu.serve.batcher import ServeOverload

__all__ = ["encode_tensor", "decode_tensor", "BinaryTransportServer",
           "BinaryTransportClient"]

#: dtype kinds the wire admits: floats, (un)signed ints, bool.  Never
#: object/void/str — the codec must not be able to smuggle pickles.
_SAFE_KINDS = frozenset("fiub")
#: element-count ceiling per tensor (mirrors network_common._MAX_LEN's
#: role: a hostile shape must not allocate unbounded memory)
_MAX_ELEMS = 1 << 28
#: per-frame byte ceiling on the serve port — far above any ladder
#: batch, far below the control plane's 1 GiB: a hostile length prefix
#: fails at the prefix (connection dropped) instead of parking the
#: reader buffering bytes that never arrive
MAX_FRAME_BYTES = 64 << 20


def encode_tensor(arr, codec="none"):
    """Tensor -> (header fields, payload bytes).  The header rides the
    frame's JSON header; the bytes are the raw C-order buffer (through
    the shared compression table for codecs other than ``none``)."""
    arr = numpy.ascontiguousarray(arr)
    if arr.dtype.kind not in _SAFE_KINDS:
        raise ValueError("refusing non-numeric dtype %s on the wire"
                         % arr.dtype)
    meta = {"dtype": arr.dtype.str, "shape": list(arr.shape),
            "codec": codec}
    raw = arr.tobytes()
    if codec != "none":
        raw = get_codec(codec)[0](raw)
    return meta, raw


def decode_tensor(meta, raw):
    """(header fields, payload bytes) -> numpy array.

    Zero-copy for the ``none`` codec: the array is a ``frombuffer``
    view over the received bytes (read-only — exactly what the
    batcher's block path wants; it either hands the buffer to
    ``Device.put``, which copies on XLA:CPU per the zero-copy hazard,
    or slice-assigns it into staging).  Every field is validated:
    unknown/object dtypes, negative or oversized shapes, and length
    mismatches raise :class:`ProtocolError` — never an allocation of
    attacker-chosen size, never an unpickle."""
    try:
        dtype = numpy.dtype(str(meta["dtype"]))
        shape = tuple(int(s) for s in meta["shape"])
        codec = str(meta.get("codec", "none"))
    except (KeyError, TypeError, ValueError) as exc:
        raise ProtocolError("malformed tensor header (%s)" % exc)
    if dtype.kind not in _SAFE_KINDS or dtype.hasobject:
        raise ProtocolError("refused dtype %r on the wire"
                            % meta.get("dtype"))
    count = 1
    for dim in shape:
        if dim < 0:
            raise ProtocolError("negative tensor dimension")
        count *= dim
    if count > _MAX_ELEMS:
        raise ProtocolError("tensor too large (%d elements)" % count)
    if codec != "none":
        try:
            raw = get_codec(codec)[1](raw)
        except ValueError:
            raise ProtocolError("unknown tensor codec %r" % codec)
        except Exception as exc:
            raise ProtocolError("tensor payload decompression failed "
                                "(%s)" % exc)
    if count * dtype.itemsize != len(raw):
        raise ProtocolError(
            "tensor length mismatch (%d x %s != %d bytes)" %
            (count, dtype, len(raw)))
    return numpy.frombuffer(raw, dtype).reshape(shape)


class _CancelledByPeer(Exception):
    """The peer cancelled this in-flight request (hedged loser): the
    serving side drops it silently — no reply frame, the router
    already retired the copy."""


class _InflightScope(object):
    """Cancellation bridge for ONE pipelined in-flight request: the
    event-loop-side cancel handler and the executor-side dispatch race
    through here.  ``add`` registers a batcher request under the scope
    (raising immediately when the cancel already landed); ``cancel``
    marks every registered request cancelled — the batcher worker
    drops undispatched ones at collect time — and releases the waiting
    executor thread with :class:`_CancelledByPeer` so it never waits
    out its timeout computing for nobody."""

    __slots__ = ("_lock", "_reqs", "cancelled")

    def __init__(self):
        self._lock = threading.Lock()
        self._reqs = []
        self.cancelled = False

    def add(self, req):
        with self._lock:
            if self.cancelled:
                req.cancelled = True
                raise _CancelledByPeer("cancelled by peer")
            self._reqs.append(req)
        return req

    def cancel(self):
        with self._lock:
            self.cancelled = True
            reqs, self._reqs = list(self._reqs), []
        for req in reqs:
            req.cancelled = True
            if not req.done.is_set():
                # racing the worker's result fill is benign: done is
                # set either way and the reply is suppressed on the
                # scope flag, not on which write landed last
                req.error = _CancelledByPeer("cancelled by peer")
                req.done.set()


class BinaryTransportServer(Logger):
    """Persistent-connection binary listener over a batcher or pool.

    ``pool`` is anything speaking the :class:`ContinuousBatcher`
    submit contract — a single batcher or a :class:`ReplicaPool`
    (whose least-loaded routing then applies per frame).  Connections
    are handled concurrently; frames within one connection are served
    in order (the discipline that keeps the two-slot shm layout safe).

    ``port=None`` starts the loop WITHOUT a TCP listener — tests adopt
    in-process ``socket.socketpair()`` duplex sockets through
    :meth:`serve_socket` and never bind a real port."""

    def __init__(self, pool, port=0, address="127.0.0.1", secret=None,
                 executor_workers=32, timeout=30.0, host_meta=None,
                 quota=None, retry_jitter=None, **kwargs):
        super(BinaryTransportServer, self).__init__(**kwargs)
        self.pool = pool
        self.address = address
        self.port = port
        self.timeout = float(timeout)
        #: per-tenant admission quota (qos.TenantQuota) — checked per
        #: infer frame BEFORE the request reaches any queue; None =
        #: quota disabled (legacy behavior, nothing rejected here)
        self.quota = quota
        self.retry_jitter = retry_jitter if retry_jitter is not None \
            else qos.RetryJitter()
        #: fleet-host identity ({"host_id": ...}) acked back in every
        #: hello reply's "host" block together with the pool's compile
        #: receipt summary; None = not a fleet host, no block
        self.host_meta = dict(host_meta) if host_meta else None
        self._secret = default_secret() if secret is None \
            else (secret or None)
        self._executor_workers = int(executor_workers)
        self._executor = None
        self._loop = None
        self._thread = None
        self._server = None
        self._writers = set()
        self._channels = set()
        self._chan_lock = threading.Lock()
        self._m_conns = _registry.counter("serve.transport.connections")
        self._m_requests = _registry.counter("serve.transport.requests")
        self._m_errors = _registry.counter("serve.transport.errors")
        self._m_sock_rx = _registry.counter(
            "serve.transport.socket_rx_bytes")
        self._m_sock_tx = _registry.counter(
            "serve.transport.socket_tx_bytes")
        self._m_shm_rx = _registry.counter(
            "serve.transport.shm_rx_bytes")
        self._m_shm_tx = _registry.counter(
            "serve.transport.shm_tx_bytes")
        self._m_latency = _registry.histogram("transport.request_s")
        # transport-owned request segments (observe/requests.py
        # taxonomy): frame decode, admission, reply encode+write
        self._h_wire_rx = _registry.histogram("serve.segment.wire_rx_s")
        self._h_wire_tx = _registry.histogram("serve.segment.wire_tx_s")
        self._h_admit = _registry.histogram("serve.segment.admit_s")
        if self.host_meta and hasattr(pool, "set_host_tag"):
            # leg attribution: request spans emitted by this host's
            # batchers carry the fleet host id, so merged cross-host
            # timelines can name the slow leg
            pool.set_host_tag(self.host_meta.get("host_id"))

    # -- lifecycle ----------------------------------------------------------

    def start_background(self):
        from concurrent.futures import ThreadPoolExecutor
        self._executor = ThreadPoolExecutor(
            max_workers=self._executor_workers,
            thread_name_prefix="serve-transport")
        started = threading.Event()
        failure = []

        def serve():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def boot():
                if self.port is not None:
                    self._server = await asyncio.start_server(
                        self._handle, host=self.address,
                        port=self.port)
                    self.port = \
                        self._server.sockets[0].getsockname()[1]

            try:
                loop.run_until_complete(boot())
            except Exception as exc:
                failure.append(exc)
                started.set()
                return
            started.set()
            try:
                loop.run_forever()
            finally:
                for task in asyncio.all_tasks(loop):
                    task.cancel()
                try:
                    loop.run_until_complete(
                        loop.shutdown_asyncgens())
                except Exception:
                    pass
                loop.close()

        self._thread = threading.Thread(target=serve,
                                        name="serve-transport")
        self._thread.start()
        started.wait()
        if failure:
            self._thread.join(timeout=5)
            self._executor.shutdown(wait=False)
            raise failure[0]
        if self.port is not None:
            self.info("binary transport on %s:%d%s", self.address,
                      self.port,
                      " (HMAC on)" if self._secret else "")
        return self._thread

    def serve_socket(self, sock):
        """Adopt an already-established socket (e.g. one end of a
        ``socket.socketpair()``) as a client connection — the
        in-process duplex path the transport tests use so tier-1 never
        binds a real port."""
        if self._loop is None:
            raise RuntimeError("start_background() first")

        async def adopt():
            reader, writer = await asyncio.open_connection(sock=sock)
            asyncio.ensure_future(self._handle(reader, writer))

        asyncio.run_coroutine_threadsafe(adopt(), self._loop).result(5)

    def stop(self):
        loop, self._loop = self._loop, None
        if loop is not None:
            async def shutdown():
                if self._server is not None:
                    self._server.close()
                    await self._server.wait_closed()
                    self._server = None
                for writer in list(self._writers):
                    try:
                        writer.close()
                    except Exception:
                        pass
            try:
                asyncio.run_coroutine_threadsafe(
                    shutdown(), loop).result(5)
            except Exception:
                pass
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None
        # a handler parked on a read when the loop died never reached
        # its finally: close whatever segments are still registered
        with self._chan_lock:
            leftovers, self._channels = set(self._channels), set()
        for chan in leftovers:
            chan.close()

    # -- connection handling ------------------------------------------------

    def _track(self, chan):
        if chan is not None:
            with self._chan_lock:
                self._channels.add(chan)
        return chan

    def _attach_bounded(self, name):
        """Attach a client-created segment — refusing one sized past
        the frame ceiling (the segment is client-owned; the bound is
        about what this server is willing to map and write)."""
        try:
            chan = ShmChannel.attach(str(name))
        except Exception:
            return None
        if chan.slot_size > MAX_FRAME_BYTES:
            chan.close()
            return None
        return self._track(chan)

    def _untrack_close(self, chan):
        if chan is not None:
            with self._chan_lock:
                self._channels.discard(chan)
            chan.close()

    async def _handle(self, reader, writer):
        self._m_conns.inc()
        self._writers.add(writer)
        chan_in = chan_out = None
        try:
            hello, _ = await read_frame(reader, secret=self._secret,
                                        max_len=MAX_FRAME_BYTES)
            if hello.get("op") != "hello":
                raise ProtocolError("expected hello, got %r"
                                    % hello.get("op"))
            engine = self.pool.engine
            same_host = hello.get("mid") == machine_id()
            pipelined = bool(hello.get("pipeline"))
            # connection-default QoS identity: a client that labels
            # its hello stamps every frame on this link; individual
            # infer frames may still override per request, and
            # un-labelled legacy clients fall through to class "batch"
            conn_tenant = hello.get("tenant")
            conn_class = hello.get("slo_class")
            # connection-default request tracing: a truthy hello
            # "trace" asks the server to mint an id for every frame
            # that does not carry its own (fleet links send explicit
            # per-frame ids instead)
            conn_trace = bool(hello.get("trace"))
            reply = {
                "op": "hello", "mid": machine_id(),
                "digest": engine.digest,
                "dtype": engine.dtype.str,
                "sample_shape": list(engine.sample_shape),
                "max_batch": engine.max_batch,
                "ladder": list(engine.ladder),
                "pipeline": pipelined,
                "shm_ok": False,
                "shm_reply_ok": False,
            }
            if self.host_meta is not None:
                # fleet-host identity + the re-warm receipt: a
                # rejoining host proves it deserialized its ladder
                # from the shared digest-keyed cache (new_compiles 0)
                # before the router puts it back in rotation
                host = dict(self.host_meta)
                receipt = getattr(self.pool, "compile_receipt", None) \
                    or getattr(engine, "compile_receipt", None)
                if receipt:
                    host["new_compiles"] = receipt.get("new_compiles")
                    host["cache_hits"] = receipt.get("cache_hits")
                reply["host"] = host
            # the CLIENT creates both segments and owns their size and
            # lifetime; the server only ever ATTACHES (bounded below) —
            # so a hostile hello cannot make the server allocate, and
            # an attach failure is known HERE and acked back, never
            # discovered mid-request (each side uses only channels it
            # verifiably has).  Pipelined (fleet) links never get shm:
            # the two-slot layout needs the in-order reply discipline
            # this mode deliberately gives up.
            if same_host and hello.get("shm") and not pipelined:
                chan_in = self._attach_bounded(hello["shm"])
                reply["shm_ok"] = chan_in is not None
            if same_host and hello.get("shm_reply") and not pipelined:
                chan_out = self._attach_bounded(hello["shm_reply"])
                reply["shm_reply_ok"] = chan_out is not None
            write_frame(writer, reply, secret=self._secret)
            await writer.drain()
            if pipelined:
                await self._handle_pipelined(reader, writer,
                                             tenant=conn_tenant,
                                             slo_class=conn_class,
                                             trace_default=conn_trace)
                return
            while True:
                try:
                    msg, payload = await read_frame(
                        reader, secret=self._secret,
                        max_len=MAX_FRAME_BYTES)
                except (asyncio.IncompleteReadError, ConnectionError,
                        OSError):
                    break
                op = msg.get("op")
                if op == "bye":
                    break
                if op == "ping":
                    write_frame(writer,
                                {"op": "pong", "id": msg.get("id")},
                                secret=self._secret)
                    await writer.drain()
                    continue
                if op == "telemetry":
                    write_frame(writer, self._telemetry_reply(msg),
                                secret=self._secret)
                    await writer.drain()
                    continue
                if op != "infer":
                    raise ProtocolError("unknown op %r" % op)
                # in-order per connection: the reply goes out before
                # the next frame is read, which is what makes the
                # two-slot shm layout race-free
                await self._serve_one(msg, payload, chan_in, chan_out,
                                      writer, tenant=conn_tenant,
                                      slo_class=conn_class,
                                      trace_default=conn_trace)
        except ProtocolError as exc:
            self._m_errors.inc()
            self.debug("transport protocol error: %s", exc)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer went away: clean close
        finally:
            self._untrack_close(chan_in)
            self._untrack_close(chan_out)
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _handle_pipelined(self, reader, writer, tenant=None,
                                slo_class=None, trace_default=False):
        """The fleet-link loop: every ``infer`` frame becomes its own
        task (replies out of order, matched by id), ``cancel`` frames
        retire in-flight scopes, and frame WRITES are serialized by
        one lock so concurrent replies never interleave bytes.  On
        disconnect every in-flight scope is cancelled: a dead link's
        requests must not keep executor threads waiting out their
        timeouts for a peer that is gone."""
        write_lock = asyncio.Lock()
        inflight = {}
        tasks = set()

        async def one(msg, payload, scope):
            try:
                await self._serve_one(msg, payload, None, None, writer,
                                      write_lock=write_lock,
                                      scope=scope, tenant=tenant,
                                      slo_class=slo_class,
                                      trace_default=trace_default)
            except (ConnectionError, OSError):
                # chaos sever / peer gone: drop the whole connection
                try:
                    writer.close()
                except Exception:
                    pass
            finally:
                inflight.pop(msg.get("id"), None)

        try:
            while True:
                try:
                    msg, payload = await read_frame(
                        reader, secret=self._secret,
                        max_len=MAX_FRAME_BYTES)
                except (asyncio.IncompleteReadError, ConnectionError,
                        OSError):
                    break
                op = msg.get("op")
                if op == "bye":
                    break
                if op == "ping":
                    async with write_lock:
                        write_frame(writer,
                                    {"op": "pong", "id": msg.get("id")},
                                    secret=self._secret)
                        await writer.drain()
                    continue
                if op == "cancel":
                    scope = inflight.get(msg.get("id"))
                    if scope is not None:
                        scope.cancel()
                    continue
                if op == "telemetry":
                    async with write_lock:
                        write_frame(writer, self._telemetry_reply(msg),
                                    secret=self._secret)
                        await writer.drain()
                    continue
                if op != "infer":
                    raise ProtocolError("unknown op %r" % op)
                scope = inflight[msg.get("id")] = _InflightScope()
                task = asyncio.ensure_future(one(msg, payload, scope))
                tasks.add(task)
                task.add_done_callback(tasks.discard)
        finally:
            for scope in list(inflight.values()):
                scope.cancel()
            for task in list(tasks):
                task.cancel()

    def _telemetry_reply(self, msg):
        """One telemetry poll answered in-line: NTP echo timestamps
        (the poller's t0 comes back with our t1/t2, so the router's
        t3 closes a clock-probe sample — telemetry polls double as
        the fleet's clock sync) plus the series buckets NEW since the
        last poll, straight in the JSON frame.  Ticks the process
        ring first so a serve host needs no Heartbeat to bucketize.
        A telemetry failure costs the buckets, never the link."""
        now = time.time()
        reply = {"op": "telemetry", "id": msg.get("id"),
                 "t0": msg.get("t0"), "t1": now, "t2": now}
        host_id = self.host_meta.get("host_id") \
            if self.host_meta else None
        if host_id is not None:
            reply["host"] = host_id
        try:
            from veles_tpu.observe.timeseries import series
            series.maybe_tick()
            reply["series"] = series.take_chunk(label=host_id)
        except Exception:
            reply["series"] = None
        return reply

    def _fire_host_chaos(self):
        """The fleet-host fault surface (docs/health.md table), fired
        per served frame: ``serve.host.stall`` parks this request
        ``param`` seconds (the induced straggler request hedging must
        beat), ``serve.host.preempt`` kills the host mid-stream
        (``kill`` = SIGKILL self for subprocess soaks; anything else
        severs the connection — the in-process stand-in).  Both points
        also fire HOST-SCOPED (``point:host_id``, the network_common
        peer-scope convention) so an in-process multi-host harness can
        arm ONE straggler while its siblings stay healthy.  Returns
        the stall seconds (awaited by the caller so a pipelined stall
        parks only its own task, never the link)."""
        stall = 0.0
        if chaos.plan is None:
            return stall
        host_id = self.host_meta.get("host_id") \
            if self.host_meta else None

        def fire(point):
            fault = chaos.plan.fire(point)
            if fault is None and host_id is not None:
                fault = chaos.plan.fire("%s:%s" % (point, host_id))
            return fault

        fault = fire("serve.host.stall")
        if fault is not None:
            stall = fault.param if fault.param else 0.05
        fault = fire("serve.host.preempt")
        if fault is not None:
            if fault.action == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise ConnectionError("chaos: serve.host.preempt")
        return stall

    async def _serve_one(self, msg, payload, chan_in, chan_out,
                         writer, write_lock=None, scope=None,
                         tenant=None, slo_class=None,
                         trace_default=False):
        start = time.perf_counter()
        rid = msg.get("id")
        self._m_requests.inc()
        # per-frame QoS labels override the hello's connection default
        tenant = msg.get("tenant", tenant)
        slo_class = qos.normalize_class(msg.get("slo_class", slo_class))
        shadow = bool(msg.get("shadow"))
        # request trace id: per-frame id (validated — plain bounded
        # string, the never-unpickle trust boundary is unchanged) wins;
        # the hello's trace default mints one per frame for clients
        # that opted in without supplying ids
        trace = None
        if reqtrace.enabled:
            trace = reqtrace.normalize_trace_id(msg.get("trace"))
            if trace is None and (trace_default or
                                  msg.get("trace") is True):
                trace = reqtrace.mint_trace_id()

        async def reply_frame(frame, raw=b""):
            if write_lock is None:
                write_frame(writer, frame, payload=raw,
                            secret=self._secret)
                await writer.drain()
            else:
                async with write_lock:
                    write_frame(writer, frame, payload=raw,
                                secret=self._secret)
                    await writer.drain()

        try:
            if self.quota is not None and not shadow:
                # shadow (canary mirror) frames are evidence, not
                # tenant load: never quota-charged, never counted
                wait = self.quota.admit(tenant)
                if wait is not None:
                    # over-quota: reject BEFORE any queue sees the
                    # request, shed attributed to the tenant's class,
                    # retry_after seeded-jittered per class so a
                    # synchronized flood does not re-stampede
                    qos.note_shed(slo_class)
                    raise ServeOverload(
                        "tenant %r over quota" % (tenant,),
                        retry_after=self.retry_jitter.apply(
                            max(wait, 0.05), slo_class))
            stall = self._fire_host_chaos()
            if stall:
                await asyncio.sleep(stall)
            t_rx = time.perf_counter()
            if "shm" in msg:
                if chan_in is None:
                    raise ProtocolError(
                        "shm descriptor without an attached channel")
                offset, length = (int(v) for v in msg["shm"])
                raw = chan_in.read(offset, length)
                self._m_shm_rx.inc(len(raw))
            else:
                raw = payload
                self._m_sock_rx.inc(len(raw))
            arr = decode_tensor(msg, raw)
            wire_rx = time.perf_counter() - t_rx
            if trace is not None:
                # admit covers quota + chaos gating (start -> decode
                # begin); wire_rx the frame decode — kept sequential so
                # the request track nests cleanly
                self._h_admit.observe(t_rx - start)
                self._h_wire_rx.observe(wire_rx)
            loop = asyncio.get_event_loop()
            result, reqs = await loop.run_in_executor(
                self._executor, self._infer, arr, scope, slo_class,
                shadow, trace, [("admit", start, t_rx - start),
                                ("wire_rx", t_rx, wire_rx)]
                if trace is not None else None)
            if scope is not None and scope.cancelled:
                return  # hedged loser: the peer forgot this copy
            t_tx = time.perf_counter()
            meta, raw_out = encode_tensor(
                result, codec=str(msg.get("codec", "none")))
            reply = {"op": "result", "id": rid}
            reply.update(meta)
            if trace is not None:
                # echo the id + the aggregated per-segment seconds so
                # a fleet front (or any client) can attribute this
                # leg's time without a trace file round-trip — plain
                # bounded JSON values only
                reply["trace"] = trace
                segs = {}
                for req in reqs:
                    for name, _, dur in (req.marks or ()):
                        segs[name] = segs.get(name, 0.0) + max(0.0, dur)
                if segs:
                    reply["segs"] = {name: round(dur, 6)
                                     for name, dur in segs.items()}
            if chan_out is not None:
                slot = None
                try:
                    slot = chan_out.write(raw_out)
                except Exception:
                    slot = None  # stale segment: inline fallback
                if slot is not None:
                    reply["shm"] = list(slot)
                    self._m_shm_tx.inc(len(raw_out))
                    raw_out = b""
            if raw_out:
                self._m_sock_tx.inc(len(raw_out))
            await reply_frame(reply, raw_out)
            if trace is not None:
                self._h_wire_tx.observe(time.perf_counter() - t_tx)
        except _CancelledByPeer:
            return  # no reply: cancelled requests answer with nothing
        except ServeOverload as exc:
            self._m_errors.inc()
            await reply_frame({
                "op": "error", "id": rid, "error": str(exc),
                "transient": True,
                "retry_after": round(exc.retry_after, 4),
            })
        except (ProtocolError, ValueError, TypeError) as exc:
            self._m_errors.inc()
            await reply_frame(
                {"op": "error", "id": rid, "error": str(exc)})
        except (ConnectionError, OSError):
            raise
        except Exception as exc:
            self._m_errors.inc()
            self.exception("transport request failed")
            await reply_frame(
                {"op": "error", "id": rid, "error": str(exc)})
        finally:
            elapsed = time.perf_counter() - start
            self._m_latency.observe(elapsed)
            if _tracer.active:
                args = {"trace": trace} if trace is not None else None
                _tracer.complete("transport.request", start, elapsed,
                                 cat="serve", args=args)

    def _infer(self, arr, scope=None, slo_class=None, shadow=False,
               trace=None, marks_prefix=None):
        """Blocking dispatch (executor thread): single samples ride
        :meth:`submit`, contiguous blocks ride :meth:`submit_block` —
        the zero-intermediate-copy path — chunked at the ladder top.
        Returns ``(block, requests)`` — the 2-D result plus the
        batcher requests it rode, so the caller can echo their segment
        timelines.  ``scope`` (pipelined mode) registers every batcher
        request so a wire cancel can retire them mid-flight instead of
        computing for a departed peer.  ``shadow`` frames (canary
        mirrors from a fleet front) ride :meth:`submit_shadow` so they
        are excluded from the served and tenant counters; a dropped
        shadow answers with a transient error — lost evidence, never a
        failed request.  ``trace`` labels every request of the frame;
        ``marks_prefix`` (wire_rx/admit marks stamped by the IO side)
        is prepended to the first request's timeline."""
        engine = self.pool.engine
        shape = engine.sample_shape
        track = scope.add if scope is not None else (lambda req: req)
        if shadow:
            if arr.shape != shape:
                raise ValueError(
                    "shadow frames mirror single samples only, got %s"
                    % (arr.shape,))
            req = self.pool.submit_shadow(arr, trace=trace)
            if req is None:
                raise ServeOverload(
                    "shadow mirror dropped (host loaded)",
                    retry_after=0.05)
            requests, single = [track(req)], True
        elif arr.shape == shape:
            requests = [track(self.pool.submit(arr,
                                               slo_class=slo_class,
                                               trace=trace))]
            single = True
        elif arr.shape[1:] == shape and arr.ndim == len(shape) + 1 \
                and arr.shape[0] >= 1:
            single = False
            requests = []
            try:
                for i in range(0, arr.shape[0], engine.max_batch):
                    requests.append(track(self.pool.submit_block(
                        arr[i:i + engine.max_batch],
                        slo_class=slo_class, trace=trace)))
            except Exception:
                for req in requests:
                    req.cancelled = True
                raise
        else:
            raise ValueError("expected sample shape %s or a batch of "
                             "them, got %s" % (shape, arr.shape))
        if marks_prefix and \
                getattr(requests[0], "marks", None) is None:
            # best-effort: the worker may already have completed the
            # request, in which case the wire marks stay histogram-only
            requests[0].marks = list(marks_prefix)
        rows = []
        try:
            for req in requests:
                if not req.done.wait(self.timeout):
                    raise TimeoutError(
                        "inference timed out after %.1fs"
                        % self.timeout)
                if req.error is not None:
                    raise req.error
                rows.append(req.result)
        except Exception:
            # a failed/timed-out chunk must not leave its siblings
            # computing for nobody (same discipline as infer_payload)
            for req in requests:
                if not req.done.is_set():
                    req.cancelled = True
            raise
        if single:
            return rows[0][None], requests
        return (rows[0] if len(rows) == 1
                else numpy.concatenate(rows)), requests


class BinaryTransportClient(object):
    """Synchronous persistent-connection client (load generators,
    same-host services, tests).

    One request in flight at a time (``infer`` is serialized by a
    lock): the closed-loop shape the latency-bound benchmarks model,
    and the discipline the shm slots rely on.  ``sock=`` adopts an
    established socket (tests pair it with ``serve_socket``); ``shm=``
    offers the same-host shared-memory bypass, silently degrading to
    inline payloads when the segment cannot be created, attached, or
    has gone stale."""

    def __init__(self, host="127.0.0.1", port=None, sock=None,
                 secret=None, shm=True, shm_slot_mb=4.0, codec="none",
                 timeout=30.0, tenant=None, slo_class=None,
                 trace=False):
        #: QoS identity stamped into the hello as this connection's
        #: default (every frame inherits it server-side; per-call
        #: overrides ride infer(..., slo_class=...)).  None = legacy
        #: un-labelled client, served as class "batch"
        self.tenant = tenant
        self.slo_class = slo_class
        #: request tracing opt-in: a truthy hello "trace" makes the
        #: server mint an id per frame; per-call ids override via
        #: infer(..., trace="...").  The reply's id + per-segment
        #: breakdown land in :attr:`last_trace` / :attr:`last_segments`
        self.trace = bool(trace)
        self.last_trace = None
        self.last_segments = None
        if sock is None:
            sock = _socketmod.create_connection((host, port), timeout)
        else:
            sock.settimeout(timeout)
        self._sock = sock
        self._secret = default_secret() if secret is None \
            else (secret or None)
        self.codec = codec
        self._lock = threading.Lock()
        self._next_id = 0
        self._chan_out = None   # client -> server payloads
        self._chan_in = None    # server -> client payloads
        # payload-byte accounting by road (the shm-bypass receipts)
        self.socket_tx_bytes = 0
        self.socket_rx_bytes = 0
        self.shm_tx_bytes = 0
        self.shm_rx_bytes = 0
        hello = {"op": "hello", "mid": machine_id()}
        if tenant is not None:
            hello["tenant"] = tenant
        if slo_class is not None:
            hello["slo_class"] = slo_class
        if self.trace:
            hello["trace"] = True
        if shm:
            # the client creates BOTH segments (it owns size and
            # lifetime; the server only attaches what it acks), so
            # there is no client-side attach step that could fail
            # after the handshake committed to the bypass
            try:
                self._chan_out = ShmChannel.create(
                    2 * int(shm_slot_mb * (1 << 20)))
                self._chan_in = ShmChannel.create(
                    2 * int(shm_slot_mb * (1 << 20)))
                hello["shm"] = self._chan_out.name
                hello["shm_reply"] = self._chan_in.name
            except Exception:
                self._drop_channels()
        try:
            self._send(hello)
            reply, _ = self._read()
            if reply.get("op") != "hello":
                raise ProtocolError("expected hello reply, got %r"
                                    % reply.get("op"))
        except Exception:
            # a failed handshake must not leak the created segments
            self._drop_channels()
            raise
        self.server_digest = reply.get("digest")
        self.server_dtype = numpy.dtype(str(reply.get("dtype", "<f4")))
        self.sample_shape = tuple(reply.get("sample_shape", ()))
        self.max_batch = int(reply.get("max_batch", 1))
        # keep only the roads the server confirmed it attached
        if self._chan_out is not None and not reply.get("shm_ok"):
            self._drop_chan_out()
        if self._chan_in is not None and not reply.get("shm_reply_ok"):
            chan, self._chan_in = self._chan_in, None
            chan.close()

    # -- framing ------------------------------------------------------------

    def _recv_exactly(self, n):
        buf = bytearray()
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("server closed the connection")
            buf += chunk
        return bytes(buf)

    def _send(self, msg, payload=b""):
        self._sock.sendall(pack_frame(msg, payload, self._secret))

    def _read(self):
        return read_frame_sync(self._recv_exactly, self._secret,
                               max_len=MAX_FRAME_BYTES)

    # -- API ----------------------------------------------------------------

    @property
    def shm_active(self):
        return self._chan_out is not None

    def infer(self, x, slo_class=None, tenant=None, trace=None):
        """One tensor round-trip: a sample or a contiguous batch in,
        the probability block out (numpy).  Overload answers raise
        :class:`ServeOverload` with the server's ``retry_after``.
        ``slo_class``/``tenant`` override this connection's hello
        default for one request; ``trace`` carries an explicit request
        trace id (the hello's ``trace=True`` default mints one
        server-side instead).  The reply's id and per-segment seconds
        are kept in :attr:`last_trace`/:attr:`last_segments`."""
        with self._lock:
            meta, raw = encode_tensor(x, self.codec)
            rid = self._next_id
            self._next_id += 1
            msg = {"op": "infer", "id": rid}
            if slo_class is not None:
                msg["slo_class"] = slo_class
            if tenant is not None:
                msg["tenant"] = tenant
            if trace is not None:
                msg["trace"] = trace
            msg.update(meta)
            payload = raw
            if self._chan_out is not None:
                slot = None
                try:
                    slot = self._chan_out.write(raw)
                except Exception:
                    # stale/closed segment mid-flight: drop the channel
                    # and fall back to the socket — the request still
                    # serves (tests/test_transport.py)
                    self._drop_chan_out()
                if slot is not None:
                    msg["shm"] = list(slot)
                    payload = b""
                    self.shm_tx_bytes += len(raw)
            if payload:
                self.socket_tx_bytes += len(payload)
            self._send(msg, payload)
            reply, rpayload = self._read()
            if reply.get("op") == "error":
                if reply.get("transient"):
                    raise ServeOverload(
                        reply.get("error", "overloaded"),
                        retry_after=float(
                            reply.get("retry_after", 0.1)))
                raise RuntimeError(reply.get("error", "serve error"))
            if reply.get("op") != "result" or reply.get("id") != rid:
                raise ProtocolError("unexpected reply %r" % reply)
            self.last_trace = reply.get("trace")
            self.last_segments = reply.get("segs")
            if "shm" in reply and self._chan_in is not None:
                offset, length = (int(v) for v in reply["shm"])
                rraw = self._chan_in.read(offset, length)
                self.shm_rx_bytes += len(rraw)
            else:
                rraw = rpayload
                self.socket_rx_bytes += len(rraw)
            return decode_tensor(reply, rraw)

    def ping(self):
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._send({"op": "ping", "id": rid})
            reply, _ = self._read()
            return reply.get("op") == "pong"

    def _drop_chan_out(self):
        chan, self._chan_out = self._chan_out, None
        if chan is not None:
            chan.close()

    def _drop_channels(self):
        self._drop_chan_out()
        chan, self._chan_in = self._chan_in, None
        if chan is not None:
            chan.close()

    def close(self):
        try:
            self._send({"op": "bye"})
        except Exception:
            pass
        try:
            self._sock.close()
        except Exception:
            pass
        self._drop_channels()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
