"""Multi-host serve tier: one front, many hosts, hedged tails.

PR 10's :class:`ReplicaPool` scales serving across one host's chips;
the ROADMAP north star — millions of users — needs a router tier that
spans HOSTS and survives losing one mid-stream.  The TPU in-datacenter
paper's framing (PAPERS.md) is the design constraint: inference is
p99-bound, not throughput-bound, so a straggling or dying host must
cost bounded tail latency and NEVER a failed request.  This module is
that tier (docs/serving.md "Multi-host tier"):

- **membership** rides :class:`veles_tpu.elastic.FleetView` — every
  host join/leave bumps a membership epoch, exactly like the training
  fleet's elasticity contract (docs/distributed.md).  A host joins
  when its pipelined binary-transport link (``serve/transport.py``
  framing + HMAC handshake, ``"pipeline": true`` hello) handshakes
  with a matching model digest; it leaves when the link severs —
  connection error, SIGKILL, or chaos ``serve.host.preempt``.  Shares
  are weighted by the **measured per-host throughput EMA**
  (``FleetView.observe_throughput``), not static power ratings: the
  router observes every completion, so a host that slows down loses
  routing weight within a handful of requests.
- **routing** is PR 10's least-loaded pick with overload cascade,
  lifted to host granularity: each request goes to the live host with
  the lowest throughput-weighted in-flight count; a host that sheds
  (transient error frame) cascades the request to its siblings, and
  only when EVERY live host shed does the front answer 503-shaped
  :class:`ServeOverload` carrying the fleet-minimum ``retry_after``.
- **request hedging** generalizes PR 9's speculative backup dispatch
  fleet-wide: a watchdog compares every single-copy in-flight request
  against :func:`veles_tpu.elastic.speculation_threshold` (the same
  power-corrected MapReduce bar, fed the throughput EMAs) and past it
  re-dispatches the request to a sibling host.  **First result wins**;
  the loser is cancelled over the wire (best-effort — exactly-once
  is the router's accounting, not the cancel's).
- **exactly-once fences**: every dispatched copy gets a fresh wire id
  and bumps its request's *epoch*; a result is accepted only while
  its wire id is still registered AND the request is unresolved.  A
  hedged request is therefore never answered twice (the second copy's
  result finds the entry resolved → ``serve.hedge.duplicates_dropped``)
  and never dropped when both copies race a host death (a dead host's
  copies are retired and, when no live sibling copy remains, the
  request is **requeued** to a survivor under a new epoch —
  ``serve.fleet.requeues`` — transparently to the waiting client).
- **re-warm before rotation**: a (re)joining host's hello carries its
  pool's compile-receipt summary; a host that restarted against the
  shared digest-keyed persistent cache reports ``new_compiles == 0``
  — the receipt the rejoin test and the soak assert before the router
  counts the host live.

The soak receipt (``scripts/fleet_soak.py`` → ``HEDGE.json``):
SIGKILL of a serve host mid-stream costs bounded p99 and zero failed
requests (every in-flight request on the dead link re-answered by
survivors, bit-identical to the unhedged reference), and hedging
measurably cuts p99 under an induced ``serve.host.stall`` straggler
vs hedging-off.
"""

import itertools
import random
import socket as _socketmod
import threading
import time
from collections import deque

import numpy

from veles_tpu import chaos, elastic
from veles_tpu.logger import Logger
from veles_tpu.network_common import (
    ProtocolError, default_secret, machine_id, pack_frame,
    read_frame_sync)
from veles_tpu.observe import requests as reqtrace
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.serve import qos
from veles_tpu.serve.batcher import ServeOverload
from veles_tpu.serve.transport import (
    MAX_FRAME_BYTES, decode_tensor, encode_tensor)

__all__ = ["FleetRequest", "FleetRouter", "HostLink"]


class _LinkIdle(Exception):
    """The link had NO traffic for a keepalive interval (timeout at a
    frame boundary, zero bytes read): not a failure — the reader
    pings and keeps listening.  A timeout MID-frame is a real link
    problem and stays an error."""


class HostLink(object):
    """One pipelined router→host connection.

    The hello carries ``"pipeline": true`` so the host dispatches every
    ``infer`` frame concurrently and answers by id (out of order); the
    link then supports many in-flight requests — sends serialized by
    one lock, replies dispatched by a reader thread through the
    router's callbacks.  ``send_cancel`` retires a hedged loser
    best-effort.  The reader thread MUST be joined (:meth:`close`);
    the router joins links it retired at :meth:`FleetRouter.stop`.

    After the handshake the socket timeout drops to ``keepalive_s``:
    an idle interval at a frame boundary makes the reader PING the
    host and keep listening (an idle fleet must not retire healthy
    hosts just for having no traffic), while a dead peer fails the
    ping/read and reports down.  The short timeout also bounds how
    long a send into a wedged host's full buffer can stall (the
    router dispatches under its lock, so that bound is fleet-wide
    back-pressure, not just this link's).
    """

    def __init__(self, sock=None, host=None, port=None, secret=None,
                 timeout=30.0, keepalive_s=5.0):
        if sock is None:
            sock = _socketmod.create_connection((host, port), timeout)
        else:
            sock.settimeout(timeout)
        self._sock = sock
        self._secret = default_secret() if secret is None \
            else (secret or None)
        self._send_lock = threading.Lock()
        self._thread = None
        self._frame_started = False
        self.keepalive_s = float(keepalive_s)
        self.closed = False
        self._send({"op": "hello", "mid": machine_id(),
                    "pipeline": True})
        reply, _ = self._read()
        if reply.get("op") != "hello":
            raise ProtocolError("expected hello reply, got %r"
                                % reply.get("op"))
        if not reply.get("pipeline"):
            raise ProtocolError(
                "host does not speak the pipelined fleet link "
                "(pre-fleet serve transport?)")
        self.digest = reply.get("digest")
        self.dtype = numpy.dtype(str(reply.get("dtype", "<f4")))
        self.sample_shape = tuple(reply.get("sample_shape", ()))
        self.max_batch = int(reply.get("max_batch", 1))
        self.ladder = tuple(int(b) for b in
                            reply.get("ladder", (self.max_batch,)))
        #: the hello's "host" block: host id + the re-warm receipt
        #: summary ({"host_id", "new_compiles", "cache_hits"})
        self.host_info = dict(reply.get("host") or {})
        # handshake done: drop to the keepalive timeout (see class
        # docstring — idle survival + bounded send stalls)
        self._sock.settimeout(self.keepalive_s)

    # -- framing ------------------------------------------------------------

    def _recv_exactly(self, n):
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except _socketmod.timeout:
                if not self._frame_started and not buf:
                    raise _LinkIdle()  # quiet link, not a dead one
                raise  # a frame stalled mid-read: real link trouble
            if not chunk:
                raise ConnectionError("host closed the connection")
            buf += chunk
            self._frame_started = True
        return bytes(buf)

    def _send(self, msg, payload=b""):
        with self._send_lock:
            self._sock.sendall(pack_frame(msg, payload, self._secret))

    def _read(self):
        self._frame_started = False
        return read_frame_sync(self._recv_exactly, self._secret,
                               max_len=MAX_FRAME_BYTES)

    # -- API ----------------------------------------------------------------

    def send_infer(self, wid, arr, slo_class=None, shadow=False,
                   trace=None):
        meta, raw = encode_tensor(arr)
        msg = {"op": "infer", "id": wid}
        if slo_class is not None:
            # the front's QoS label travels with the copy so the
            # host's batcher sheds and accounts by the SAME class
            msg["slo_class"] = slo_class
        if shadow:
            # canary-slice mirror: the host serves it via
            # submit_shadow — computed and answered, never counted in
            # the served/tenant metrics
            msg["shadow"] = True
        if trace is not None:
            # request trace id rides the copy so both hedge legs of
            # one request stamp the SAME id on their host timelines
            # (plain bounded string — observe/requests.py contract)
            msg["trace"] = trace
        msg.update(meta)
        self._send(msg, raw)

    def send_cancel(self, wid):
        self._send({"op": "cancel", "id": wid})

    def send_telemetry_poll(self):
        """One telemetry poll frame (transport ``telemetry`` op):
        ``t0`` stamps the send so the reply's t1/t2 plus receipt t3
        close an NTP clock-probe sample.  Thread-safe (the send lock)
        — the router's watchdog fires it off-reader."""
        self._send({"op": "telemetry", "id": -2, "t0": time.time()})

    def start_reader(self, on_result, on_error, on_down,
                     on_telemetry=None):
        """Spawn the reply-dispatch thread: ``on_result(wid, arr,
        msg)`` / ``on_error(wid, exc)`` per answered frame (``msg`` is
        the reply header — carries the host's echoed ``trace``/
        ``segs``), ``on_down()`` once when the link dies (or closes),
        ``on_telemetry(msg, t3)`` per telemetry-poll reply (``t3`` is
        the receipt wall stamp that closes the clock sample)."""

        def loop():
            try:
                while True:
                    try:
                        msg, payload = self._read()
                    except _LinkIdle:
                        # no traffic for a keepalive interval: PROVE
                        # the peer is alive instead of retiring it —
                        # a dead one fails the ping or the next read
                        self._send({"op": "ping", "id": -1})
                        continue
                    op = msg.get("op")
                    if op == "result":
                        try:
                            arr = decode_tensor(msg, payload)
                        except ProtocolError as exc:
                            on_error(msg.get("id"), exc)
                            continue
                        on_result(msg.get("id"), arr, msg)
                    elif op == "error":
                        if msg.get("transient"):
                            exc = ServeOverload(
                                msg.get("error", "overloaded"),
                                retry_after=float(
                                    msg.get("retry_after", 0.1)))
                        else:
                            exc = RuntimeError(
                                msg.get("error", "serve error"))
                        on_error(msg.get("id"), exc)
                    elif op == "telemetry":
                        if on_telemetry is not None:
                            try:
                                on_telemetry(msg, time.time())
                            except Exception:
                                pass  # telemetry never kills a link
                    # pong / unknown: ignore
            except (ConnectionError, OSError, ProtocolError,
                    ValueError):
                pass
            finally:
                on_down()

        self._thread = threading.Thread(target=loop, name="fleet-link")
        self._thread.start()
        return self._thread

    def close(self, join=True):
        """Close the socket (unblocking the reader) and join the
        reader thread.  ``join=False`` when called FROM the reader's
        own ``on_down`` — the router joins retired threads later."""
        if not self.closed:
            self.closed = True
            try:
                self._send({"op": "bye"})
            except Exception:
                pass
        try:
            self._sock.close()
        except Exception:
            pass
        if join and self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=10)


class FleetRequest(object):
    """One client request in the front tier — duck-types the batcher's
    ``_Request`` surface (``done``/``result``/``error``/``cancelled``)
    so :class:`ServeService` and the binary transport drive a
    :class:`FleetRouter` exactly like a pool.  ``epoch`` counts
    dispatched copies (the request-epoch half of the exactly-once
    fence); ``copies`` maps live wire ids → host ids."""

    __slots__ = ("sample", "rows", "block", "enqueued", "done",
                 "result", "error", "cancelled", "epoch", "copies",
                 "sheds", "hedges", "resolved", "slo_class", "latency",
                 "mirror", "trace", "requeues", "legs")

    def __init__(self, sample, block=False, slo_class=None,
                 trace=None):
        self.sample = sample
        self.rows = sample.shape[0] if block else 1
        self.block = block
        self.enqueued = time.perf_counter()
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.cancelled = False
        self.epoch = 0
        self.copies = {}        # wid -> host_id
        self.sheds = {}         # host_id -> retry_after offered
        self.hedges = 0
        self.resolved = False
        #: canonical SLO class — decides the class-aware inflight
        #: bound, the per-class hedge budget, and the class the host's
        #: batcher accounts the copy under
        self.slo_class = qos.normalize_class(slo_class)
        #: end-to-end seconds, stamped at resolution — the canary
        #: comparator reads it off mirrored pairs
        self.latency = None
        #: _ShadowCopy when the canary slice mirrored this request to
        #: the canary host; cleared once the pair is emitted (or the
        #: shadow failed)
        self.mirror = None
        #: request trace id (observe/requests.py) — rides every
        #: dispatched copy so hedge legs stitch under one id
        self.trace = trace
        #: times this request was requeued to a survivor after losing
        #: ALL its live copies (host death / send failure)
        self.requeues = 0
        #: dispatch-leg records, appended per copy: {"host", "start",
        #: "end", "hedge", "outcome", "segs"} — the front-tier
        #: critical-path story (serve.req.leg spans + exemplars)
        self.legs = []


class _Copy(object):
    """One dispatched copy of a request (original or hedge)."""

    __slots__ = ("wid", "entry", "host_id", "epoch", "sent_at",
                 "hedge", "leg")

    def __init__(self, wid, entry, host_id, epoch, hedge):
        self.wid = wid
        self.entry = entry
        self.host_id = host_id
        self.epoch = epoch
        self.sent_at = time.perf_counter()
        self.hedge = hedge
        #: this copy's record in entry.legs (None when untraced)
        self.leg = None


class _Host(object):
    """Router-side record of one serve host."""

    __slots__ = ("host_id", "link", "state", "inflight", "info",
                 "joined_epoch")

    def __init__(self, host_id, link, joined_epoch):
        self.host_id = host_id
        self.link = link
        self.state = "live"     # live | dead | leaving | canary
        self.inflight = set()   # wire ids currently on this host
        self.info = dict(link.host_info)
        self.joined_epoch = joined_epoch


class _ShadowCopy(object):
    """The canary-slice mirror of one request: dispatched to the
    canary host beside (never instead of) the primary copy, tracked in
    the router's SEPARATE shadow wire map so it can never trip the
    exactly-once fence, resolve the entry, or count as served."""

    __slots__ = ("entry", "host_id", "sent_at", "out", "latency")

    def __init__(self, entry, host_id):
        self.entry = entry
        self.host_id = host_id
        self.sent_at = time.perf_counter()
        self.out = None
        self.latency = None


class _CanarySlice(object):
    """Router-side state of an active fleet-canary traffic slice: ONE
    host out of rotation, a seeded fraction of single-sample traffic
    mirrored to it as shadow copies, mirrored (primary, shadow) pairs
    fed to ``on_pair`` for the comparator's verdict."""

    __slots__ = ("host_id", "fraction", "rng", "on_pair", "mirrored",
                 "pairs", "shadow_errors", "link_down", "armed")

    def __init__(self, host_id, fraction, seed, on_pair):
        self.host_id = host_id
        self.fraction = float(fraction)
        self.rng = random.Random(seed)
        self.on_pair = on_pair
        self.mirrored = 0
        self.pairs = 0
        self.shadow_errors = 0
        self.link_down = False
        #: mirroring is held off until the controller ARMS the slice —
        #: after the candidate is staged — so every judged pair really
        #: compares candidate output, never stale old-vs-old evidence
        self.armed = False


class _FleetProfile(object):
    """What the front knows about the model it fronts — learned from
    the first host's hello and enforced on every later join (the
    bit-identity contract needs ONE digest fleet-wide)."""

    __slots__ = ("digest", "dtype", "sample_shape", "max_batch",
                 "ladder")

    def __init__(self, link):
        self.digest = link.digest
        self.dtype = link.dtype
        self.sample_shape = link.sample_shape
        self.max_batch = link.max_batch
        self.ladder = link.ladder


class FleetRouter(Logger):
    """The front tier: dispatch over many serve hosts with hedged
    tails and exactly-once completion under host loss.

    Duck-types the :class:`ContinuousBatcher` submit surface
    (``submit``/``submit_block``/``infer``/``start``/``stop``/
    ``engine``/``snapshot``), so :class:`ServeService` and the binary
    transport can front a host fleet exactly like a local pool.

    ``hedge_factor``/``hedge_floor_s`` feed
    :func:`elastic.speculation_threshold` (``hedge=False`` disables
    the watchdog entirely); ``max_hedges`` bounds copies per request
    (default 1 backup — the PR 9 discipline); ``hedge_warmup``
    completed requests must land before the first hedge fires — with
    no latency evidence the threshold would collapse to the floor and
    a cold front under load would duplicate its entire first wave of
    traffic (the PR 9 jobfarm seeds its duration stats the same way).
    """

    def __init__(self, secret=None, hedge=True, hedge_factor=2.0,
                 hedge_floor_s=0.05, hedge_tick_s=0.02, max_hedges=1,
                 hedge_warmup=8, throughput_alpha=0.2,
                 link_timeout=30.0, keepalive_s=5.0, hedge_budget=None,
                 max_inflight=None, retry_jitter=None,
                 telemetry_interval_s=2.0, alert_rules=None,
                 **kwargs):
        super(FleetRouter, self).__init__(**kwargs)
        self._secret = secret
        self.hedge = bool(hedge)
        self.hedge_factor = float(hedge_factor)
        self.hedge_floor_s = float(hedge_floor_s)
        self.hedge_tick_s = float(hedge_tick_s)
        self.max_hedges = int(max_hedges)
        self.hedge_warmup = int(hedge_warmup)
        self.link_timeout = float(link_timeout)
        self.keepalive_s = float(keepalive_s)
        #: per-class hedge token buckets (qos.HedgeBudget): an
        #: exhausted class routes normally (no hedge this tick), it
        #: never fails; None = unlimited (legacy behavior)
        self.hedge_budget = hedge_budget
        #: class-aware bound on unresolved front requests: past it an
        #: incoming request evicts one of STRICTLY lower class (shed
        #: attributed to the victim) or is shed itself; None =
        #: unbounded (legacy behavior — hosts shed at their queues)
        self.max_inflight = max_inflight
        self.retry_jitter = retry_jitter if retry_jitter is not None \
            else qos.RetryJitter()
        #: unresolved entries per class — the eviction pool behind
        #: max_inflight
        self._unresolved = {cls: set() for cls in qos.SLO_CLASSES}
        #: active _CanarySlice (begin_canary_slice), or None
        self._canary = None
        #: wid -> _ShadowCopy: the canary mirror's OWN wire map —
        #: checked before the primary map so shadow replies can never
        #: trip the duplicate fence or resolve an entry
        self._shadow_wire = {}
        self.fleet = elastic.FleetView(
            throughput_alpha=throughput_alpha)
        self._lock = threading.RLock()
        self._hosts = {}            # host_id -> _Host
        self._retired = []          # dead links awaiting thread join
        self._wire = {}             # wid -> _Copy
        self._wids = itertools.count(1)
        self._auto_ids = itertools.count(1)
        self._latencies = deque(maxlen=256)
        self._profile = None
        self._stop_ = threading.Event()
        self._watchdog = None
        self._g_live = _registry.gauge("serve.fleet.hosts_live")
        self._g_epoch = _registry.gauge(
            "serve.fleet.membership_epoch")
        self._m_requests = _registry.counter("serve.fleet.requests")
        self._m_failed = _registry.counter("serve.fleet.failed")
        self._m_requeues = _registry.counter("serve.fleet.requeues")
        self._m_cascades = _registry.counter("serve.fleet.cascades")
        self._m_hedges = _registry.counter("serve.hedge.fired")
        self._m_hedge_wins = _registry.counter("serve.hedge.wins")
        self._m_dup = _registry.counter(
            "serve.hedge.duplicates_dropped")
        self._m_shed = _registry.counter("serve.fleet.shed")
        self._m_mirrors = _registry.counter("serve.fleet.canary.mirrors")
        self._m_latency = _registry.histogram("serve.fleet.latency_s")
        self._g_live.set(0)
        self._g_epoch.set(0)
        #: the fleet telemetry plane (observe/timeseries.py +
        #: observe/alerts.py): the watchdog polls every live host's
        #: link every ``telemetry_interval_s`` (0/None disables), the
        #: reply's NTP echo feeds the clock offsets, and the router's
        #: OWN alert manager evaluates ``alert_rules`` (declarative
        #: specs or AlertRule objects; None = the stock serve set)
        #: over the offset-corrected rollup after each poll round.
        self.telemetry_interval_s = float(telemetry_interval_s or 0.0)
        self.telemetry = None
        self.alerts = None
        if self.telemetry_interval_s > 0:
            from veles_tpu.observe.alerts import (AlertManager,
                                                  default_rules,
                                                  rule_from_spec)
            from veles_tpu.observe.timeseries import FleetTelemetry
            self.telemetry = FleetTelemetry(
                interval_s=self.telemetry_interval_s)
            if alert_rules is None:
                # fleet scope: the burn rules watch the front's
                # end-to-end class histograms (the ones that see
                # transport stalls), not the host serving-edge ones
                rules = default_rules(scope="fleet")
            else:
                rules = [rule_from_spec(r) if isinstance(r, dict)
                         else r for r in alert_rules]
            self.alerts = AlertManager(rules)
        self._last_poll = 0.0

    # -- membership ---------------------------------------------------------

    def add_host(self, address=None, sock=None, host_id=None):
        """Handshake a serve host into the fleet; returns its host id.

        ``address`` is ``"host:port"`` (or a ``(host, port)`` pair);
        ``sock`` adopts an established socket (tests pair it with
        ``BinaryTransportServer.serve_socket`` — no port binds).  A
        digest mismatch with the fleet's profile is REFUSED: routed
        and hedged copies must be bit-identical wherever they land,
        so one fleet serves one digest."""
        if address is not None and sock is None:
            if isinstance(address, str):
                host, _, port = address.partition(":")
                address = (host, int(port))
            link = HostLink(host=address[0], port=address[1],
                            secret=self._secret,
                            timeout=self.link_timeout,
                            keepalive_s=self.keepalive_s)
        else:
            link = HostLink(sock=sock, secret=self._secret,
                            timeout=self.link_timeout,
                            keepalive_s=self.keepalive_s)
        hid = host_id or link.host_info.get("host_id") or \
            "host-%d" % next(self._auto_ids)
        with self._lock:
            if self._profile is None:
                self._profile = _FleetProfile(link)
            elif link.digest != self._profile.digest:
                link.close()
                raise ValueError(
                    "host %s serves digest %s, fleet serves %s — "
                    "refusing a mixed fleet" %
                    (hid, link.digest, self._profile.digest))
            if hid in self._hosts and \
                    self._hosts[hid].state == "live":
                link.close()
                raise ValueError("host id %r already live" % hid)
            epoch = self.fleet.join(hid, 1.0)
            host = self._hosts[hid] = _Host(hid, link, epoch)
            self._publish_membership()
        link.start_reader(
            lambda wid, arr, msg=None: self._on_result(
                host, wid, arr, msg),
            lambda wid, exc: self._on_error(host, wid, exc),
            lambda: self._on_link_down(host),
            on_telemetry=(
                (lambda msg, t3: self._on_telemetry(hid, msg, t3))
                if self.telemetry is not None else None))
        _tracer.instant("serve.fleet.join", cat="serve", host=hid,
                        epoch=epoch,
                        new_compiles=host.info.get("new_compiles"))
        self.info("fleet host %s joined at membership epoch %d "
                  "(digest %s, re-warm new_compiles=%s)", hid, epoch,
                  link.digest, host.info.get("new_compiles"))
        return hid

    def remove_host(self, host_id):
        """Graceful leave: the host is taken out of rotation, its
        in-flight copies requeue to survivors, the link closes."""
        with self._lock:
            host = self._hosts.get(host_id)
            if host is None or host.state != "live":
                return
            host.state = "leaving"
            self._retire_host(host, reason="removed")
        host.link.close()

    def _on_link_down(self, host):
        with self._lock:
            if host.state not in ("live", "canary"):
                # graceful close or already handled: just park the
                # thread for the final join
                self._retired.append(host.link)
                return
            if host.state == "canary" and self._canary is not None \
                    and self._canary.host_id == host.host_id:
                # the canary host died mid-judgment: the slice is
                # over (the controller sees link_down and rolls back);
                # shadow copies die with it — mirrors are
                # observations, nothing requeues
                self._canary.link_down = True
            host.state = "dead"
            self._retire_host(host, reason="link down")
            self._retired.append(host.link)
        host.link.close(join=False)
        self.warning("fleet host %s LOST (membership epoch %d); "
                     "in-flight requests requeued to survivors",
                     host.host_id, self.fleet.membership_epoch)

    def _retire_host(self, host, reason):
        """Under the lock: epoch-bumped membership removal + requeue
        of every in-flight copy that has no live sibling.  The half of
        the elasticity contract that makes a SIGKILL mid-stream cost
        latency, never a failed request."""
        epoch = self.fleet.leave(host.host_id)
        self._publish_membership()
        _tracer.instant("serve.fleet.leave", cat="serve",
                        host=host.host_id, epoch=epoch, reason=reason)
        now = time.perf_counter()
        wids, host.inflight = list(host.inflight), set()
        for wid in wids:
            shadow = self._shadow_wire.pop(wid, None)
            if shadow is not None:
                # a canary mirror dies with its host: drop the record
                # so the entry's pair simply never emits
                shadow.entry.mirror = None
                continue
            copy = self._wire.pop(wid, None)
            if copy is None:
                continue
            entry = copy.entry
            entry.copies.pop(wid, None)
            if copy.leg is not None and copy.leg["end"] is None:
                copy.leg["end"] = now
                copy.leg["outcome"] = "lost"
            if entry.resolved or entry.cancelled:
                continue
            if entry.copies:
                continue  # a hedged sibling still lives: let it win
            self._m_requeues.inc()
            entry.requeues += 1
            if _tracer.active:
                # cat stays "serve": instants land on the caller's
                # thread track, which mixes request ids — the analyzer
                # matches by NAME, the trace arg attributes it
                kwargs = {"host": host.host_id, "reason": reason}
                if entry.trace is not None:
                    kwargs["trace"] = entry.trace
                _tracer.instant("serve.fleet.requeue", cat="serve",
                                **kwargs)
            try:
                self._send_copy(entry, exclude=set(entry.sheds))
            except ServeOverload as exc:
                self._resolve_error(entry, exc)

    def _publish_membership(self):
        self._g_live.set(sum(1 for h in self._hosts.values()
                             if h.state == "live"))
        self._g_epoch.set(self.fleet.membership_epoch)

    def _live_hosts(self):
        return [h for h in self._hosts.values() if h.state == "live"]

    # -- dispatch -----------------------------------------------------------

    def _host_weight(self, host_id, mean_tp):
        """Routing weight: the measured throughput EMA, or — for a
        cold (just-joined) host — the fleet mean, so it competes for
        traffic and earns a real measurement instead of starving
        against absolute rates (the neutral 1.0 is orders of
        magnitude off a measured rows/sec)."""
        tp = self.fleet.throughput(host_id, default=None)
        return tp if tp is not None else mean_tp

    def _mean_throughput(self):
        observed = [tp for tp in
                    (self.fleet.throughput(h.host_id, default=None)
                     for h in self._live_hosts()) if tp is not None]
        return sum(observed) / len(observed) if observed else 1.0

    def _pick(self, exclude):
        """Least-loaded live host outside ``exclude``, in-flight count
        weighted by the measured throughput EMA — a host that slowed
        down carries proportionally less."""
        best, best_load = None, None
        mean_tp = self._mean_throughput()
        for host in self._live_hosts():
            if host.host_id in exclude:
                continue
            load = (len(host.inflight) + 1) / \
                self._host_weight(host.host_id, mean_tp)
            if best_load is None or load < best_load:
                best, best_load = host, load
        return best

    def _send_copy(self, entry, exclude=(), hedge=False):
        """Under the lock: dispatch one copy of ``entry`` to the best
        live host outside ``exclude``; raises :class:`ServeOverload`
        with the fleet's best ``retry_after`` promise when no host is
        available.  A link that dies at send time retires its host
        (requeueing THAT host's other work) and the dispatch moves on
        to the next survivor."""
        exclude = set(exclude)
        while True:
            host = self._pick(exclude)
            if host is None:
                retry = min(entry.sheds.values()) \
                    if entry.sheds else 0.5
                raise ServeOverload(
                    "no live serve host available "
                    "(%d shed, %d live)" %
                    (len(entry.sheds), len(self._live_hosts())),
                    retry_after=retry)
            wid = next(self._wids)
            entry.epoch += 1
            copy = _Copy(wid, entry, host.host_id, entry.epoch, hedge)
            self._wire[wid] = copy
            entry.copies[wid] = host.host_id
            host.inflight.add(wid)
            if reqtrace.enabled:
                copy.leg = {"host": host.host_id,
                            "start": copy.sent_at, "end": None,
                            "hedge": hedge, "outcome": None,
                            "segs": None}
                entry.legs.append(copy.leg)
            try:
                host.link.send_infer(wid, entry.sample,
                                     slo_class=entry.slo_class,
                                     trace=entry.trace)
                return copy
            except Exception:
                del self._wire[wid]
                entry.copies.pop(wid, None)
                host.inflight.discard(wid)
                if copy.leg is not None:
                    copy.leg["end"] = time.perf_counter()
                    copy.leg["outcome"] = "send_failed"
                exclude.add(host.host_id)
                if host.state == "live":
                    host.state = "dead"
                    self._retire_host(host, reason="send failed")
                    self._retired.append(host.link)
                    host.link.close(join=False)

    def submit(self, sample, slo_class=None, trace=None):
        """Enqueue one sample on the fleet; returns the pending
        request (the batcher contract).  Raises ServeOverload when
        every live host sheds.  ``slo_class`` labels the request for
        the QoS layer; un-labelled callers default to ``batch``.
        ``trace`` is the request's trace id (observe/requests.py),
        already normalized by the front door."""
        if self._profile is None:
            raise ServeOverload("fleet has no hosts", retry_after=1.0)
        sample = numpy.ascontiguousarray(sample, self._profile.dtype)
        if sample.shape != self._profile.sample_shape:
            raise ValueError("expected sample shape %s, got %s" %
                             (self._profile.sample_shape, sample.shape))
        return self._submit_entry(
            FleetRequest(sample, slo_class=slo_class, trace=trace))

    def submit_block(self, block, slo_class=None, trace=None):
        """Enqueue a contiguous batch as ONE request (the transport's
        block path); rows stay together on one host per copy."""
        if self._profile is None:
            raise ServeOverload("fleet has no hosts", retry_after=1.0)
        block = numpy.ascontiguousarray(block, self._profile.dtype)
        if block.ndim != len(self._profile.sample_shape) + 1 or \
                block.shape[1:] != self._profile.sample_shape:
            raise ValueError("expected a (n,) + %s block, got %s" %
                             (self._profile.sample_shape, block.shape))
        if not 1 <= block.shape[0] <= self._profile.max_batch:
            raise ValueError(
                "block of %d rows overflows the fleet ladder (max %d);"
                " chunk at the caller" %
                (block.shape[0], self._profile.max_batch))
        return self._submit_entry(
            FleetRequest(block, block=True, slo_class=slo_class,
                         trace=trace))

    def _inflight_total(self):
        return sum(len(pool) for pool in self._unresolved.values())

    def _evict_lower(self, incoming_cls):
        """Under the lock: resolve one unresolved entry of STRICTLY
        lower class with ServeOverload (copies cancelled over the
        wire, shed attributed to the victim's class) to admit an
        incoming ``incoming_cls`` request past ``max_inflight``.
        Returns False when nothing lower is pending — the incoming
        request must be shed instead."""
        incoming_rank = qos.class_rank(incoming_cls)
        for victim_cls in qos.SHED_ORDER:
            if qos.class_rank(victim_cls) >= incoming_rank:
                return False
            pool = self._unresolved[victim_cls]
            while pool:
                victim = pool.pop()
                if victim.resolved or victim.cancelled:
                    continue
                victim.resolved = True
                for wid, hid in list(victim.copies.items()):
                    self._wire.pop(wid, None)
                    host = self._hosts.get(hid)
                    if host is not None:
                        host.inflight.discard(wid)
                        if host.state == "live":
                            try:
                                host.link.send_cancel(wid)
                            except Exception:
                                pass
                victim.copies.clear()
                victim.mirror = None
                self._m_shed.inc()
                qos.note_shed(victim_cls)
                victim.error = ServeOverload(
                    "shed for %s admission (class-ordered eviction)"
                    % incoming_cls,
                    retry_after=self.retry_jitter.apply(
                        self._retry_estimate(), victim_cls))
                if _tracer.active:
                    _tracer.instant("serve.fleet.shed", cat="serve",
                                    slo_class=victim_cls,
                                    evicted_for=incoming_cls)
                victim.done.set()
                return True
        return False

    def _retry_estimate(self):
        """Base retry_after for front-side sheds: the recent mean
        end-to-end latency, floored for cold fronts."""
        if self._latencies:
            return max(0.05,
                       sum(self._latencies) / len(self._latencies))
        return 0.1

    def _submit_entry(self, entry):
        self._m_requests.inc()
        with self._lock:
            if self.max_inflight is not None and \
                    self._inflight_total() >= self.max_inflight and \
                    not self._evict_lower(entry.slo_class):
                self._m_shed.inc()
                qos.note_shed(entry.slo_class)
                raise ServeOverload(
                    "fleet front full (%d unresolved)"
                    % self._inflight_total(),
                    retry_after=self.retry_jitter.apply(
                        self._retry_estimate(), entry.slo_class))
            self._send_copy(entry, exclude=set())
            self._unresolved[entry.slo_class].add(entry)
            self._maybe_mirror(entry)
        return entry

    def _maybe_mirror(self, entry):
        """Under the lock: canary-slice mirroring — a seeded fraction
        of single-sample traffic gets a shadow copy on the canary
        host, tracked in the SEPARATE shadow wire map.  Never raises:
        mirroring is an observation, the primary dispatch already
        succeeded and stands either way."""
        slice_ = self._canary
        if slice_ is None or not slice_.armed or entry.block:
            return
        if slice_.rng.random() >= slice_.fraction:
            return
        host = self._hosts.get(slice_.host_id)
        if host is None or host.state != "canary":
            return
        wid = next(self._wids)
        shadow = _ShadowCopy(entry, slice_.host_id)
        self._shadow_wire[wid] = shadow
        host.inflight.add(wid)
        try:
            host.link.send_infer(wid, entry.sample,
                                 slo_class=entry.slo_class,
                                 shadow=True, trace=entry.trace)
        except Exception:
            self._shadow_wire.pop(wid, None)
            host.inflight.discard(wid)
            slice_.shadow_errors += 1
            return
        entry.mirror = shadow
        slice_.mirrored += 1
        self._m_mirrors.inc()

    def infer(self, sample, timeout=30.0, slo_class=None, trace=None):
        """Blocking single-sample round-trip through the fleet."""
        return self._wait(
            self.submit(sample, slo_class=slo_class, trace=trace),
            timeout)

    def infer_block(self, block, timeout=30.0, slo_class=None,
                    trace=None):
        return self._wait(
            self.submit_block(block, slo_class=slo_class, trace=trace),
            timeout)

    def _wait(self, entry, timeout):
        if not entry.done.wait(timeout):
            self._abandon(entry)
            raise TimeoutError("fleet inference timed out after %.1fs"
                               % timeout)
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _abandon(self, entry):
        """Caller gave up: retire the entry's copies (cancels over the
        wire) so hosts stop computing for nobody and late results are
        rejected as duplicates."""
        with self._lock:
            entry.cancelled = True
            self._unresolved[entry.slo_class].discard(entry)
            entry.mirror = None
            for wid, hid in list(entry.copies.items()):
                self._wire.pop(wid, None)
                host = self._hosts.get(hid)
                if host is not None:
                    host.inflight.discard(wid)
                    if host.state == "live":
                        try:
                            host.link.send_cancel(wid)
                        except Exception:
                            pass
            entry.copies.clear()

    # -- completion (reader-thread callbacks) -------------------------------

    def _on_result(self, host, wid, arr, msg=None):
        now = time.perf_counter()
        with self._lock:
            shadow = self._shadow_wire.pop(wid, None)
            if shadow is not None:
                # canary mirror reply: pure evidence, NEVER a caller
                # answer — record and try to emit the judgment pair
                host.inflight.discard(wid)
                shadow.out = arr[0] if arr.ndim == 2 and \
                    not shadow.entry.block else arr
                shadow.latency = now - shadow.sent_at
                entry = shadow.entry
            else:
                entry = None
        if entry is not None:
            self._maybe_emit_pair(entry)
            return
        with self._lock:
            copy = self._wire.pop(wid, None)
            if copy is None or copy.entry.resolved or \
                    copy.entry.cancelled:
                # the exactly-once fence: a late duplicate (hedge
                # loser whose cancel lost the race, or chaos
                # serve.hedge.lose_race skipping the cancel) finds its
                # wire id retired or its entry resolved — rejected,
                # never answered twice
                self._m_dup.inc()
                host.inflight.discard(wid)
                return
            entry = copy.entry
            entry.resolved = True
            self._unresolved[entry.slo_class].discard(entry)
            host.inflight.discard(wid)
            entry.copies.pop(wid, None)
            if copy.leg is not None:
                copy.leg["end"] = now
                copy.leg["outcome"] = "win"
                copy.leg["segs"] = self._leg_segments(msg)
            latency = now - copy.sent_at
            self.fleet.observe_throughput(
                host.host_id, entry.rows / max(latency, 1e-9))
            if copy.hedge:
                self._m_hedge_wins.inc()
                if _tracer.active:
                    _tracer.instant("serve.hedge.win", cat="serve",
                                    host=host.host_id, epoch=copy.epoch)
            self._cancel_losers(entry)
        # the batcher result contract: a single-sample submit resolves
        # to the output ROW, a block submit to the 2-D block — the
        # host's transport always replies 2-D, so unwrap singles here
        # (ServeService.infer_payload and the front's own binary
        # transport both rely on row semantics)
        entry.result = arr if entry.block or arr.ndim != 2 else arr[0]
        entry.error = None
        # tenant served counters are bumped at the HOST batcher (the
        # serving edge), never here: an in-process front + host pair
        # shares one registry and would double-count otherwise
        # end-to-end latency is anchored at the ORIGINAL front-door
        # arrival (entry.enqueued, stamped once in FleetRequest): a
        # requeue or hedge re-dispatch must never restart the clock
        entry.latency = now - entry.enqueued
        self._m_latency.observe(entry.latency)
        # per-class END-TO-END latency under the FLEET name (distinct
        # from the host batcher's serve.tenant.* serving-edge series,
        # which an in-process front+host pair would double-count):
        # this is the digest the fleet-scoped SLO burn rules watch —
        # it includes transport stalls the batcher clock never sees
        _registry.histogram(
            "serve.fleet.%s.latency_s" % entry.slo_class).observe(
                entry.latency)
        self._latencies.append(entry.latency)
        entry.done.set()
        self._emit_entry(entry, now)
        self._maybe_emit_pair(entry)

    @staticmethod
    def _leg_segments(msg):
        """The host's echoed per-segment totals off a result frame —
        defensively re-validated (plain floats, known segment names
        only) even though the link is HMAC-authenticated."""
        segs = (msg or {}).get("segs")
        if not isinstance(segs, dict):
            return None
        clean = {}
        for name in reqtrace.SEGMENTS:
            value = segs.get(name)
            if isinstance(value, (int, float)) and value >= 0:
                clean[name] = float(value)
        return clean or None

    def _emit_entry(self, entry, now):
        """Outside the lock: the front tier's request-scoped
        observability for one resolved entry — tail exemplar + (for
        sampled ids) a ``serve.request`` span with ``serve.req.leg``
        children on the entry's own request track.  Per-SEGMENT spans
        live on the HOST tracks under the same id; the merge stitch
        (observe/merge.py) is what joins the two tiers."""
        if not reqtrace.enabled:
            return
        start = entry.enqueued
        marks = []
        win_segs = None
        for leg in entry.legs:
            end = min(leg["end"] if leg["end"] is not None else now,
                      now)
            marks.append(("leg", leg["start"],
                          max(0.0, end - leg["start"])))
            if leg["outcome"] == "win" and leg["segs"]:
                win_segs = (leg["start"], leg["segs"])
        if win_segs is not None:
            # synthesize sequential segment marks from the winning
            # leg's echoed totals so the exemplar timeline carries a
            # real breakdown even when the host dump is not at hand
            cursor, segs = win_segs
            for name in reqtrace.SEGMENTS:
                if name in segs:
                    marks.append((name, cursor, segs[name]))
                    cursor += segs[name]
        reqtrace.exemplars.note(
            entry.trace, entry.latency, marks=marks, t0=start,
            slo_class=entry.slo_class,
            budget_s=qos.slo_budget_s(entry.slo_class), kind="fleet",
            extra={"hedges": entry.hedges,
                   "requeues": entry.requeues,
                   "legs": [{"host": leg["host"],
                             "hedge": leg["hedge"],
                             "outcome": leg["outcome"]}
                            for leg in entry.legs]})
        if entry.trace is None or not _tracer.active or \
                not reqtrace.sampled(entry.trace):
            return
        tid = _tracer.request_track((entry.trace, start),
                                    "req:%s" % entry.trace)
        _registry.counter("serve.reqtrace.sampled").inc()
        _tracer.complete(
            reqtrace.REQUEST_SPAN, start, max(0.0, now - start),
            cat="req", args={"trace": entry.trace, "tier": "fleet",
                             "slo_class": entry.slo_class,
                             "hedges": entry.hedges,
                             "requeues": entry.requeues,
                             "legs": len(entry.legs)}, tid=tid)
        for leg in entry.legs:
            # clamp to the parent span so a loser cancelled
            # microseconds after resolution still nests
            end = min(leg["end"] if leg["end"] is not None else now,
                      now)
            args = {"trace": entry.trace, "host": leg["host"],
                    "hedge": leg["hedge"]}
            if leg["outcome"]:
                args["outcome"] = leg["outcome"]
            _tracer.complete(
                reqtrace.LEG_SPAN, leg["start"],
                max(0.0, end - leg["start"]), cat="req", args=args,
                tid=tid)

    def _cancel_losers(self, entry):
        """Under the lock: retire every other live copy of a resolved
        entry and cancel it over the wire — unless chaos
        ``serve.hedge.lose_race`` says to skip the cancel, in which
        case the loser completes and its late result deterministically
        exercises the duplicate-rejection fence.

        The loser's burned time also PENALIZES its host's throughput
        EMA: the copy ran at least this long without answering, which
        bounds that host's rate from above.  Without the penalty a
        straggler whose slow copies always get cancelled never feeds
        the EMA a bad sample — it keeps its healthy rating, stays in
        rotation, and the fleet hedges forever instead of routing
        around a persistently sick host."""
        now = time.perf_counter()
        for wid, hid in list(entry.copies.items()):
            lcopy = self._wire.pop(wid, None)
            entry.copies.pop(wid, None)
            if lcopy is not None and lcopy.leg is not None and \
                    lcopy.leg["end"] is None:
                lcopy.leg["end"] = now
                lcopy.leg["outcome"] = "cancelled"
            loser = self._hosts.get(hid)
            if loser is None:
                continue
            loser.inflight.discard(wid)
            if lcopy is not None:
                self.fleet.observe_throughput(
                    hid, entry.rows / max(now - lcopy.sent_at, 1e-9))
            skip = chaos.plan is not None and \
                chaos.plan.fire("serve.hedge.lose_race") is not None
            if not skip and loser.state == "live":
                try:
                    loser.link.send_cancel(wid)
                except Exception:
                    pass  # the link will report its own death

    def _on_error(self, host, wid, exc):
        with self._lock:
            shadow = self._shadow_wire.pop(wid, None)
            if shadow is not None:
                # a failed mirror is lost evidence, never a failed
                # request — the primary copy answers the caller
                host.inflight.discard(wid)
                if self._canary is not None:
                    self._canary.shadow_errors += 1
                shadow.entry.mirror = None
                return
            copy = self._wire.pop(wid, None)
            if copy is None or copy.entry.resolved or \
                    copy.entry.cancelled:
                host.inflight.discard(wid)
                return
            entry = copy.entry
            host.inflight.discard(wid)
            entry.copies.pop(wid, None)
            if copy.leg is not None and copy.leg["end"] is None:
                copy.leg["end"] = time.perf_counter()
                copy.leg["outcome"] = "shed" \
                    if isinstance(exc, ServeOverload) else "error"
            if isinstance(exc, ServeOverload):
                # host-granular overload cascade: remember this host's
                # promise, try the next live sibling; only when every
                # live host shed does the FLEET shed — with the
                # smallest retry_after any host offered
                entry.sheds[copy.host_id] = exc.retry_after
                if entry.copies:
                    return  # a sibling copy still runs: let it win
                try:
                    self._send_copy(entry, exclude=set(entry.sheds))
                    self._m_cascades.inc()
                except ServeOverload as fleet_exc:
                    self._resolve_error(entry, fleet_exc)
                return
            if entry.copies:
                return  # the sibling copy may still succeed
            self._resolve_error(entry, exc)

    def _resolve_error(self, entry, exc):
        entry.resolved = True
        self._unresolved[entry.slo_class].discard(entry)
        for wid in list(entry.copies):
            self._wire.pop(wid, None)
        entry.copies.clear()
        entry.mirror = None
        self._m_failed.inc()
        entry.error = exc
        entry.done.set()

    # -- telemetry polling --------------------------------------------------

    def _on_telemetry(self, host_id, msg, t3):
        """One host's telemetry-poll reply (reader thread): the NTP
        echo closes a clock-probe sample (min-delay estimate, same as
        trace merging), the carried series chunk lands in the fleet
        merge, then the alert rules sweep the offset-corrected
        rollup.  The router's own alert manager is EDGE-triggered —
        a stall that keeps burning fires once, with the flight +
        exemplar evidence dump riding the firing."""
        telemetry = self.telemetry
        if telemetry is None:
            return
        t0, t1, t2 = msg.get("t0"), msg.get("t1"), msg.get("t2")
        if all(isinstance(t, (int, float)) for t in (t0, t1, t2)):
            # convention matches cluster.estimate_offset: host_wall +
            # offset = router_wall
            telemetry.add_probe(host_id, (t0, t1, t2, t3))
        chunk = msg.get("series")
        if chunk:
            telemetry.add_chunk(host_id, chunk)
        alerts = self.alerts
        if alerts is not None:
            fired = alerts.evaluate(
                telemetry.rollup(window=64),
                context={"scope": "fleet", "host": host_id})
            for record in fired:
                self.warning("fleet alert %s: %s", record["alert"],
                             record["reason"])

    def _poll_telemetry(self, now):
        if self.telemetry is None or \
                now - self._last_poll < self.telemetry_interval_s:
            return
        self._last_poll = now
        # the router's own process metrics join the merge as host
        # "front" (offset 0 by construction — it IS the reference
        # clock); front + host series then roll up in one pass
        try:
            from veles_tpu.observe.timeseries import series
            series.maybe_tick()
            chunk = series.take_chunk(label="front")
            if chunk is not None:
                self.telemetry.add_chunk("front", chunk)
        except Exception:
            pass
        with self._lock:
            hosts = self._live_hosts()
        for host in hosts:
            try:
                host.link.send_telemetry_poll()
            except Exception:
                pass  # a dying link's reader handles the death

    # -- hedging watchdog ---------------------------------------------------

    def _watch_loop(self):
        while not self._stop_.wait(self.hedge_tick_s):
            now = time.perf_counter()
            self._poll_telemetry(now)
            if not self.hedge:
                continue
            with self._lock:
                if len(self._live_hosts()) < 2:
                    continue  # nobody to hedge to
                if len(self._latencies) < self.hedge_warmup:
                    # no evidence yet: a floor-collapsed threshold on
                    # a cold front would hedge-storm the first wave
                    continue
                mean = sum(self._latencies) / len(self._latencies)
                mean_tp = self._mean_throughput()
                for copy in list(self._wire.values()):
                    entry = copy.entry
                    if entry.resolved or entry.cancelled or \
                            len(entry.copies) != 1 or \
                            entry.hedges >= self.max_hedges:
                        continue
                    threshold = elastic.speculation_threshold(
                        mean, self.hedge_factor, self.hedge_floor_s,
                        owner_power=self._host_weight(copy.host_id,
                                                      mean_tp),
                        mean_power=mean_tp)
                    if now - copy.sent_at <= threshold:
                        continue
                    if self.hedge_budget is not None and \
                            not self.hedge_budget.try_take(
                                entry.slo_class):
                        # budget exhausted for this class: route
                        # normally — the primary copy stands, the
                        # request NEVER fails for lack of hedge tokens
                        continue
                    entry.hedges += 1
                    try:
                        self._send_copy(
                            entry,
                            exclude={copy.host_id} | set(entry.sheds),
                            hedge=True)
                    except ServeOverload:
                        entry.hedges -= 1  # retry a later tick
                        continue
                    self._m_hedges.inc()
                    if _tracer.active:
                        _tracer.instant(
                            "serve.hedge.fired", cat="serve",
                            owner=copy.host_id,
                            age_ms=round((now - copy.sent_at) * 1e3,
                                         3),
                            threshold_ms=round(threshold * 1e3, 3))

    # -- lifecycle (batcher duck-type) --------------------------------------

    @property
    def running(self):
        return self._watchdog is not None or \
            bool(self._live_hosts())

    def start(self):
        if (self.hedge or self.telemetry is not None) and \
                self._watchdog is None:
            self._stop_.clear()
            self._watchdog = threading.Thread(
                target=self._watch_loop, name="fleet-hedge")
            self._watchdog.start()
        return self

    def stop(self):
        self._stop_.set()
        watchdog, self._watchdog = self._watchdog, None
        if watchdog is not None:
            watchdog.join(timeout=10)
        with self._lock:
            hosts = list(self._hosts.values())
            self._hosts.clear()
            retired, self._retired = list(self._retired), []
            for host in hosts:
                # a front shutting down is not a host death: the
                # links' readers must not count membership losses
                if host.state == "live":
                    host.state = "leaving"
            # fail whatever is still pending: callers must not block
            # out their timeouts on a stopped front
            for copy in list(self._wire.values()):
                if not copy.entry.resolved:
                    self._resolve_error(
                        copy.entry,
                        ServeOverload("fleet front shutting down",
                                      retry_after=1.0))
            self._wire.clear()
            self._shadow_wire.clear()
            self._canary = None
            for pool in self._unresolved.values():
                pool.clear()
        for host in hosts:
            host.link.close()
        for link in retired:
            link.close()
        self._g_live.set(0)

    # -- canary slicing (fleet canary controller hooks) ---------------------

    def begin_canary_slice(self, host_id, fraction=0.25, seed=0,
                           on_pair=None):
        """Take ``host_id`` out of the routing rotation and mirror a
        seeded ``fraction`` of live single-sample traffic to it as
        shadow copies.  ``on_pair(primary_out, shadow_out,
        primary_latency, shadow_latency)`` fires (outside the lock)
        once BOTH sides of a mirrored request answered — the fleet
        canary controller's evidence stream.

        The host keeps draining its previously-assigned inflight work
        (it is ``canary``, not ``dead``); it just receives no new
        PRIMARY dispatches, so the staged candidate only ever answers
        shadow traffic until promotion."""
        with self._lock:
            if self._canary is not None:
                raise RuntimeError(
                    "a canary slice is already active on %r"
                    % self._canary.host_id)
            host = self._hosts.get(host_id)
            if host is None or host.state != "live":
                raise RuntimeError(
                    "cannot slice host %r: not a live host" % host_id)
            if not any(h.state == "live"
                       for h in self._hosts.values()
                       if h.host_id != host_id):
                raise RuntimeError(
                    "cannot slice host %r: no live sibling would "
                    "remain to serve primary traffic" % host_id)
            host.state = "canary"
            self._canary = _CanarySlice(host_id, fraction, seed,
                                        on_pair)
            if _tracer.active:
                _tracer.instant("serve.fleet.canary.begin",
                                cat="serve", host=host_id,
                                fraction=fraction)
            return self._canary

    def end_canary_slice(self):
        """Tear down the active slice: purge the shadow wire, restore
        the host to the routing rotation (unless it died mid-slice)
        and return the slice's evidence counters."""
        with self._lock:
            slice_, self._canary = self._canary, None
            if slice_ is None:
                return None
            for wid in list(self._shadow_wire):
                rec = self._shadow_wire.pop(wid)
                rec.entry.mirror = None
                host = self._hosts.get(rec.host_id)
                if host is not None:
                    host.inflight.discard(wid)
            host = self._hosts.get(slice_.host_id)
            if host is not None and host.state == "canary":
                host.state = "live"
            if _tracer.active:
                _tracer.instant("serve.fleet.canary.end", cat="serve",
                                host=slice_.host_id,
                                mirrored=slice_.mirrored,
                                pairs=slice_.pairs)
            return {"host_id": slice_.host_id,
                    "mirrored": slice_.mirrored,
                    "pairs": slice_.pairs,
                    "shadow_errors": slice_.shadow_errors,
                    "link_down": slice_.link_down}

    def host_inflight(self, host_id):
        """How many wire ids (primary + shadow) the host still owes —
        the controller drains this to 0 before staging a candidate so
        old-model work never mixes with new-model judging."""
        with self._lock:
            host = self._hosts.get(host_id)
            return len(host.inflight) if host is not None else 0

    def _maybe_emit_pair(self, entry):
        """Emit the (primary, shadow) judgment pair once both sides of
        a mirrored request answered.  The callback runs OUTSIDE the
        lock — comparator judging must never stall reader threads."""
        with self._lock:
            shadow = entry.mirror
            if shadow is None or shadow.out is None or \
                    entry.result is None or not entry.resolved:
                return
            entry.mirror = None
            slice_ = self._canary
            if slice_ is None:
                return
            slice_.pairs += 1
            on_pair = slice_.on_pair
        if on_pair is None:
            return
        try:
            on_pair(entry.result, shadow.out, entry.latency,
                    shadow.latency)
        except Exception:
            pass  # judging is evidence collection, never a fault path

    # -- metadata (pool duck-type) ------------------------------------------

    @property
    def engine(self):
        """The fleet's model profile (digest/dtype/sample shape/
        ladder), learned at the first host's handshake — what
        /healthz reports the fleet serves."""
        if self._profile is None:
            raise RuntimeError("fleet has no hosts yet")
        return self._profile

    @property
    def digest(self):
        return self._profile.digest if self._profile else None

    @property
    def compile_receipt(self):
        """Aggregate of the per-host hello re-warm receipts."""
        hosts = {hid: dict(h.info) for hid, h in self._hosts.items()}
        if not hosts:
            return None
        return {
            "hosts": hosts,
            "new_compiles": sum(
                h.get("new_compiles") or 0 for h in hosts.values()),
        }

    def reload(self, *args, **kwargs):
        raise RuntimeError(
            "the fleet front holds no model: reload/publish on the "
            "serve HOSTS (each is a full PR-12 freshness fleet) and "
            "rejoin them")

    reload_workflow = reload

    # -- observability ------------------------------------------------------

    def snapshot(self):
        """Plain-data fleet state for /healthz and the dashboard."""
        with self._lock:
            hosts = {
                h.host_id: {
                    "state": h.state,
                    "inflight": len(h.inflight),
                    "throughput_ema": round(
                        self.fleet.throughput(h.host_id), 3),
                    "joined_epoch": h.joined_epoch,
                    "new_compiles": h.info.get("new_compiles"),
                }
                for h in self._hosts.values()}
            return {
                "hosts": hosts,
                "hosts_live": sum(1 for h in self._hosts.values()
                                  if h.state == "live"),
                "membership_epoch": self.fleet.membership_epoch,
                "digest": self.digest,
                "hedging": self.hedge,
                "hedges_fired": self._m_hedges.value,
                "hedge_wins": self._m_hedge_wins.value,
                "duplicates_dropped": self._m_dup.value,
                "requeues": self._m_requeues.value,
                "max_inflight": self.max_inflight,
                "unresolved": {
                    cls: len(pool)
                    for cls, pool in self._unresolved.items()},
                "canary": None if self._canary is None else {
                    "host_id": self._canary.host_id,
                    "fraction": self._canary.fraction,
                    "mirrored": self._canary.mirrored,
                    "pairs": self._canary.pairs,
                    "shadow_errors": self._canary.shadow_errors,
                },
                "telemetry": None if self.telemetry is None
                else self.telemetry.snapshot(),
                "alerts": None if self.alerts is None
                else self.alerts.snapshot(),
            }

    def fleet_rollup(self, window=None):
        """Offset-corrected fleet rollup buckets (empty when
        telemetry is off) — the ``observe fleet`` CLI's live
        counterpart."""
        if self.telemetry is None:
            return []
        return self.telemetry.rollup(window=window)
