"""Train-to-serve freshness loop: watch, verify, canary, promote.

The last seam in the production story (ROADMAP "close the loop"): the
trainer's snapshotter *publishes* every manifest-verified snapshot into
a watched directory (:func:`veles_tpu.snapshotter.publish_snapshot` —
atomic ``LATEST`` pointer, export-ordinal ordered), and this module is
the serve half that carries the model the rest of the way — or pulls a
bad one back out:

- :class:`SnapshotWatcher` polls the publish directory (or is pushed
  via ``POST /publish`` -> :meth:`notify`) and **verifies the manifest
  before unpickling** — the ``snapshotter.import_file`` discipline —
  so a truncated, torn, or tampered publish is rejected at the
  watcher, never loaded.  A half-written snapshot or transient
  manifest mismatch is *skipped and retried* with bounded backoff (a
  publisher mid-copy is normal, not an incident); only a publish that
  stays invalid past ``invalid_ttl_s`` is raised to the flight
  recorder and counted ``serve.freshness.poisoned_rejected``.
- :class:`CanaryComparator` judges the candidate against the live
  fleet on mirrored traffic, reusing the divergence watchdog's EMA
  spike discipline (:class:`veles_tpu.health.EmaSpikeWatch`, PR 3) on
  canary-vs-baseline latency, plus an absolute output-divergence bound
  and a hard non-finite-output tripwire.
- :class:`FreshnessController` runs the loop: finite-gate the params,
  AOT-warm the candidate in the background (PR 10's per-replica
  warm-up), enter the router's :class:`~veles_tpu.serve.router.
  CanaryCutover` state machine, mirror a seeded traffic slice to the
  canary (shadow requests are never returned to clients and never
  counted in served metrics), then **promote** fleet-wide (rolling,
  between batches) or **auto-roll back** to the last-good digest —
  swap-backs only, zero new compiles by construction.

The "In-Datacenter Performance Analysis of a TPU" framing applies: a
bad model push *is* an outage, so every transition here is reversible,
receipted, and observable (``serve.freshness.*`` counters ride
heartbeats and the web-status serve column; ``serve.canary`` instants
mark begin/promoted/rolled_back in traces and the flight ring).
``scripts/freshness_soak.py`` is the chaos-soak receipt (FRESH.json).
"""

import collections
import os
import pickle
import random
import threading
import time

import numpy

from veles_tpu import chaos
from veles_tpu.health import EmaSpikeWatch, all_finite
from veles_tpu.logger import Logger
from veles_tpu.observe.flight import flight as _flight
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.snapshotter import (
    SnapshotterBase, read_latest)
from veles_tpu.tune.cache import BANK_FILE_NAME as _BANK_FILE_NAME

__all__ = ["CanaryComparator", "FleetCanaryController",
           "FreshnessController", "LocalHostControl", "ModelCandidate",
           "SnapshotWatcher", "export_model_spec"]

#: keys a published "model spec" pickle must carry (the lightweight
#: alternative to a whole-workflow snapshot: what the serve fleet
#: actually needs, nothing else)
SPEC_KEYS = frozenset(("plans", "params", "sample_shape"))


def export_model_spec(path, plans, params, sample_shape):
    """Write a *model spec* snapshot — ``{"plans", "params",
    "sample_shape"}`` — with the snapshotter's crash-consistency
    contract (tmp -> fsync -> ``os.replace``) and a sidecar manifest,
    so it is publishable via :func:`snapshotter.publish_snapshot` and
    verifiable by the watcher exactly like a whole-workflow snapshot.

    This is the soak/test-sized publish format; real trainers publish
    their workflow snapshots via ``Snapshotter(publish_dir=...)``.
    Honors the ``snapshot.write`` chaos point (``crash`` dies with a
    half-written ``.tmp`` and no final file — the torn-export case the
    loop must survive)."""
    payload = pickle.dumps(
        {"plans": list(plans), "params": [dict(p) for p in params],
         "sample_shape": tuple(sample_shape)},
        protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fout:
        fault = chaos.plan.fire("snapshot.write") \
            if chaos.plan is not None else None
        if fault is not None and fault.action == "crash":
            fout.write(payload[:max(1, len(payload) // 2)])
            fout.flush()
            raise chaos.ChaosCrash("simulated crash mid-spec-export")
        fout.write(payload)
        fout.flush()
        os.fsync(fout.fileno())
    os.replace(tmp, path)
    SnapshotterBase.write_manifest(path, workflow_name="ModelSpec")
    return path


class ModelCandidate(object):
    """One verified, loaded publish: what the controller judges."""

    __slots__ = ("ordinal", "path", "sha256", "plans", "params",
                 "sample_shape")

    def __init__(self, ordinal, path, sha256, plans, params,
                 sample_shape):
        self.ordinal = ordinal
        self.path = path
        self.sha256 = sha256
        self.plans = plans
        self.params = params
        self.sample_shape = sample_shape


class SnapshotWatcher(Logger):
    """Poll (or be pushed) the publish directory; hand VERIFIED
    candidates to a callback.

    Failure discipline (the satellite fix): a half-written snapshot or
    transient manifest mismatch — a publisher mid-copy, an NFS rename
    still settling — is skipped and retried with bounded exponential
    backoff, logged at debug so a poll tick never warn-spams; only an
    ordinal that stays invalid past ``invalid_ttl_s`` is escalated:
    ONE warning, a flight-recorder dump, ``serve.freshness.
    poisoned_rejected`` + permanent rejection of that ordinal (a newer
    publish supersedes it the moment it lands)."""

    def __init__(self, watch_dir, callback=None, poll_s=0.25,
                 invalid_ttl_s=10.0, max_backoff_s=2.0,
                 default_sample_shape=None, **kwargs):
        super(SnapshotWatcher, self).__init__(**kwargs)
        self.watch_dir = watch_dir
        self.callback = callback
        self.poll_s = float(poll_s)
        self.invalid_ttl_s = float(invalid_ttl_s)
        self.max_backoff_s = float(max_backoff_s)
        self.default_sample_shape = default_sample_shape
        self.last_ordinal = 0
        self._rejected = set()
        self._pending = None  # {"ordinal", "first_bad", "backoff",
        #                        "next_try"}: the skip-and-retry state
        self._bank_stamp = None  # (mtime_ns, size) of the last
        #                          merged/handled schedule bank
        self._thread = None
        self._stop_ = False
        self._wake = threading.Event()
        self._m_poisoned = _registry.counter(
            "serve.freshness.poisoned_rejected")

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop_ = False
        self._thread = threading.Thread(target=self._loop,
                                        name="freshness-watcher")
        self._thread.start()
        return self

    def stop(self):
        self._stop_ = True
        thread, self._thread = self._thread, None
        self._wake.set()
        if thread is not None:
            thread.join(timeout=30)

    def notify(self, path=None):
        """Push-mode hand-off (``POST /publish``): wake the poll loop
        now instead of waiting out the interval.  ``path`` is advisory
        — the loop still reads LATEST and verifies; a push can never
        bypass the manifest check."""
        if path is not None:
            self.debug("publish push for %s", path)
        self._wake.set()

    def _loop(self):
        while not self._stop_:
            self._wake.wait(timeout=self.poll_s)
            self._wake.clear()
            if self._stop_:
                break
            try:
                self.poll_once()
            except Exception:
                self.exception("freshness watcher poll failed")

    # -- the verify-before-unpickle pickup ----------------------------------

    def poll_once(self):
        """One pickup attempt; returns the accepted
        :class:`ModelCandidate` or None.  Public so push handlers and
        tests can drive the watcher synchronously."""
        self._maybe_merge_bank()
        latest = read_latest(self.watch_dir)
        if latest is None:
            return None
        try:
            ordinal = int(latest.get("ordinal", 0))
        except (TypeError, ValueError):
            return None
        if ordinal <= self.last_ordinal or ordinal in self._rejected:
            return None
        now = time.monotonic()
        pend = self._pending
        if pend is not None and pend["ordinal"] == ordinal and \
                now < pend["next_try"]:
            return None  # inside the backoff window: not even a stat
        path = os.path.join(self.watch_dir, str(latest["snapshot"]))
        ok, detail = SnapshotterBase.verify_snapshot(path)
        cand = None
        if ok is True:
            try:
                cand = self._load(ordinal, path, latest)
            except Exception as exc:
                ok, detail = False, "load failed: %s: %s" % (
                    type(exc).__name__, exc)
        else:
            detail = "manifest: %s" % (detail,)
        if cand is None:
            self._note_invalid(ordinal, path, detail)
            return None
        self.info("publish #%d verified: %s", ordinal, path)
        if self.callback is not None:
            try:
                self.callback(cand)
            except Exception as exc:
                # a TRANSIENT cycle failure (e.g. the candidate warm-up
                # hit RESOURCE_EXHAUSTED) must not consume the ordinal:
                # leave last_ordinal alone and retry with backoff.  The
                # publish itself VERIFIED — escalate=False keeps the
                # TTL from branding a healthy model "poisoned"; it
                # simply keeps retrying at the max backoff until the
                # failure clears or a newer publish supersedes it
                self.exception("freshness cycle for publish #%d failed",
                               ordinal)
                self._note_invalid(ordinal, path,
                                   "cycle failed: %s: %s" %
                                   (type(exc).__name__, exc),
                                   escalate=False)
                return None
        self._pending = None
        self.last_ordinal = ordinal
        return cand

    def _load(self, ordinal, path, latest):
        # verify_snapshot passed above; import_file re-checks the
        # manifest BEFORE unpickling and never cascades to siblings —
        # this publish stands or falls alone
        restored = SnapshotterBase.import_file(path, fallback=False)
        if isinstance(restored, dict) and SPEC_KEYS <= set(restored):
            plans = list(restored["plans"])
            params = [dict(p) for p in restored["params"]]
            shape = tuple(restored["sample_shape"])
        else:
            from veles_tpu.serve.router import ReplicaPool
            try:
                plans, params, shape = ReplicaPool._workflow_spec(
                    restored)
            except ValueError:
                if self.default_sample_shape is None:
                    raise
                plans, params, shape = ReplicaPool._workflow_spec(
                    restored, self.default_sample_shape)
        return ModelCandidate(ordinal, path, latest.get("sha256"),
                              plans, params, shape)

    def _maybe_merge_bank(self):
        """Merge the trainer-published fleet schedule bank
        (``schedule_bank.json`` beside the snapshots) into the local
        schedule cache whenever its bytes change — one host's tuning
        pays for every serve replica.  Verified against its manifest
        BEFORE parsing, same as snapshots; a mid-replace mismatch is
        silently retried next poll.  Returns the merge counts dict or
        None."""
        bank_path = os.path.join(self.watch_dir, _BANK_FILE_NAME)
        try:
            stat = os.stat(bank_path)
        except OSError:
            return None
        stamp = (stat.st_mtime_ns, stat.st_size)
        if stamp == self._bank_stamp:
            return None
        ok, detail = SnapshotterBase.verify_snapshot(bank_path)
        if ok is not True:
            # publisher mid-replace (manifest flipped, bank not yet) —
            # normal; leave the stamp unset so the next poll retries
            self.debug("schedule bank not (yet) valid (%s); retrying",
                       detail)
            return None
        from veles_tpu.tune.cache import cache_for
        try:
            counts = cache_for().merge_bank(bank_path)
        except Exception as exc:
            # consume the stamp: a structurally broken bank must not
            # warn-spam every poll; the next publish supersedes it
            self._bank_stamp = stamp
            self.warning(
                "schedule bank merge from %s failed (%s: %s); serving "
                "continues on current schedules", bank_path,
                type(exc).__name__, exc)
            return None
        self._bank_stamp = stamp
        self.info(
            "schedule bank merged from %s: %d adopted, %d kept, "
            "%d stale, %d invalid of %d", bank_path,
            counts["adopted"], counts["kept"], counts["stale"],
            counts["invalid"], counts["total"])
        return counts

    def _note_invalid(self, ordinal, path, detail, escalate=True):
        """Record a failed pickup and arm the retry backoff.
        ``escalate=False`` marks a failure that happened AFTER the
        publish verified (a transient controller/cycle failure): it
        retries forever at the max backoff instead of TTL-escalating —
        a healthy model must never be branded poisoned because the
        serve side had a bad minute."""
        now = time.monotonic()
        pend = self._pending
        if pend is None or pend["ordinal"] != ordinal:
            pend = self._pending = {
                "ordinal": ordinal, "first_bad": now,
                "backoff": self.poll_s, "next_try": now + self.poll_s,
                "escalate": escalate}
            # debug, not warning: a publisher mid-copy is NORMAL; the
            # escalation below owns the loud path
            self.debug("publish #%d not (yet) valid (%s); retrying "
                       "with backoff", ordinal, detail)
            return
        pend["escalate"] = pend.get("escalate", True) and escalate
        pend["backoff"] = min(pend["backoff"] * 2, self.max_backoff_s)
        pend["next_try"] = now + pend["backoff"]
        if now - pend["first_bad"] >= self.invalid_ttl_s and \
                not pend["escalate"]:
            if not pend.get("warned"):
                pend["warned"] = True
                self.warning(
                    "publish #%d verified but its freshness cycle "
                    "keeps failing (%.1fs so far: %s); retrying every "
                    "%.1fs until it clears or a newer publish lands",
                    ordinal, now - pend["first_bad"], detail,
                    pend["backoff"])
            return
        if now - pend["first_bad"] >= self.invalid_ttl_s:
            self.warning(
                "publish #%d at %s stayed invalid for %.1fs (%s): "
                "rejecting as poisoned; a newer publish supersedes it",
                ordinal, path, now - pend["first_bad"], detail)
            self._m_poisoned.inc()
            _tracer.instant("serve.canary", cat="serve",
                            phase="poisoned", ordinal=ordinal,
                            reason=str(detail))
            _flight.dump(reason="freshness-poisoned")
            self._rejected.add(ordinal)
            self._pending = None


class CanaryComparator(object):
    """Judge a canary on mirrored (primary, shadow) result pairs.

    Three tripwires, strictest first:

    - **non-finite canary output** — instant rollback verdict (the
      NaN-params snapshot the soak injects dies here if it somehow
      passed the finite gate);
    - **output divergence** — ``max|primary - shadow|`` above
      ``divergence_limit`` counts a breach (outputs legitimately
      differ between model versions; a *bound* catches "this model
      answers a different question", e.g. weights scaled 50x);
    - **latency** — the live fleet's per-request latencies prime an
      EMA baseline (:meth:`EmaSpikeWatch.observe`) and each shadow
      latency is spike-checked against it — the PR 3 watchdog
      discipline pointed at canary-vs-baseline tails.

    ``breach_budget`` breaches -> ``rolled_back``; ``min_mirrors``
    clean pairs -> ``promote``.  One-shot: the verdict latches."""

    def __init__(self, min_mirrors=8, divergence_limit=0.5,
                 latency_spike_factor=10.0, latency_floor_s=0.05,
                 beta=0.5, breach_budget=3):
        self.min_mirrors = int(min_mirrors)
        self.divergence_limit = float(divergence_limit)
        self.breach_budget = int(breach_budget)
        self._lat_watch = EmaSpikeWatch(
            spike_factor=latency_spike_factor,
            spike_floor=latency_floor_s, beta=beta,
            label="canary latency")
        self.pairs = 0
        self.breaches = 0
        self.max_divergence = 0.0
        self.reasons = []
        self.verdict = None

    def add(self, primary_out, shadow_out, primary_latency=None,
            shadow_latency=None):
        """Feed one mirrored pair; returns the latched verdict
        (``"promote"`` / ``"rolled_back"``) or None while undecided."""
        if self.verdict is not None:
            return self.verdict
        if not all_finite(primary_out):
            # a sick BASELINE row is no evidence about the candidate —
            # and NaN would poison the divergence math into silent
            # no-ops (NaN > limit is False forever)
            return None
        self.pairs += 1
        if not all_finite(shadow_out):
            self.reasons.append("non-finite canary output")
            self.verdict = "rolled_back"
            return self.verdict
        div = float(numpy.max(numpy.abs(
            numpy.asarray(primary_out, numpy.float64) -
            numpy.asarray(shadow_out, numpy.float64))))
        self.max_divergence = max(self.max_divergence, div)
        if div > self.divergence_limit:
            self.breaches += 1
            self.reasons.append(
                "output divergence %.4g > %.4g" %
                (div, self.divergence_limit))
        if primary_latency is not None:
            self._lat_watch.observe(primary_latency)
        if shadow_latency is not None:
            spike = self._lat_watch.update(shadow_latency)
            if spike is not None:
                self.breaches += 1
                self.reasons.append(spike)
        if self.breaches >= self.breach_budget:
            self.verdict = "rolled_back"
        elif self.pairs >= self.min_mirrors and self.breaches == 0:
            self.verdict = "promote"
        return self.verdict

    def reason(self):
        return "; ".join(self.reasons[-self.breach_budget:]) \
            or "unspecified"


class FreshnessController(Logger):
    """The loop: watcher pickup -> finite gate -> background AOT warm
    -> canary -> mirrored verdict -> promote or auto-rollback.

    Runs entirely on the watcher thread (one cycle at a time — a
    publish that lands mid-cycle is simply picked up next, newest
    wins).  The controller owns policy; the fleet mechanics live in
    :class:`veles_tpu.serve.router.CanaryCutover`."""

    def __init__(self, pool, watch_dir, poll_s=0.25,
                 invalid_ttl_s=10.0, mirror_fraction=0.25,
                 min_mirrors=8, divergence_limit=0.5,
                 latency_spike_factor=10.0, latency_floor_s=0.05,
                 breach_budget=3, verdict_timeout_s=30.0,
                 probe_idle_s=0.25, finite_gate=True, canary=True,
                 seed=0, **kwargs):
        super(FreshnessController, self).__init__(**kwargs)
        self.pool = pool
        self.mirror_fraction = float(mirror_fraction)
        self.verdict_timeout_s = float(verdict_timeout_s)
        self.probe_idle_s = float(probe_idle_s)
        self.finite_gate = bool(finite_gate)
        self.canary = bool(canary)
        self._comparator_kwargs = dict(
            min_mirrors=min_mirrors, divergence_limit=divergence_limit,
            latency_spike_factor=latency_spike_factor,
            latency_floor_s=latency_floor_s,
            breach_budget=breach_budget)
        self._rng = random.Random(seed)
        self._pairs = collections.deque()
        self._last_good_value = None
        self.history = []
        self.watcher = SnapshotWatcher(
            watch_dir, callback=self._on_candidate, poll_s=poll_s,
            invalid_ttl_s=invalid_ttl_s,
            default_sample_shape=pool.engine.sample_shape)
        self._m_candidates = _registry.counter(
            "serve.freshness.candidates")
        self._m_poisoned = _registry.counter(
            "serve.freshness.poisoned_rejected")

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        from veles_tpu.serve.engine import value_digest
        if self._last_good_value is None:
            self._last_good_value = value_digest(self.pool.engine.params)
        self.watcher.start()
        return self

    def stop(self):
        self.watcher.stop()
        self.pool.mirror_hook = None

    def notify(self, path=None):
        self.watcher.notify(path)

    # -- one cycle (watcher thread) -----------------------------------------

    def _record(self, cand, verdict, receipt=None, reason=None,
                comparator=None):
        entry = {
            "ordinal": cand.ordinal, "verdict": verdict,
            "snapshot": cand.path,
        }
        if reason:
            entry["reason"] = reason
        if receipt is not None:
            entry["digest"] = receipt.get("digest")
            entry["new_compiles"] = receipt.get("new_compiles")
        if comparator is not None:
            entry["mirrors"] = comparator.pairs
            entry["max_divergence"] = round(
                comparator.max_divergence, 6)
        self.history.append(entry)
        return entry

    def _on_candidate(self, cand):
        from veles_tpu.serve.engine import AOTEngine, value_digest
        pool = self.pool
        self._m_candidates.inc()
        if pool.cutover.state != "idle":
            # cannot happen from the single watcher thread, but a
            # manually driven cutover must not be trampled — and the
            # ordinal must NOT be consumed: raising routes this
            # through the watcher's non-escalating retry, so the
            # publish is picked up once the cutover settles (a
            # silently skipped FINAL publish would never be served)
            raise RuntimeError(
                "cutover busy (%s); candidate #%d will be retried" %
                (pool.cutover.state, cand.ordinal))
        if self.finite_gate and not all_finite(cand.params):
            # first line of defense: NaN/Inf params never even warm —
            # the canary exists for the failures a static check CANNOT
            # see, not the ones it can
            self._m_poisoned.inc()
            _tracer.instant("serve.canary", cat="serve",
                            phase="poisoned", ordinal=cand.ordinal,
                            reason="non-finite params")
            _flight.dump(reason="freshness-poisoned")
            self.warning("candidate #%d REJECTED: non-finite params "
                         "(never warmed, never served)", cand.ordinal)
            self._record(cand, "poisoned", reason="non-finite params")
            return
        value = value_digest(cand.params)
        if value == self._last_good_value:
            self._record(cand, "skipped", reason="already serving")
            return
        live = pool._live()
        shape_changed = tuple(cand.sample_shape) != \
            tuple(pool.engine.sample_shape)
        if not self.canary or len(live) < 2 or shape_changed:
            # verified direct reload — still manifest- and
            # finite-gated, just without the mirrored judgment — for a
            # single-replica fleet, --no-canary, or a candidate whose
            # INPUT shape changed: live traffic cannot drive such a
            # canary at all (every mirrored sample would be refused),
            # so pretending to judge it would only warn-spam for the
            # whole verdict window and roll back a possibly-good model
            if shape_changed:
                self.warning(
                    "candidate #%d changes the sample shape %s -> %s: "
                    "canary judgment impossible on live traffic, "
                    "cutting over via verified direct reload",
                    cand.ordinal, pool.engine.sample_shape,
                    cand.sample_shape)
            receipt = pool.reload(cand.params, plans=cand.plans,
                                  sample_shape=cand.sample_shape)
            self._last_good_value = value
            self._record(cand, "reloaded", receipt=receipt,
                         reason="sample shape changed"
                         if shape_changed else None)
            return
        start = time.perf_counter()
        target = live[-1]  # CanaryCutover.begin's pick
        with _tracer.span("serve.canary.warm", cat="serve",
                          ordinal=cand.ordinal):
            engine = AOTEngine(cand.plans, cand.params,
                               cand.sample_shape, device=target.device,
                               **pool._engine_kwargs)
            engine.compile()
        pool.cutover.begin(engine)
        comparator = CanaryComparator(**self._comparator_kwargs)
        self._pairs.clear()
        pool.mirror_hook = self._mirror
        try:
            verdict = self._judge(comparator)
        except Exception:
            # an unexpected judging failure must not strand the fleet
            # in canary state: restore, then let the watcher's retry
            # discipline re-attempt the publish
            pool.cutover.rollback(reason="freshness cycle failed")
            raise
        finally:
            pool.mirror_hook = None
        if verdict == "promote":
            receipt = pool.cutover.promote()
            self._last_good_value = value
        else:
            receipt = pool.cutover.rollback(reason=comparator.reason())
        entry = self._record(cand, receipt["verdict"], receipt=receipt,
                             reason=comparator.reason()
                             if verdict != "promote" else None,
                             comparator=comparator)
        entry["seconds"] = round(time.perf_counter() - start, 4)

    def _mirror(self, sample, primary_req):
        """The router's per-submit hook while a canary is live: mirror
        a seeded slice of traffic.  The primary request is already
        queued and is NEVER touched — mirroring cannot change, delay,
        or fail what the client receives."""
        if self._rng.random() >= self.mirror_fraction:
            return
        shadow = self.pool.cutover.shadow(
            numpy.array(sample, copy=True),
            trace=getattr(primary_req, "trace", None))
        if shadow is not None:
            self._pairs.append((primary_req, shadow))

    def _probe(self):
        """Synthesize one mirrored pair without client traffic: the
        SAME seeded sample shadow-submitted to a live replica (the
        baseline) and to the canary.  Both legs are shadow requests —
        excluded from served counters, invisible to clients — so an
        idle fleet can still judge a candidate on real evidence
        instead of timing out into a verdict nobody earned."""
        pool = self.pool
        live = pool._live()
        if not live:
            return None
        engine = pool.engine
        x = numpy.asarray(
            self._probe_rng.rand(*engine.sample_shape), engine.dtype)
        primary = live[0].batcher.submit_shadow(x)
        shadow = pool.cutover.shadow(numpy.array(x, copy=True))
        if primary is None or shadow is None:
            return None
        return primary, shadow

    def _judge(self, comparator):
        """Drain mirrored pairs into the comparator until it latches a
        verdict or the window times out.  When no client traffic
        mirrors for ``probe_idle_s``, the controller self-probes
        (:meth:`_probe`) — a quiet fleet must not wedge the pipeline
        OR promote/reject a candidate on zero evidence.  At timeout a
        clean window promotes, a window with breaches rolls back."""
        self._probe_rng = numpy.random.RandomState(
            self._rng.randrange(1 << 31))
        deadline = time.monotonic() + self.verdict_timeout_s
        idle_since = time.monotonic()
        while time.monotonic() < deadline:
            try:
                primary, shadow = self._pairs.popleft()
            except IndexError:
                if time.monotonic() - idle_since >= self.probe_idle_s:
                    idle_since = time.monotonic()
                    pair = self._probe()
                    if pair is not None:
                        self._pairs.append(pair)
                        continue
                time.sleep(0.01)
                continue
            idle_since = time.monotonic()
            if not (primary.done.wait(5.0) and shadow.done.wait(5.0)):
                continue  # a stalled pair is no evidence either way
            if primary.error is not None or shadow.error is not None:
                continue
            verdict = comparator.add(
                primary.result, shadow.result,
                primary_latency=primary.latency,
                shadow_latency=shadow.latency)
            if verdict is not None:
                return verdict
        if comparator.breaches == 0 and \
                comparator.pairs >= comparator.min_mirrors:
            # the comparator would have latched on the next add();
            # closing the window a hair early must not flip the verdict
            self.info("canary verdict window closed clean after %d "
                      "mirror(s): promoting", comparator.pairs)
            return "promote"
        # with self-probing, starving below min_mirrors means shadows
        # are being DROPPED (overloaded/wedged canary) — thin evidence
        # is itself evidence against the candidate; never promote past
        # the operator's min_mirrors bar on less
        comparator.reasons.append(
            "verdict timeout (%d/%d mirrors, %d breaches)" %
            (comparator.pairs, comparator.min_mirrors,
             comparator.breaches))
        return "rolled_back"

    # -- observability ------------------------------------------------------

    def snapshot(self):
        """Plain-data loop state for /healthz and the dashboard."""
        out = {
            "state": self.pool.cutover.state,
            "watch_dir": self.watcher.watch_dir,
            "last_ordinal": self.watcher.last_ordinal,
            "cycles": len(self.history),
            "last_good_value": self._last_good_value,
        }
        for name, short in (
                ("serve.freshness.published", "published"),
                ("serve.freshness.candidates", "candidates"),
                ("serve.freshness.promotions", "promotions"),
                ("serve.freshness.rollbacks", "rollbacks"),
                ("serve.freshness.poisoned_rejected",
                 "poisoned_rejected")):
            metric = _registry.peek(name)
            if metric is not None and metric.value is not None:
                out[short] = metric.value
        if self.history:
            out["last_cycle"] = self.history[-1]
        return out


class LocalHostControl(object):
    """Stage/revert control over ONE serve host's engines — the
    in-process handle the fleet canary controller drives.

    ``stage(params)`` swaps a same-architecture candidate into every
    engine behind the host's pool via
    :meth:`~veles_tpu.serve.engine.AOTEngine.swap_params` — the
    structural-digest-checked buffer swap, ZERO new backend compiles by
    construction, receipted via ``xla_introspect.compile_delta`` —
    saving the previous params once so ``revert()`` restores them
    exactly.  In a real fleet each host runs one of these next to its
    transport server; tests drive them directly over socketpair
    hosts."""

    def __init__(self, pool):
        self.pool = pool
        self._saved = None

    def _engines(self):
        replicas = getattr(self.pool, "replicas", None)
        if replicas is not None:
            return [rep.engine for rep in replicas]
        return [self.pool.engine]

    def stage(self, params):
        """Swap ``params`` into every engine; returns ``{"digest",
        "new_compiles"}``.  Raises ``ValueError`` (from swap_params)
        when the candidate is a different architecture — staging is
        swap-only, never a recompile."""
        from veles_tpu.observe import xla_introspect
        engines = self._engines()
        if self._saved is None:
            self._saved = [dict(p) for p in engines[0].params]
        with xla_introspect.compile_delta() as delta:
            digest = None
            for engine in engines:
                digest = engine.swap_params(params)
        receipt = dict(delta.receipt)
        receipt["digest"] = digest
        return receipt

    def revert(self):
        """Restore the params saved by the first :meth:`stage`;
        returns the swap receipt or None when nothing was staged."""
        if self._saved is None:
            return None
        saved, self._saved = self._saved, None
        return self.stage_params_quietly(saved)

    def stage_params_quietly(self, params):
        from veles_tpu.observe import xla_introspect
        with xla_introspect.compile_delta() as delta:
            digest = None
            for engine in self._engines():
                digest = engine.swap_params(params)
        self._saved = None
        receipt = dict(delta.receipt)
        receipt["digest"] = digest
        return receipt


class FleetCanaryController(Logger):
    """Fleet-level canary: judge a candidate on ONE host's live
    traffic slice, then promote host-by-host or roll the whole fleet
    back.

    The freshness loop's discipline lifted one tier up: where
    :class:`FreshnessController` canaries a candidate on one REPLICA
    of a single-host pool, this controller canaries it on one HOST of
    a :class:`~veles_tpu.serve.fleet.FleetRouter` fleet —

    - finite-gate the candidate (:func:`veles_tpu.health.all_finite`);
    - ``begin_canary_slice`` pulls the canary host from rotation and
      mirrors a seeded fraction of live single-sample traffic to it;
    - drain the host's previously-assigned inflight work, then
      ``stage`` the candidate via the host's
      :class:`LocalHostControl` — a zero-new-compile buffer swap;
    - judge real mirrored (primary, shadow) pairs through
      :class:`CanaryComparator` (output divergence bound + the
      :class:`~veles_tpu.health.EmaSpikeWatch` latency spike
      discipline + the non-finite tripwire);
    - **promote**: stage every sibling host in order (the rolling
      fleet-wide swap), then end the slice — the canary host returns
      to rotation already serving the candidate;
    - **rollback**: revert the canary host FIRST, then end the slice —
      a bad candidate never serves a primary request on ANY host.

    A timed-out or evidence-starved verdict rolls back: thin evidence
    is evidence against the candidate (the single-host loop's rule).
    Counters: ``serve.fleet.canary.{promotions,rollbacks}`` (mirrors
    are counted by the router as it sends them)."""

    def __init__(self, router, controls, mirror_fraction=0.25,
                 min_mirrors=8, divergence_limit=0.5,
                 latency_spike_factor=10.0, latency_floor_s=0.05,
                 breach_budget=3, verdict_timeout_s=30.0,
                 drain_timeout_s=10.0, finite_gate=True, seed=0,
                 **kwargs):
        super(FleetCanaryController, self).__init__(**kwargs)
        self.router = router
        #: ``{host_id: LocalHostControl-like}`` — stage/revert handles
        #: for every host in the fleet (duck-typed: anything with
        #: ``stage(params)`` / ``revert()``)
        self.controls = dict(controls)
        self.mirror_fraction = float(mirror_fraction)
        self.verdict_timeout_s = float(verdict_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.finite_gate = bool(finite_gate)
        self.seed = int(seed)
        self._comparator_kwargs = dict(
            min_mirrors=min_mirrors, divergence_limit=divergence_limit,
            latency_spike_factor=latency_spike_factor,
            latency_floor_s=latency_floor_s,
            breach_budget=breach_budget)
        self.history = []
        self._m_promotions = _registry.counter(
            "serve.fleet.canary.promotions")
        self._m_rollbacks = _registry.counter(
            "serve.fleet.canary.rollbacks")

    def run(self, params, canary_host):
        """One full fleet-canary cycle for ``params`` judged on
        ``canary_host``; returns the receipt dict (``verdict`` is
        ``"promote"`` / ``"rolled_back"`` / ``"poisoned"``)."""
        start = time.perf_counter()
        receipt = {"canary_host": canary_host, "new_compiles": 0}
        if self.finite_gate and not all_finite(params):
            self._m_rollbacks.inc()
            _tracer.instant("serve.canary", cat="serve",
                            phase="poisoned", host=canary_host,
                            reason="non-finite params")
            _flight.dump(reason="fleet-canary-poisoned")
            self.warning("fleet candidate REJECTED: non-finite params "
                         "(never staged, never mirrored)")
            receipt.update(verdict="poisoned",
                           reason="non-finite params")
            self.history.append(receipt)
            return receipt
        control = self.controls[canary_host]
        comparator = CanaryComparator(**self._comparator_kwargs)
        verdict_ready = threading.Event()

        def on_pair(primary_out, shadow_out, p_lat, s_lat):
            if comparator.add(primary_out, shadow_out,
                              primary_latency=p_lat,
                              shadow_latency=s_lat) is not None:
                verdict_ready.set()

        slice_ = self.router.begin_canary_slice(
            canary_host, fraction=self.mirror_fraction,
            seed=self.seed, on_pair=on_pair)
        _tracer.instant("serve.canary", cat="serve", phase="begin",
                        host=canary_host, tier="fleet")
        try:
            # drain: old-model inflight work must finish before the
            # swap so mirrored judging only ever sees candidate output
            deadline = time.monotonic() + self.drain_timeout_s
            while self.router.host_inflight(canary_host) and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            stage = control.stage(params)
            receipt["new_compiles"] += stage.get("new_compiles") or 0
            receipt["digest"] = stage.get("digest")
            # arm mirroring only now: every judged pair compares
            # CANDIDATE output against the live fleet, never a stale
            # pre-stage shadow
            slice_.armed = True
            verdict_ready.wait(self.verdict_timeout_s)
            verdict = comparator.verdict
            if verdict is None:
                if comparator.breaches == 0 and \
                        comparator.pairs >= comparator.min_mirrors:
                    verdict = "promote"
                else:
                    comparator.reasons.append(
                        "verdict timeout (%d/%d mirrors, %d breaches)"
                        % (comparator.pairs, comparator.min_mirrors,
                           comparator.breaches))
                    verdict = "rolled_back"
            if slice_.link_down:
                comparator.reasons.append(
                    "canary host link died mid-judgment")
                verdict = "rolled_back"
        except Exception:
            # an unexpected staging/judging failure must not strand
            # the fleet mid-canary: revert, restore routing, re-raise
            try:
                control.revert()
            finally:
                self.router.end_canary_slice()
            raise
        if verdict == "promote":
            # rolling fleet-wide promotion: siblings first (each a
            # zero-new-compile swap), the canary host re-enters
            # rotation LAST — already serving the candidate
            for host_id, sibling in self.controls.items():
                if host_id == canary_host:
                    continue
                rec = sibling.stage(params)
                receipt["new_compiles"] += rec.get("new_compiles") or 0
            self._m_promotions.inc()
        else:
            # rollback: revert the canary BEFORE it re-enters rotation
            # — the bad candidate never answers a primary request
            control.revert()
            self._m_rollbacks.inc()
            receipt["reason"] = comparator.reason()
        stats = self.router.end_canary_slice()
        _tracer.instant("serve.canary", cat="serve", phase=verdict,
                        host=canary_host, tier="fleet",
                        mirrors=comparator.pairs)
        receipt.update(
            verdict=verdict, mirrors=comparator.pairs,
            max_divergence=round(comparator.max_divergence, 6),
            slice=stats, seconds=round(time.perf_counter() - start, 4))
        self.history.append(receipt)
        self.info("fleet canary on %s: %s (%d mirrored pairs, %d new "
                  "compiles)", canary_host, verdict, comparator.pairs,
                  receipt["new_compiles"])
        return receipt
