"""Continuous-batching request queue over the AOT engine.

One worker thread drains pending requests into the largest fitting
ladder rung: the first request of a batch waits at most ``max_delay_s``
for company (the latency/throughput knob), the tail is zero-padded up
to the rung, and the batch runs on **ping-pong host staging buffers**
(the PR 1 ``memory.Array.stage_init/stage_begin/stage_put`` machinery)
so the next batch's host fill overlaps the current batch's transfer.
``stage_put`` goes through ``Device.put``, which on XLA:CPU makes the
XLA-owned copy that the zero-copy ``device_put`` hazard demands (see
``CPUDevice.put``) — the staged host buffer is never aliased by a live
executable input, donated or not.

Overload protocol (mirrors the distributed server's TTL-blacklist
rejects, docs/distributed.md): past ``max_queue`` pending requests,
:meth:`ContinuousBatcher.submit` raises :class:`ServeOverload` carrying
a ``retry_after`` estimate instead of growing the queue without bound;
the HTTP front turns it into ``503 {"retry_after": ...}`` and a
well-behaved client sleeps it out, exactly like a blacklisted slave.

Degradation: an OOM-shaped engine failure (`RESOURCE_EXHAUSTED` /
``MemoryError``) permanently caps the ladder below the failing rung and
replays the batch in capped chunks — serving gets slower, not dead.
Other engine failures fail only that batch's requests and keep the
worker alive.

SLO watch: per-request end-to-end latency feeds the ``serve.latency_s``
histogram; every ``slo_check_every`` batches the recent window's
p50/p99 are compared against the configured thresholds and each breach
bumps ``serve.slo_violations`` + records a trace/flight-recorder
instant, so a post-mortem dump shows *when* the tail blew up, next to
the batch spans that did it.

Multi-tenant QoS (docs/serving.md "Multi-tenant QoS"): every request
carries an SLO class (``interactive`` / ``batch`` / ``best_effort``;
un-labelled traffic defaults to ``batch``) and the queue bound is
class-aware — a full queue evicts a pending request of STRICTLY lower
class (shed attributed to the victim's class, with a seeded per-class
jittered ``retry_after``) before it sheds the incoming one, so
interactive work starves last and is shed only when the queue is
saturated with interactive work itself.

Chaos points (docs/health.md table): ``serve.drop`` (submit-side shed),
``serve.stall`` (worker sleeps ``param`` seconds — trips the SLO
watch), ``serve.device.stall`` (sleeps at the DEVICE-dispatch edge so
request timelines attribute the stall to the device segment — the
tail-attribution chaos hook), ``serve.oom`` (simulated
RESOURCE_EXHAUSTED — exercises the degrade path),
``serve.tenant.flood`` (``param`` synthetic best-effort requests storm
the queue as real load — exercises class-ordered shedding).

Request tracing (docs/observability.md "Request tracing"): while
``VELES_REQTRACE`` is on, the worker stamps each request's segment
timeline (queue / assemble / h2d / device / d2h) on the request object
before ``done.set()``, feeds the tail-exemplar ring, and emits
request-track spans for sampled ids; an SLO-breach ENTER edge dumps
the exemplar ring with the flight recorder.
"""

import collections
import queue
import threading
import time

import numpy

from veles_tpu import chaos
from veles_tpu.logger import Logger
from veles_tpu.memory import Array
from veles_tpu.observe import requests as reqtrace
from veles_tpu.observe.metrics import percentiles
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.serve import qos

__all__ = ["ContinuousBatcher", "ServeOverload", "serve_snapshot"]


class ServeOverload(Exception):
    """Load shed: the queue is full (or chaos dropped the request).
    ``retry_after`` (seconds) marks the rejection transient — the HTTP
    layer ships it as 503 + retry_after, like the server blacklist."""

    def __init__(self, message, retry_after=0.1):
        super(ServeOverload, self).__init__(message)
        self.retry_after = float(retry_after)


class _Request(object):
    __slots__ = ("sample", "enqueued", "done", "result", "error",
                 "cancelled", "block", "shadow", "latency", "slo_class",
                 "claimed", "trace", "marks", "child")

    def __init__(self, sample, block=False, shadow=False,
                 slo_class=None, trace=None):
        self.sample = sample
        #: canonical SLO class ("interactive" / "batch" /
        #: "best_effort") — decides shed order under overload and which
        #: serve.tenant.<class>.* series the request lands in
        self.slo_class = qos.normalize_class(slo_class)
        #: request trace id (observe/requests.py id contract) — rides
        #: the request through requeue/hedge/chunked replay unchanged
        self.trace = trace
        #: segment timeline [(segment, start_perf, dur_s)] stamped by
        #: the worker at completion, BEFORE done.set() so a transport
        #: waiter can echo it over the wire; None while VELES_REQTRACE
        #: is off (the zero-overhead kill switch)
        self.marks = None
        #: OOM-replay slice of a block request: its marks fold into the
        #: parent's timeline instead of emitting their own spans /
        #: exemplars (the parent is the request the client knows)
        self.child = False
        self.enqueued = time.perf_counter()
        self.done = threading.Event()
        self.result = None
        self.error = None
        #: set by a caller that gave up on the request (e.g. a batch
        #: payload that shed partway through submission); the worker
        #: drops it at dispatch instead of computing for nobody
        self.cancelled = False
        #: True when ``sample`` is a whole contiguous batch submitted
        #: via :meth:`ContinuousBatcher.submit_block` — the worker can
        #: hand its buffer to ``Device.put`` verbatim when it fills a
        #: rung exactly (the binary transport's zero-copy hot path)
        self.block = block
        #: canary-mirror shadow copy (docs/serving.md "Freshness
        #: loop"): computed and scored like any request but NEVER
        #: counted in the served metrics (``serve.requests`` /
        #: ``serve.latency_s``) — shadow traffic must not double-count
        #: in capacity math or skew the SLO watch
        self.shadow = shadow
        #: end-to-end seconds, stamped by the worker at completion —
        #: the canary comparator reads it off shadow/primary pairs
        #: instead of re-timing around the Event wait
        self.latency = None
        #: set by the worker when it dequeues the request: class-
        #: ordered eviction must only cancel work still WAITING — a
        #: claimed request is already being served, so evicting it
        #: would not free queue capacity
        self.claimed = False

    @property
    def rows(self):
        return self.sample.shape[0] if self.block else 1


def _oom_shaped(exc):
    return isinstance(exc, MemoryError) or \
        "RESOURCE_EXHAUSTED" in str(exc) or \
        "Out of memory" in str(exc)


class ContinuousBatcher(Logger):
    """Worker thread turning a request stream into padded-rung batches.

    ``max_delay_s`` bounds how long the OLDEST request of a forming
    batch waits for more arrivals; ``max_queue`` bounds pending
    requests before :meth:`submit` sheds; ``slo_p50_ms``/``slo_p99_ms``
    arm the SLO watch (None disables a threshold)."""

    def __init__(self, engine, max_delay_s=0.002, max_queue=256,
                 slo_p50_ms=None, slo_p99_ms=None, slo_check_every=4,
                 replica=None, retry_jitter=None, **kwargs):
        super(ContinuousBatcher, self).__init__(**kwargs)
        self.engine = engine
        self.max_delay_s = float(max_delay_s)
        self.max_queue = int(max_queue)
        #: seeded per-class retry_after jitter (satellite of the QoS
        #: layer): synchronized clients shed together must not
        #: re-stampede together
        self.retry_jitter = retry_jitter if retry_jitter is not None \
            else qos.RetryJitter()
        #: pending requests a HIGHER class may evict when the queue is
        #: full — interactive has no deque: it is never evicted, only
        #: shed at its own admission when the queue is saturated with
        #: interactive work itself (qos.SHED_ORDER contract)
        self._evictable = {cls: collections.deque()
                           for cls in qos.SHED_ORDER
                           if cls != "interactive"}
        self.slo_p50_ms = slo_p50_ms
        self.slo_p99_ms = slo_p99_ms
        self.slo_check_every = max(1, int(slo_check_every))
        #: replica index inside a ReplicaPool; scopes the GAUGES (each
        #: replica's queue depth / rung cap is its own signal) while
        #: counters and histograms stay process-shared so fleet totals
        #: and latency percentiles aggregate by construction
        self.replica = replica
        #: fleet host identity (set by BinaryTransportServer via
        #: ``set_host_tag`` when host_meta names one): rides request-
        #: span args so two in-process hosts' legs stay attributable
        #: in a shared tracer, and a merged cross-host timeline can
        #: name the slow leg
        self.host_tag = None
        self._q = queue.Queue()
        self._thread = None
        self._stop_ = False
        self._rung_cap = engine.max_batch
        self._stage = {}      # rung -> (Array, [slot])
        self._carry = None    # popped request that overflowed a batch
        self._pending_engine = None
        self._batches_since_check = 0
        self._slo_breached = False
        # metrics resolved once (docs/observability.md serve set)
        scope = "serve" if replica is None else \
            "serve.replica.%d" % replica
        self._m_depth = _registry.gauge(scope + ".queue_depth")
        self._g_rung_cap = _registry.gauge(scope + ".rung_cap")
        self._m_batch = _registry.histogram("serve.batch_size")
        self._m_latency = _registry.histogram("serve.latency_s")
        self._m_requests = _registry.counter("serve.requests")
        self._m_batches = _registry.counter("serve.batches")
        self._m_padded = _registry.counter("serve.padded_rows")
        self._m_shed = _registry.counter("serve.shed")
        self._m_errors = _registry.counter("serve.errors")
        self._m_slo = _registry.counter("serve.slo_violations")
        # per-segment latency histograms (observe/requests.py segment
        # taxonomy); queue is per-request, the rest per-batch — fed
        # only while request tracing is enabled
        self._h_seg = {
            name: _registry.histogram("serve.segment.%s_s" % name)
            for name in ("queue", "assemble", "h2d", "device", "d2h")}
        self._m_depth.set(0)

    def set_host_tag(self, tag):
        """Name the fleet host this batcher serves (transport hello
        host_meta); request spans carry it as the leg attribution."""
        self.host_tag = tag

    # -- lifecycle ----------------------------------------------------------

    @property
    def running(self):
        return self._thread is not None

    def start(self):
        if self._thread is not None:
            return self
        self._stop_ = False
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher")
        self._thread.start()
        return self

    def stop(self):
        """Stop the worker and JOIN it (the test suite's thread-leak
        fixture enforces this); pending requests fail with overload so
        no caller blocks forever on a dead queue."""
        self._stop_ = True
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
        carry, self._carry = self._carry, None
        while True:
            if carry is not None:
                req, carry = carry, None
            else:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    break
            if not req.done.is_set():
                req.error = ServeOverload("server shutting down",
                                          retry_after=1.0)
                req.done.set()
        self._m_depth.set(0)

    # -- hot reload ---------------------------------------------------------

    def swap_engine(self, engine):
        """Queue an atomic engine cutover (snapshot hot-reload with a
        NEW digest): the worker applies it BETWEEN batches, so no batch
        is ever torn across engines and no queued request is dropped —
        requests keep queueing during the background compile and are
        served by whichever engine owns the batch they land in.

        Same-digest reloads never come here: ``AOTEngine.swap_params``
        swaps device buffers in place with zero recompiles."""
        if engine.compile_receipt is None:
            raise RuntimeError(
                "swap_engine needs a COMPILED engine (warm the ladder "
                "before cutover — compiling on the serving path is the "
                "failure mode the AOT design exists to avoid)")
        if self._thread is None:
            self._apply_engine(engine)  # stopped: no batch to tear
        else:
            self._pending_engine = engine

    def _apply_engine(self, engine):
        """Worker-side half of :meth:`swap_engine` (between batches)."""
        self._pending_engine = None
        old = self.engine
        self.engine = engine
        # staging buffers are shaped by the OLD engine's sample shape/
        # dtype; drop them (rebuilt lazily) and lift any OOM cap — the
        # new model's memory behavior is its own
        self._stage.clear()
        self._rung_cap = engine.max_batch
        self._g_rung_cap.set(engine.max_batch)
        if _tracer.active:
            _tracer.instant(
                "serve.reload.cutover", cat="serve",
                replica=self.replica if self.replica is not None else 0,
                old_digest=old.digest, new_digest=engine.digest)
        self.info("engine cutover: %s -> %s", old.digest, engine.digest)

    # -- submit side --------------------------------------------------------

    def _retry_after(self):
        """Transient-backoff estimate: the queue drained at the recent
        per-batch pace, bounded to something a client will tolerate."""
        window = self._m_latency.window_values()
        p50 = percentiles(window, ps=(50,)).get("p50") if window else None
        per_batch = p50 if p50 else 0.05
        depth = self._q.qsize()
        return min(5.0, max(0.05, per_batch * (
            1 + depth / float(self.engine.max_batch))))

    def _shed(self, slo_class, message):
        """Account one shed against ``slo_class`` and raise the
        overload with the class-jittered ``retry_after``."""
        self._m_shed.inc()
        qos.note_shed(slo_class)
        retry = self.retry_jitter.apply(self._retry_after(), slo_class)
        if _tracer.active:
            _tracer.instant("serve.shed", cat="serve",
                            depth=self._q.qsize(), slo_class=slo_class,
                            retry_after=round(retry, 4))
        raise ServeOverload(message, retry_after=retry)

    def _evict_lower(self, incoming_cls):
        """Cancel one pending request of STRICTLY lower class than
        ``incoming_cls`` to make room; the shed is attributed to the
        VICTIM's class.  Returns False when no lower-class work is
        pending — the incoming request must be shed instead (so a
        queue saturated with interactive work sheds interactive, and
        nothing below interactive ever evicts it)."""
        incoming_rank = qos.class_rank(incoming_cls)
        for victim_cls in qos.SHED_ORDER:
            if qos.class_rank(victim_cls) >= incoming_rank:
                return False
            dq = self._evictable[victim_cls]
            while True:
                try:
                    victim = dq.popleft()
                except IndexError:
                    break
                if victim.cancelled or victim.claimed or \
                        victim.done.is_set():
                    continue  # served, being served, or evicted
                victim.cancelled = True
                victim.error = ServeOverload(
                    "shed for %s admission (class-ordered eviction)"
                    % incoming_cls,
                    retry_after=self.retry_jitter.apply(
                        self._retry_after(), victim_cls))
                self._m_shed.inc()
                qos.note_shed(victim_cls)
                if _tracer.active:
                    _tracer.instant("serve.shed", cat="serve",
                                    depth=self._q.qsize(),
                                    slo_class=victim_cls,
                                    evicted_for=incoming_cls)
                victim.done.set()
                return True
        return False

    def _flood(self, count):
        """Chaos ``serve.tenant.flood``: enqueue ``count`` synthetic
        zero-sample best_effort requests as REAL load (no waiter) —
        the storm contends for queue capacity like any bulk tenant
        would, and rows past the bound are shed like any best_effort."""
        zero = numpy.zeros(self.engine.sample_shape, self.engine.dtype)
        for _ in range(count):
            if self._q.qsize() >= self.max_queue:
                self._m_shed.inc()
                qos.note_shed("best_effort")
                continue
            try:
                self._enqueue(_Request(zero, slo_class="best_effort"))
            except ServeOverload:
                break  # racing a stop(): the storm dies with the queue

    def _admit(self, slo_class=qos.DEFAULT_CLASS):
        """Shared admission control: running check, chaos shed, class-
        aware queue bound.  Raises :class:`ServeOverload` when the
        request must be shed."""
        if self._thread is None or self._stop_:
            raise ServeOverload("batcher not running", retry_after=1.0)
        if chaos.plan is not None:
            fault = chaos.plan.fire("serve.tenant.flood")
            if fault is not None:
                self._flood(int(fault.param) if fault.param else 32)
            fault = chaos.plan.fire("serve.drop")
            if fault is not None:
                self._m_shed.inc()
                qos.note_shed(slo_class)
                raise ServeOverload(
                    "chaos: request dropped",
                    retry_after=self.retry_jitter.apply(
                        self._retry_after(), slo_class))
        if self._q.qsize() >= self.max_queue and \
                not self._evict_lower(slo_class):
            self._shed(slo_class,
                       "queue full (%d pending)" % self._q.qsize())

    def _enqueue(self, req):
        self._q.put(req)
        if not req.shadow and req.slo_class in self._evictable:
            dq = self._evictable[req.slo_class]
            dq.append(req)
            if len(dq) > 2 * self.max_queue:
                # lazy compaction: drop served/evicted entries so the
                # deque tracks only live pending work
                live = [r for r in dq
                        if not r.cancelled and not r.done.is_set()]
                dq.clear()
                dq.extend(live)
        if self._stop_:
            # lost the race with a concurrent stop(): its drain may
            # have already run, so complete the request here — nobody
            # else will, and the caller must not block out its timeout
            req.error = ServeOverload("server shutting down",
                                      retry_after=1.0)
            req.done.set()
            raise req.error
        self._m_depth.set(self._q.qsize())
        return req

    def submit(self, sample, slo_class=None, trace=None):
        """Enqueue one sample; returns the pending request.  Raises
        :class:`ServeOverload` when shedding (full queue or chaos
        ``serve.drop``).  ``slo_class`` labels the request for the QoS
        layer (class-ordered shedding + per-class accounting);
        un-labelled callers default to ``batch``.  ``trace`` is the
        request trace id (observe/requests.py) the worker stamps its
        segment timeline against."""
        slo_class = qos.normalize_class(slo_class)
        self._admit(slo_class)
        sample = numpy.ascontiguousarray(sample, self.engine.dtype)
        if sample.shape != self.engine.sample_shape:
            raise ValueError("expected sample shape %s, got %s" %
                             (self.engine.sample_shape, sample.shape))
        return self._enqueue(_Request(sample, slo_class=slo_class,
                                      trace=trace))

    def submit_block(self, block, slo_class=None, trace=None):
        """Enqueue a whole batch as ONE request whose rows stay in
        their caller-provided buffer.

        For an already-contiguous same-dtype block — exactly what the
        binary transport decodes with ``numpy.frombuffer`` — the rows
        are NEVER copied into the ping-pong staging `memory.Array`:
        when the block fills a rung by itself the worker hands the
        buffer straight to ``Device.put`` (which on XLA:CPU makes the
        one XLA-owned copy the zero-copy ``device_put`` hazard demands
        — never raw ``jax.device_put``; see ``CPUDevice.put``), and
        when it co-batches, the fill is one vectorized slice-assign
        instead of a Python loop.  Non-conforming input falls back to
        one normalizing copy here, so callers need no special casing.
        """
        slo_class = qos.normalize_class(slo_class)
        self._admit(slo_class)
        block = numpy.asarray(block)
        if block.dtype != self.engine.dtype or \
                not block.flags["C_CONTIGUOUS"]:
            block = numpy.ascontiguousarray(block, self.engine.dtype)
        if block.ndim != len(self.engine.sample_shape) + 1 or \
                block.shape[1:] != self.engine.sample_shape:
            raise ValueError("expected a (n,) + %s block, got %s" %
                             (self.engine.sample_shape, block.shape))
        if not 1 <= block.shape[0] <= self.engine.max_batch:
            raise ValueError(
                "block of %d rows overflows the ladder (max %d); "
                "chunk at the caller" %
                (block.shape[0], self.engine.max_batch))
        return self._enqueue(_Request(block, block=True,
                                      slo_class=slo_class,
                                      trace=trace))

    def submit_shadow(self, sample, trace=None):
        """Best-effort enqueue of a canary-mirror shadow copy: never
        raises :class:`ServeOverload` — a loaded (or chaos-shedding)
        canary simply mirrors less — and returns None instead of a
        request when dropped.  Shadow requests co-batch like real ones
        but are excluded from the served counters (``serve.requests``,
        ``serve.latency_s``) and never bump the shed counter: mirrored
        traffic is an observation, not load.  A shadow KEEPS the
        primary's trace id (its spans are tagged ``shadow``) but is
        excluded from the tail-exemplar ring."""
        if self._thread is None or self._stop_ or \
                self._q.qsize() >= self.max_queue:
            return None
        sample = numpy.ascontiguousarray(sample, self.engine.dtype)
        if sample.shape != self.engine.sample_shape:
            raise ValueError("expected sample shape %s, got %s" %
                             (self.engine.sample_shape, sample.shape))
        try:
            return self._enqueue(_Request(sample, shadow=True,
                                          trace=trace))
        except ServeOverload:
            return None  # lost the race with stop(): drop the shadow

    def infer(self, sample, timeout=30.0):
        """Blocking submit: returns the output row (numpy) or raises
        the request's error."""
        req = self.submit(sample)
        if not req.done.wait(timeout):
            raise TimeoutError("inference timed out after %.1fs"
                               % timeout)
        if req.error is not None:
            raise req.error
        return req.result

    # -- worker side --------------------------------------------------------

    def _loop(self):
        while not self._stop_:
            pending = self._pending_engine
            if pending is not None:
                self._apply_engine(pending)
            first, self._carry = self._carry, None
            if first is None:
                try:
                    first = self._q.get(timeout=0.2)
                except queue.Empty:
                    continue
            first.claimed = True
            if first.cancelled:
                # evicted by a higher class while queued: drop the
                # corpse without charging it against the rung budget
                self._m_depth.set(self._q.qsize())
                continue
            batch = self._collect(first)
            self._m_depth.set(self._q.qsize())
            try:
                self._run_batch(batch)
            except Exception as exc:  # never kill the worker
                self._m_errors.inc()
                self.exception("serve batch failed")
                for req in batch:
                    if not req.done.is_set():
                        req.error = exc
                        req.done.set()

    def _collect(self, first):
        """Grow a batch around the oldest pending request: drain
        whatever is already queued instantly, then wait out the
        remaining queue-delay budget for stragglers.  Accounting is in
        ROWS (a block request carries several); a popped request that
        would overflow the rung limit becomes the head of the next
        batch via the carry slot."""
        batch = [first]
        rows = first.rows
        limit = min(self._rung_cap, self.engine.max_batch)
        deadline = first.enqueued + self.max_delay_s
        while rows < limit and not self._stop_:
            remaining = deadline - time.perf_counter()
            try:
                if remaining <= 0:
                    req = self._q.get_nowait()
                else:
                    req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            req.claimed = True
            if req.cancelled:
                continue  # evicted while queued: zero rows, skip
            if rows + req.rows > limit:
                self._carry = req
                break
            batch.append(req)
            rows += req.rows
        return batch

    def _staging(self, rung):
        arr_slot = self._stage.get(rung)
        if arr_slot is None:
            arr = Array(numpy.zeros(
                (rung,) + self.engine.sample_shape, self.engine.dtype))
            arr.stage_init(2)
            arr_slot = self._stage[rung] = [arr, 0]
        return arr_slot

    def _run_batch(self, batch):
        if chaos.plan is not None:
            fault = chaos.plan.fire("serve.stall")
            if fault is not None:
                # a stalled device/runtime: latency climbs, the SLO
                # watch must notice (tests/test_serve.py)
                time.sleep(fault.param if fault.param else 0.05)
        batch = [req for req in batch if not req.cancelled]
        if not batch:
            return
        n = sum(req.rows for req in batch)
        rung = self.engine.rung_for(n, cap=self._rung_cap)
        if n > rung:  # capped ladder (post-OOM degrade): chunk by rows
            self._run_chunked(batch, rung)
            return
        start = time.perf_counter()
        if len(batch) == 1 and batch[0].block and \
                batch[0].rows == rung:
            # zero-copy hot path: a contiguous block filling the rung
            # exactly skips the staging fill — Device.put gets the
            # caller's buffer (and on XLA:CPU makes the one hazard-safe
            # XLA-owned copy; see CPUDevice.put / submit_block)
            t_h2d = start  # no staging fill: the put IS the H2D edge
            x_dev = self.engine.device.put(batch[0].sample)
        else:
            arr, slot = self._staging(rung)
            arr.stage_begin(slot)
            self._stage[rung][1] = slot ^ 1
            mem = arr.mem
            off = 0
            for req in batch:
                if req.block:
                    mem[off:off + req.rows] = req.sample
                else:
                    mem[off] = req.sample
                off += req.rows
            if n < rung:
                # deterministic padding (bit-equality contract)
                mem[n:] = 0
                self._m_padded.inc(rung - n)
            t_h2d = time.perf_counter()
            x_dev = arr.stage_put(self.engine.device)
        t_dev = time.perf_counter()
        try:
            if chaos.plan is not None:
                fault = chaos.plan.fire("serve.device.stall")
                if fault is not None:
                    # a slow accelerator (thermal throttle, preempted
                    # chip): the stall lands INSIDE the device segment
                    # so request timelines attribute it correctly
                    time.sleep(fault.param if fault.param else 0.05)
                fault = chaos.plan.fire("serve.oom")
                if fault is not None:
                    raise MemoryError(
                        "RESOURCE_EXHAUSTED: chaos serve.oom (rung %d)"
                        % rung)
            out = self.engine.run(x_dev, rung)
            t_d2h = time.perf_counter()
            # the ONE host sync of the whole batch (the old RESTfulAPI
            # synced per request)
            host = numpy.asarray(out)
        except Exception as exc:
            self._degrade_or_fail(batch, rung, exc)
            return
        done = time.perf_counter()
        self._m_batches.inc()
        # served accounting EXCLUDES shadow (canary-mirror) rows: a
        # mirrored request must never double-count in capacity totals
        # or skew the SLO latency window (docs/serving.md)
        served = sum(req.rows for req in batch if not req.shadow)
        if served:
            self._m_requests.inc(served)
        self._m_batch.observe(n)
        stamps = reqtrace.enabled
        if stamps:
            # per-batch segment histograms (serve_snapshot "segments"
            # block); queue is per-request, observed in _note_request
            self._h_seg["assemble"].observe(t_h2d - start)
            self._h_seg["h2d"].observe(t_dev - t_h2d)
            self._h_seg["device"].observe(t_d2h - t_dev)
            self._h_seg["d2h"].observe(done - t_d2h)
        off = 0
        for req in batch:
            # hand out VIEWS of the one per-batch host block: the
            # per-request row copy (and its per-element boxing further
            # down the JSON front) is paid zero times — `host` is a
            # fresh buffer each batch, so nothing ever overwrites a
            # view a waiter still holds
            if req.block:
                req.result = host[off:off + req.rows]
            else:
                req.result = host[off]
            off += req.rows
            req.latency = done - req.enqueued
            if stamps:
                # marks must land BEFORE done.set(): a transport
                # waiter echoes them over the wire at wake-up
                self._note_request(req, start, t_h2d, t_dev, t_d2h,
                                   done, rung)
            if not req.shadow:
                self._m_latency.observe(req.latency)
                # per-class accounting (docs/serving.md "Multi-tenant
                # QoS") — shadow/mirror rows stay excluded here too
                qos.note_request(req.slo_class, req.rows)
                qos.note_latency(req.slo_class, req.latency)
            req.done.set()
        if _tracer.active:
            args = {"n": n, "rung": rung}
            if self.replica is not None:
                args["replica"] = self.replica
            _tracer.complete("serve.batch", start, done - start,
                             cat="serve", args=args)
        self._batches_since_check += 1
        if self._batches_since_check >= self.slo_check_every:
            self._batches_since_check = 0
            self._check_slo()

    def _note_request(self, req, start, t_h2d, t_dev, t_d2h, done,
                      rung):
        """Stamp one completed request's segment timeline (observe/
        requests.py taxonomy), feed the tail-exemplar ring, and emit
        request-track spans when the request is sampled."""
        queue_wait = start - req.enqueued
        marks = [("queue", req.enqueued, queue_wait),
                 ("assemble", start, t_h2d - start),
                 ("h2d", t_h2d, t_dev - t_h2d),
                 ("device", t_dev, t_d2h - t_dev),
                 ("d2h", t_d2h, done - t_d2h)]
        if req.marks:
            # a front (HTTP admit, transport wire_rx) stamped marks
            # before the queue segment began: keep them at the head
            marks = list(req.marks) + marks
        req.marks = marks
        if req.child:
            return  # the sliced parent reports for the whole request
        self._h_seg["queue"].observe(queue_wait)
        self._emit_request(req, done, rung=rung)

    def _emit_request(self, req, done, rung=None):
        reqtrace.exemplars.note(
            req.trace, req.latency, marks=req.marks or (),
            t0=req.enqueued, slo_class=req.slo_class,
            budget_s=qos.slo_budget_s(req.slo_class), kind="host",
            shadow=req.shadow)
        if req.trace and _tracer.active and reqtrace.sampled(req.trace):
            args = {"slo_class": req.slo_class, "tier": "host",
                    "rows": req.rows}
            if rung is not None:
                args["rung"] = rung
            if self.host_tag:
                args["host"] = self.host_tag
            if self.replica is not None:
                args["replica"] = self.replica
            if req.shadow:
                args["shadow"] = True
            reqtrace.emit_spans(_tracer, req.trace, req.enqueued,
                                done, req.marks or (), args=args)

    def _run_chunked(self, batch, rung):
        """Replay a too-large batch within a capped rung: requests are
        regrouped by rows; a block wider than the cap itself is sliced
        into view sub-requests (still contiguous — the zero-copy
        dispatch applies to full slices) and its result reassembled."""
        chunk, rows = [], 0
        for req in batch:
            if req.rows > rung:
                if chunk:
                    self._run_batch(chunk)
                    chunk, rows = [], 0
                self._run_block_sliced(req, rung)
                continue
            if rows + req.rows > rung:
                self._run_batch(chunk)
                chunk, rows = [], 0
            chunk.append(req)
            rows += req.rows
        if chunk:
            self._run_batch(chunk)

    def _run_block_sliced(self, req, cap):
        children = []
        for i in range(0, req.rows, cap):
            child = _Request(req.sample[i:i + cap], block=True,
                             shadow=req.shadow, slo_class=req.slo_class,
                             trace=req.trace)
            child.enqueued = req.enqueued
            child.child = True
            children.append(child)
        for child in children:
            self._run_batch([child])
        errors = [c.error for c in children if c.error is not None]
        if errors:
            req.error = errors[0]
        else:
            req.result = numpy.concatenate(
                [c.result for c in children])
        done = time.perf_counter()
        req.latency = done - req.enqueued
        if reqtrace.enabled and not errors:
            # the parent's timeline is the chunk sequence: keep only
            # the first chunk's queue mark (later "queues" would
            # overlap the earlier chunks' spans on the request track)
            marks = []
            for index, child in enumerate(children):
                for mark in (child.marks or ()):
                    if index and mark[0] == "queue":
                        continue
                    marks.append(mark)
            req.marks = marks
            self._emit_request(req, done)
        req.done.set()

    def _degrade_or_fail(self, batch, rung, exc):
        self._m_errors.inc()
        if _oom_shaped(exc) and rung > self.engine.ladder[0]:
            # permanent cap below the failing rung, replay in chunks:
            # slower beats dead, and the cap note reaches the logs +
            # health block (serve.rung_cap gauge)
            smaller = [r for r in self.engine.ladder if r < rung]
            self._rung_cap = smaller[-1]
            self._g_rung_cap.set(self._rung_cap)
            self.warning(
                "engine OOM at rung %d (%s); capping ladder at %d and "
                "replaying", rung, exc, self._rung_cap)
            if _tracer.active:
                _tracer.instant("serve.degrade", cat="serve",
                                rung=rung, cap=self._rung_cap)
            self._run_batch(batch)
            return
        self.error("engine failure at rung %d: %s", rung, exc)
        for req in batch:
            req.error = exc
            req.done.set()

    def _check_slo(self):
        if self.slo_p50_ms is None and self.slo_p99_ms is None:
            return
        window = self._m_latency.window_values()
        if not window:
            return
        ps = percentiles(window, ps=(50, 99))
        p50_ms = ps["p50"] * 1e3
        p99_ms = ps["p99"] * 1e3
        breaches = []
        if self.slo_p50_ms is not None and p50_ms > self.slo_p50_ms:
            breaches.append(("p50", p50_ms, self.slo_p50_ms))
        if self.slo_p99_ms is not None and p99_ms > self.slo_p99_ms:
            breaches.append(("p99", p99_ms, self.slo_p99_ms))
        for which, measured, budget in breaches:
            self._m_slo.inc()
            # instant -> trace AND the always-on flight ring, so a
            # post-mortem dump carries the breach next to its batches
            _tracer.instant(
                "serve.slo_violation", cat="serve", slo=which,
                measured_ms=round(measured, 3),
                budget_ms=round(budget, 3))
        if breaches and not self._slo_breached:
            # log on the ENTER edge only: the counter/instants carry
            # the per-check record, a sustained breach must not flood
            # the log at batch rate
            self.warning("SLO violation began: %s", "; ".join(
                "%s %.2fms > %.2fms budget" % b for b in breaches))
            if reqtrace.enabled:
                # the flight dump for this violation carries the tail
                # exemplars, so the breach always ships the offending
                # requests' full segment timelines (never raises)
                reqtrace.exemplars.dump("serve.slo_violation")
        elif self._slo_breached and not breaches:
            self.info("SLO recovered (window p50 %.2fms p99 %.2fms)",
                      p50_ms, p99_ms)
        self._slo_breached = bool(breaches)


#: serve health keys surfaced to web_status / heartbeats
def serve_snapshot(reg=None):
    """The serving health block as a flat plain-data dict: queue depth,
    SLO violations, shed/error counts, latency percentiles (ms) and
    mean batch size.  Empty dict when nothing ever served — dashboards
    show the block only on serving processes.

    On a multi-replica server (``serve.replicas`` gauge set by the
    ReplicaPool) the block also carries the replica count and the
    per-replica queue depths, and ``queue_depth`` becomes their sum —
    counters and histograms are process-shared, so the totals and
    percentiles already aggregate across replicas by construction."""
    reg = reg if reg is not None else _registry
    out = {}
    for name, short in (("serve.queue_depth", "queue_depth"),
                        ("serve.slo_violations", "slo_violations"),
                        ("serve.requests", "requests"),
                        ("serve.shed", "shed"),
                        ("serve.errors", "errors"),
                        ("serve.reloads", "reloads"),
                        ("serve.rung_cap", "rung_cap"),
                        # int8 quantized engine flag + calibration
                        # clip health (docs/serving.md "Quantized
                        # ladder")
                        ("serve.quantized", "quantized"),
                        ("serve.quant.clip_fraction",
                         "quant_clip_fraction"),
                        # freshness loop (docs/serving.md): the serve
                        # column shows cutover traffic next to load
                        ("serve.freshness.published",
                         "freshness_published"),
                        ("serve.freshness.candidates",
                         "freshness_candidates"),
                        ("serve.freshness.promotions", "promotions"),
                        ("serve.freshness.rollbacks", "rollbacks"),
                        ("serve.freshness.poisoned_rejected",
                         "poisoned_rejected"),
                        # multi-host tier (docs/serving.md "Multi-host
                        # tier"): the serve column shows fleet
                        # membership + hedging next to load
                        ("serve.fleet.hosts_live", "hosts_live"),
                        ("serve.fleet.membership_epoch",
                         "fleet_membership_epoch"),
                        ("serve.fleet.requeues", "fleet_requeues"),
                        ("serve.hedge.fired", "hedges_fired"),
                        ("serve.hedge.wins", "hedge_wins"),
                        ("serve.hedge.duplicates_dropped",
                         "hedge_duplicates_dropped"),
                        # multi-tenant QoS (docs/serving.md
                        # "Multi-tenant QoS"): hedge suppressions and
                        # fleet-canary verdicts next to load; the
                        # per-class detail is the "tenants" block below
                        ("serve.hedge.budget_exhausted",
                         "hedge_budget_exhausted"),
                        ("serve.fleet.canary.mirrors",
                         "fleet_canary_mirrors"),
                        ("serve.fleet.canary.promotions",
                         "fleet_canary_promotions"),
                        ("serve.fleet.canary.rollbacks",
                         "fleet_canary_rollbacks"),
                        # request tracing (docs/observability.md
                        # "Request tracing"): sampled-span and tail-
                        # exemplar volume; the per-segment breakdown
                        # is the "segments" block below
                        ("serve.reqtrace.sampled", "reqtrace_sampled"),
                        ("serve.reqtrace.exemplars",
                         "reqtrace_exemplars"),
                        # fleet telemetry plane (docs/observability.md
                        # "Fleet telemetry"): alert firings + what is
                        # burning RIGHT NOW next to load; the alert
                        # history ring is /healthz's "alerts" block
                        ("alerts.fired", "alerts_fired"),
                        ("alerts.active", "alerts_active"),
                        ("telemetry.buckets", "telemetry_buckets"),
                        ("telemetry.chunks_shipped",
                         "telemetry_chunks_shipped")):
        metric = reg.peek(name)
        if metric is not None and metric.value is not None:
            out[short] = metric.value
    replicas = reg.peek("serve.replicas")
    if replicas is not None and replicas.value:
        out["replicas"] = replicas.value
        depths = []
        for i in range(int(replicas.value)):
            gauge = reg.peek("serve.replica.%d.queue_depth" % i)
            depths.append(
                gauge.value if gauge is not None and
                gauge.value is not None else 0)
        out["replica_queue_depths"] = depths
        out["queue_depth"] = sum(depths)
    hist = reg.peek("serve.latency_s")
    if hist is not None and hist.count:
        snap = hist.snapshot()
        for p in ("p50", "p95", "p99"):
            if snap.get(p) is not None:
                out["%s_ms" % p] = round(snap[p] * 1e3, 3)
    batch = reg.peek("serve.batch_size")
    if batch is not None and batch.count:
        out["batch_mean"] = round(batch.snapshot()["mean"], 2)
    # per-segment latency breakdown (observe/requests.py taxonomy):
    # WHERE the time goes, next to the end-to-end percentiles above —
    # populated while request tracing is enabled
    segments = {}
    for name in reqtrace.SEGMENTS:
        hist = reg.peek("serve.segment.%s_s" % name)
        if hist is not None and hist.count:
            snap = hist.snapshot()
            segments[name] = {
                "count": snap["count"],
                "p50_ms": round((snap.get("p50") or 0.0) * 1e3, 3),
                "p99_ms": round((snap.get("p99") or 0.0) * 1e3, 3),
            }
    if segments:
        out["segments"] = segments
    tenants = qos.tenant_snapshot(reg)
    if tenants:
        out["tenants"] = tenants
    return out
