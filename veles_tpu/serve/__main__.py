"""``python -m veles_tpu.serve`` — stand up the inference service.

Serves a trained workflow snapshot (the crash-consistent pickles
``snapshotter.py`` writes) behind one AOT engine + continuous batcher
REPLICA per visible device (``--replicas`` overrides), with the
persistent compilation cache ON by default so a restart of this
process performs zero new backend compiles — and, because all replicas
share the digest-keyed cache, a warm fleet start costs one compile
set, not N:

    python -m veles_tpu.serve --snapshot mnist_current.pickle \\
        --port 8080 --transport-port 8081 \\
        --ladder 1,8,32,128 --max-delay-ms 2 \\
        --slo-p50-ms 20 --slo-p99-ms 100

``--transport-port`` opens the binary frame listener (raw tensor
bytes, no JSON, no pickle — docs/serving.md wire format) beside the
JSON front.  ``SIGHUP`` or ``POST /reload {"snapshot": path}``
hot-swaps the served weights without dropping the queue (same digest =
zero recompiles).  ``--watch-dir`` closes the train-to-serve loop:
snapshots the trainer publishes there (``--publish-dir``) are
manifest-verified, canaried on one replica under mirrored traffic, and
promoted fleet-wide or auto-rolled back (docs/serving.md "Freshness
loop"); ``POST /publish`` pushes a pickup without waiting for the
poll.  ``--demo`` trains a tiny blobs MLP in-process instead (a smoke
target for the load generator and the docs walkthrough).
"""

import argparse
import signal
import sys
import threading
import time


def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.serve",
        description="AOT-compiled, continuously-batched inference "
                    "service")
    source = parser.add_mutually_exclusive_group()
    source.add_argument("--snapshot", help="trained workflow snapshot "
                        "(snapshotter export) to serve")
    source.add_argument("--demo", action="store_true",
                        help="train a tiny demo MLP and serve it")
    source.add_argument("--fleet", metavar="HOST:PORT,HOST:PORT,...",
                        help="run the FRONT tier of a multi-host "
                        "serve fleet over these serve hosts "
                        "(docs/serving.md 'Multi-host tier'): no "
                        "local model — hosts provide it; requests are "
                        "routed least-loaded with hedged tails and "
                        "exactly-once completion under host loss")
    parser.add_argument("--fleet-host", action="store_true",
                        help="run as a serve HOST of a multi-host "
                        "fleet: the binary transport listener only "
                        "(--transport-port), announced with "
                        "--host-id; a front started with --fleet "
                        "dials it")
    parser.add_argument("--host-id", default=None,
                        help="fleet host identity (--fleet-host; "
                        "default: machine id + pid)")
    parser.add_argument("--no-hedge", action="store_true",
                        help="--fleet: disable request hedging (the "
                        "straggler A/B's control leg)")
    parser.add_argument("--tenant-quota", default=None,
                        metavar="TENANT=RATE[:BURST],...",
                        help="per-tenant token-bucket admission quotas "
                        "(requests/s with optional burst; '*' sets the "
                        "default for unlisted tenants, which are "
                        "otherwise unlimited).  Over-quota requests "
                        "get 503 + a per-class seeded-jittered "
                        "retry_after; un-labelled traffic defaults to "
                        "the 'batch' class (docs/serving.md "
                        "'Multi-tenant QoS')")
    parser.add_argument("--hedge-budget", action="store_true",
                        help="--fleet: cap hedges per SLO class with "
                        "per-class token budgets (exhausted budget = "
                        "route normally, never fail)")
    parser.add_argument("--max-inflight", type=int, default=None,
                        help="--fleet: bound on unresolved front "
                        "requests; past it the class-ordered shedder "
                        "evicts best_effort, then batch — interactive "
                        "only when the front is saturated with "
                        "interactive work itself")
    parser.add_argument("--hedge-factor", type=float, default=2.0,
                        help="--fleet: hedge past factor x the mean "
                        "completed latency (throughput-corrected)")
    parser.add_argument("--hedge-floor-ms", type=float, default=50.0,
                        help="--fleet: minimum straggler age before a "
                        "hedge fires")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--path", default="/infer")
    parser.add_argument("--replicas", type=int, default=None,
                        help="engine replicas (default: one per "
                        "visible device)")
    parser.add_argument("--transport-port", type=int, default=None,
                        help="also listen for the binary frame "
                        "transport on this port (0 = ephemeral)")
    parser.add_argument("--ladder", default="1,8,32,128",
                        help="comma-separated batch-shape ladder")
    parser.add_argument("--max-delay-ms", type=float, default=2.0,
                        help="max continuous-batching queue delay")
    parser.add_argument("--max-queue", type=int, default=256,
                        help="pending-request bound before 503 shedding")
    parser.add_argument("--cache-root", default=None,
                        help="persistent compile-cache root (default: "
                        "~/.cache/veles_tpu/serve_cache; 'none' "
                        "disables)")
    parser.add_argument("--slo-p50-ms", type=float, default=None)
    parser.add_argument("--slo-p99-ms", type=float, default=None)
    parser.add_argument("--watch-dir", default=None, metavar="DIR",
                        help="run the train-to-serve freshness loop "
                        "over this publish directory (the trainer's "
                        "--publish-dir): new manifest-verified "
                        "snapshots are canaried on one replica and "
                        "promoted fleet-wide or auto-rolled back "
                        "(docs/serving.md)")
    parser.add_argument("--mirror-fraction", type=float, default=0.25,
                        help="traffic slice mirrored to the canary "
                        "replica (shadow-scored, never returned to "
                        "clients)")
    parser.add_argument("--min-mirrors", type=int, default=8,
                        help="clean mirrored pairs required before a "
                        "canary is promoted")
    parser.add_argument("--freshness-poll-s", type=float, default=0.5,
                        help="publish-directory poll interval (POST "
                        "/publish pushes skip the wait)")
    parser.add_argument("--no-canary", action="store_true",
                        help="freshness loop reloads candidates "
                        "directly (still manifest- and finite-gated) "
                        "instead of canarying them")
    parser.add_argument("--quantize", action="store_true",
                        help="post-training-quantize the model to int8 "
                        "before serving (docs/serving.md 'Quantized "
                        "ladder'): per-channel symmetric weight scales "
                        "+ activation scales calibrated from "
                        "--calibrate (or the loader's data)")
    parser.add_argument("--calibrate", default=None, metavar="FILE.npy",
                        help="calibration sample stream for --quantize "
                        "(numpy .npy of shape (N,) + sample_shape); "
                        "default: the loader's first samples, else a "
                        "random stream (smoke-grade scales, warned)")
    parser.add_argument("--calibration-percentile", type=float,
                        default=99.9,
                        help="abs-activation percentile the int8 grid "
                        "covers (100 = min/max calibration)")
    parser.add_argument("--duration", type=float, default=None,
                        help="serve for N seconds then exit (default: "
                        "until interrupted)")
    return parser


def _quantize_spec(sw, args):
    """--quantize: extract the f32 spec from the workflow, calibrate,
    and return the quantized (plans, params, sample_shape) triple."""
    import numpy

    from veles_tpu.quant import quantize_model_spec
    from veles_tpu.serve.router import ReplicaPool

    plans, params, sample_shape = ReplicaPool._workflow_spec(sw)
    if args.calibrate:
        samples = numpy.load(args.calibrate)
    else:
        loader = getattr(sw, "loader", None)
        data = getattr(loader, "original_data", None)
        if data is not None and data:
            samples = numpy.asarray(data.mem[:1024], numpy.float32)
        else:
            print("WARNING: no calibration stream (--calibrate) and no "
                  "loader data; calibrating on random samples — "
                  "smoke-grade activation scales only")
            rng = numpy.random.RandomState(11)
            samples = rng.randn(
                256, *sample_shape).astype(numpy.float32)
    mode = ("minmax" if args.calibration_percentile >= 100.0
            else "percentile")
    qparams, calib = quantize_model_spec(
        plans, params, samples, mode=mode,
        percentile=args.calibration_percentile)
    print("quantized %d/%d layers (clip fraction %.5f)"
          % (len(calib.layers), len(plans), calib.clip_fraction))
    return plans, qparams, sample_shape


def _demo_workflow():
    import numpy

    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    class BlobsLoader(FullBatchLoader):
        """Deterministic 4-class Gaussian blobs (the test zoo's demo)."""

        def load_data(self):
            self.class_lengths[:] = [0, 64, 256]
            self._calc_class_end_offsets()
            self.create_originals((16,))
            rng = numpy.random.RandomState(99)
            centers = rng.randn(4, 16) * 2.0
            for i in range(self.total_samples):
                label = i % 4
                self.original_data.mem[i] = (
                    centers[label] + rng.randn(16) * 0.3)
                self.original_labels[i] = label

    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("serve-demo", seed=1)),
        decision_config=dict(max_epochs=3),
    )
    sw.initialize(device=Device(backend="cpu"))
    sw.run()
    return sw


def _fleet_front_main(args):
    """--fleet: the front tier — no local model, route over hosts."""
    from veles_tpu.serve import ServeService
    from veles_tpu.serve.fleet import FleetRouter
    from veles_tpu.serve.qos import HedgeBudget, TenantQuota
    router = FleetRouter(hedge=not args.no_hedge,
                         hedge_factor=args.hedge_factor,
                         hedge_floor_s=args.hedge_floor_ms / 1e3,
                         hedge_budget=HedgeBudget()
                         if args.hedge_budget else None,
                         max_inflight=args.max_inflight)
    for address in args.fleet.split(","):
        router.add_host(address=address.strip())
    quota = TenantQuota.from_spec(args.tenant_quota) \
        if args.tenant_quota else None
    service = ServeService(router, port=args.port, path=args.path,
                           transport_port=args.transport_port,
                           quota=quota)
    service.start_background()
    snap = router.snapshot()
    print("fleet front on http://127.0.0.1:%d%s over %d host(s) "
          "(digest %s, hedging %s)"
          % (service.port, args.path, snap["hosts_live"],
             snap["digest"], "on" if router.hedge else "off"))
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        service.stop()
    return 0


def _fleet_host_main(args, pool, receipt, freshness=None):
    """--fleet-host: the binary listener a --fleet front dials.  A
    host is a full PR-12 serve process — ``--watch-dir`` runs the
    freshness loop here too, so published snapshots keep canarying
    and promoting on the host while the front routes to it."""
    import os

    from veles_tpu.network_common import machine_id
    from veles_tpu.serve.transport import BinaryTransportServer
    host_id = args.host_id or "%s-%d" % (machine_id(), os.getpid())
    pool.start()
    quota = None
    if args.tenant_quota:
        from veles_tpu.serve.qos import TenantQuota
        quota = TenantQuota.from_spec(args.tenant_quota)
    transport = BinaryTransportServer(
        pool, port=args.transport_port or 0,
        host_meta={"host_id": host_id}, quota=quota)
    transport.start_background()
    # the READY line is the soak driver's handshake: parse, then dial
    print("FLEET_HOST_READY port=%d host_id=%s digest=%s "
          "new_compiles=%d" % (transport.port, host_id, pool.digest,
                               receipt["new_compiles"]), flush=True)
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if freshness is not None:
            freshness.stop()
        transport.stop()
        pool.stop()
    return 0


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.fleet:
        if args.fleet_host:
            parser.error("--fleet (front) and --fleet-host (host) are "
                         "different roles; pick one")
        return _fleet_front_main(args)
    if not (args.snapshot or args.demo):
        parser.error("one of --snapshot / --demo / --fleet is required")
    if args.fleet_host and args.transport_port is None:
        args.transport_port = 0
    if args.demo:
        sw = _demo_workflow()
    else:
        from veles_tpu.workflow import restore_workflow
        sw = restore_workflow(args.snapshot)

    from veles_tpu.serve import ReplicaPool, ServeService
    ladder = tuple(int(b) for b in args.ladder.split(","))
    cache_kwargs = {}
    if args.cache_root != "none":
        cache_kwargs["persistent_cache"] = True
        if args.cache_root:
            cache_kwargs["cache_root"] = args.cache_root
    pool_kwargs = dict(
        replicas=args.replicas, ladder=ladder,
        max_delay_s=args.max_delay_ms / 1e3, max_queue=args.max_queue,
        slo_p50_ms=args.slo_p50_ms, slo_p99_ms=args.slo_p99_ms,
        **cache_kwargs)
    if args.quantize:
        plans, qparams, sample_shape = _quantize_spec(sw, args)
        pool = ReplicaPool(plans, qparams, sample_shape, **pool_kwargs)
    else:
        pool = ReplicaPool.from_workflow(sw, **pool_kwargs)
    receipt = pool.compile()
    freshness = None
    if args.watch_dir:
        from veles_tpu.serve import FreshnessController
        freshness = FreshnessController(
            pool, args.watch_dir, poll_s=args.freshness_poll_s,
            mirror_fraction=args.mirror_fraction,
            min_mirrors=args.min_mirrors,
            canary=not args.no_canary).start()
    if args.fleet_host:
        return _fleet_host_main(args, pool, receipt, freshness)
    loader = getattr(sw, "loader", None)
    quota = None
    if args.tenant_quota:
        from veles_tpu.serve.qos import TenantQuota
        quota = TenantQuota.from_spec(args.tenant_quota)
    service = ServeService(
        pool, port=args.port, path=args.path,
        labels_mapping=getattr(loader, "reversed_labels_mapping", None),
        transport_port=args.transport_port, freshness=freshness,
        quota=quota)
    service.start_background()
    print("serving on http://127.0.0.1:%d%s with %d replica(s)%s  "
          "(compile receipt: %s)"
          % (service.port, args.path, len(pool.replicas),
             "; binary transport :%d" % service.transport_port
             if service.transport_port is not None else "",
             {k: v for k, v in receipt.items() if k != "per_replica"}))
    if args.snapshot:
        # SIGHUP = hot-reload the snapshot path in place (the classic
        # daemon contract); runs on a thread so the handler returns
        def _reload(signum, frame):
            def run():
                try:
                    print("SIGHUP: reloading %s -> %s" % (
                        args.snapshot,
                        service.reload_snapshot(args.snapshot)))
                except Exception as exc:
                    print("SIGHUP reload failed: %s" % exc)
            threading.Thread(target=run, name="serve-reload").start()
        signal.signal(signal.SIGHUP, _reload)
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if freshness is not None:
            freshness.stop()
        service.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
