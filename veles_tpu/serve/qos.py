"""Multi-tenant QoS primitives for the serve tier.

The serve stack (docs/serving.md "Multi-tenant QoS") labels every
request with a tenant id and an SLO class — ``interactive`` /
``batch`` / ``best_effort`` — and degrades *selectively* instead of
uniformly:

- **Admission control**: a :class:`TokenBucket` quota per tenant
  (rate + burst, CLI/config-driven via :class:`TenantQuota`) rejects
  over-quota traffic at the service front / binary transport with a
  per-class 503 ``retry_after`` before the request ever reaches a
  queue.
- **Class-ordered shedding**: when a queue bound trips, the batcher
  and fleet front evict ``best_effort`` work first, then ``batch``;
  ``interactive`` is shed only when the queue is saturated with
  interactive work itself (:data:`SHED_ORDER` is the contract).
- **Per-class hedge budgets**: :class:`HedgeBudget` caps how fast each
  class may fire hedges so bulk traffic cannot burn the hedge capacity
  interactive traffic needs — an exhausted budget routes normally, it
  never fails the request.
- **Retry de-stampeding**: :class:`RetryJitter` gives every overload
  rejection a deterministic, seeded, per-class jitter so synchronized
  clients with the same rejection do not re-stampede the queue at the
  same instant.

Un-labelled legacy traffic keeps working unchanged: ``None`` / unknown
class names normalize to :data:`DEFAULT_CLASS` (``batch``).

Per-class accounting rides ``serve.tenant.<class>.{requests,shed,
latency_s}`` (served counters are bumped at the batcher — the serving
edge — so a fleet front and its hosts never double-count in-process)
and ``serve.hedge.budget_exhausted``; all of it surfaces through
``serve_snapshot`` / heartbeats / the web status page.
"""

import hashlib
import threading
import time

from veles_tpu.observe.metrics import registry as _registry

__all__ = [
    "SLO_CLASSES", "DEFAULT_CLASS", "SHED_ORDER", "normalize_class",
    "class_rank", "TokenBucket", "TenantQuota", "parse_quota_spec",
    "RetryJitter", "HedgeBudget", "note_request", "note_shed",
    "note_latency", "tenant_snapshot", "DEFAULT_SLO_BUDGETS_S",
    "slo_budget_s", "burn_rule_specs",
]

#: SLO classes, most- to least-important.  The taxonomy mirrors the
#: datacenter reality in "In-Datacenter Performance Analysis of a TPU":
#: latency-bounded interactive inference coexisting with bulk work.
SLO_CLASSES = ("interactive", "batch", "best_effort")

#: Un-labelled legacy traffic lands here — the middle class: it is
#: never preferred over interactive, but a best-effort storm is shed
#: before it.
DEFAULT_CLASS = "batch"

#: Shedding order contract: evict left-to-right.  ``interactive`` is
#: last — it is shed only when the queue is saturated with interactive
#: work itself (the "interactive starves last" invariant).
SHED_ORDER = ("best_effort", "batch", "interactive")

_RANK = {name: rank for rank, name in enumerate(SHED_ORDER)}


def normalize_class(name):
    """Map a wire-level class label to a canonical SLO class.

    ``None``, unknown names and case/punctuation variants all fold to
    :data:`DEFAULT_CLASS` so un-labelled legacy clients keep working
    unchanged.
    """
    if not name:
        return DEFAULT_CLASS
    canon = str(name).strip().lower().replace("-", "_")
    return canon if canon in _RANK else DEFAULT_CLASS


def class_rank(name):
    """Importance rank (higher = shed later): best_effort=0 < batch=1
    < interactive=2.  Unknown names rank as :data:`DEFAULT_CLASS`."""
    return _RANK[normalize_class(name)]


class TokenBucket(object):
    """Classic token bucket: ``rate`` tokens/second refill, capacity
    ``burst``.  Starts full.  The clock is injectable so quota math is
    deterministic under test.

    ``rate <= 0`` means the bucket never refills — whatever ``burst``
    grants is all a caller ever gets (used for "no hedges for this
    class" budgets).
    """

    def __init__(self, rate, burst=None, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._clock = clock
        self._tokens = self.burst
        self._stamp = clock()
        self._lock = threading.Lock()

    def _refill(self):
        now = self._clock()
        elapsed = now - self._stamp
        self._stamp = now
        if elapsed > 0 and self.rate > 0:
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)

    def try_take(self, n=1.0):
        """Take ``n`` tokens if available; never blocks."""
        with self._lock:
            self._refill()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def time_until(self, n=1.0):
        """Seconds until ``n`` tokens will be available (0 if already
        are; ``inf`` when the bucket can never grant ``n``)."""
        with self._lock:
            self._refill()
            deficit = n - self._tokens
            if deficit <= 0:
                return 0.0
            if self.rate <= 0 or n > self.burst:
                return float("inf")
            return deficit / self.rate

    @property
    def tokens(self):
        with self._lock:
            self._refill()
            return self._tokens


def parse_quota_spec(spec):
    """Parse the CLI quota spec ``"tenant=rate[:burst],..."`` into a
    ``{tenant: (rate, burst)}`` dict.  ``*`` names the default quota
    applied to any tenant not listed.  Example::

        acme=100:200,free_tier=5,*=50

    means tenant ``acme`` gets 100 req/s with a burst of 200, the
    ``free_tier`` tenant 5 req/s (burst = rate), and everyone else 50.
    """
    quotas = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError("quota spec entry %r: expected tenant=rate[:burst]"
                             % part)
        tenant, _, rhs = part.partition("=")
        rate, _, burst = rhs.partition(":")
        quotas[tenant.strip()] = (
            float(rate), float(burst) if burst else None)
    return quotas


class TenantQuota(object):
    """Per-tenant admission quota: one :class:`TokenBucket` per tenant.

    ``quotas`` maps tenant id -> ``(rate, burst)``; the ``*`` entry is
    the default applied (per tenant, each with its own bucket) to any
    tenant not listed.  Tenants with no entry and no default are
    unlimited — quota is opt-in, legacy traffic is never rejected by a
    quota nobody configured.  ``None``/missing tenant ids share one
    anonymous bucket under the default quota.
    """

    def __init__(self, quotas=None, clock=time.monotonic):
        quotas = dict(quotas or {})
        self._default = quotas.pop("*", None)
        self._spec = quotas
        self._clock = clock
        self._buckets = {}
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec, clock=time.monotonic):
        """Build from the CLI spec string (see :func:`parse_quota_spec`)."""
        return cls(parse_quota_spec(spec), clock=clock)

    def _bucket(self, tenant):
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                quota = self._spec.get(tenant, self._default)
                if quota is None:
                    return None
                rate, burst = quota
                bucket = TokenBucket(rate, burst, clock=self._clock)
                self._buckets[tenant] = bucket
            return bucket

    def admit(self, tenant, cost=1.0):
        """Charge ``cost`` tokens to ``tenant``'s bucket.  Returns
        ``None`` when admitted, else the seconds-until-refill hint the
        rejection's ``retry_after`` should be based on."""
        bucket = self._bucket(tenant if tenant else "*anonymous*")
        if bucket is None:
            return None
        if bucket.try_take(cost):
            return None
        wait = bucket.time_until(cost)
        return wait if wait != float("inf") else 1.0


class RetryJitter(object):
    """Deterministic seeded per-class jitter for overload ``retry_after``.

    Synchronized clients that hit the same rejection must not sleep
    the same interval and re-stampede the queue at the same instant —
    so each rejection of a class stretches the base estimate by a
    pseudo-random factor in ``[1, 1 + spread]`` drawn from
    ``sha256(seed, class, per-class rejection counter)``.  Same seed +
    same rejection sequence = same jitters (replayable under test);
    consecutive rejections of one class get distinct values.
    """

    def __init__(self, seed=0, spread=0.5):
        self.seed = int(seed)
        self.spread = float(spread)
        self._counters = {}
        self._lock = threading.Lock()

    def apply(self, base, slo_class=None):
        cls = normalize_class(slo_class)
        with self._lock:
            n = self._counters.get(cls, 0)
            self._counters[cls] = n + 1
        digest = hashlib.sha256(
            ("%d:%s:%d" % (self.seed, cls, n)).encode()).digest()
        frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
        return float(base) * (1.0 + frac * self.spread)


#: Default per-class end-to-end latency budgets in SECONDS, measured
#: from the ORIGINAL front-door arrival (requeues/hedges never restart
#: the clock).  This is the tail-exemplar trigger (observe/requests.py):
#: a non-shadow request past its class budget keeps its full segment
#: timeline in the bounded exemplar ring, dumped with the flight
#: recorder on ``serve.slo_violation``.  Deliberately loose defaults —
#: deployments with real SLOs pass their own dict to ``slo_budget_s``.
DEFAULT_SLO_BUDGETS_S = {
    "interactive": 0.100,
    "batch": 1.0,
    "best_effort": 5.0,
}


def slo_budget_s(slo_class, budgets=None):
    """The class's end-to-end latency budget in seconds (None when the
    class has no budget configured)."""
    budgets = DEFAULT_SLO_BUDGETS_S if budgets is None else budgets
    return budgets.get(normalize_class(slo_class))


def burn_rule_specs(budgets=None, objective=0.99, fast_buckets=3,
                    slow_buckets=12, factor=2.0, min_count=20,
                    scope="tenant"):
    """Declarative multi-window burn-rate rule specs, one per class
    with a configured budget — the bridge from the QoS budget table
    to the alert plane (observe/alerts.py ``rule_from_spec``): each
    watches the class's ``serve.<scope>.<class>.latency_s`` digest
    series and fires only when the fast AND slow windows both burn
    the ``1 - objective`` error budget at >= ``factor``.

    ``scope="tenant"`` (default) watches the HOST batcher's serving-
    edge histograms (``note_latency``); ``scope="fleet"`` watches the
    fleet front's end-to-end histograms — the ones that see transport
    stalls and straggler tails the serving edge never measures (a
    stalled frame parks BEFORE the batcher clock starts)."""
    budgets = DEFAULT_SLO_BUDGETS_S if budgets is None else budgets
    specs = []
    for cls in SLO_CLASSES:
        budget = budgets.get(cls)
        if budget is None:
            continue
        name = ("slo_burn.%s" % cls if scope == "tenant"
                else "slo_burn.%s.%s" % (scope, cls))
        specs.append({
            "name": name, "kind": "burn_rate",
            "hist": "serve.%s.%s.latency_s" % (scope, cls),
            "budget_s": float(budget), "objective": objective,
            "fast_buckets": fast_buckets,
            "slow_buckets": slow_buckets, "factor": factor,
            "min_count": min_count})
    return specs


#: Default per-class hedge budgets (tokens/second, burst).  Interactive
#: gets the lion's share — hedging exists to protect ITS tail; bulk
#: classes get a trickle so a stuck host still unwedges batch work
#: without burning the capacity interactive needs.
DEFAULT_HEDGE_BUDGETS = {
    "interactive": (20.0, 40.0),
    "batch": (5.0, 10.0),
    "best_effort": (1.0, 2.0),
}


class HedgeBudget(object):
    """Per-class token buckets gating hedge sends in the FleetRouter.

    ``try_take(cls)`` is asked right before a hedge would fire; a
    ``False`` answer means the class's budget is exhausted — the
    caller routes normally (the primary copy stands, the request NEVER
    fails because of budget) and ``serve.hedge.budget_exhausted``
    records the suppression.
    """

    def __init__(self, budgets=None, clock=time.monotonic):
        budgets = dict(DEFAULT_HEDGE_BUDGETS, **(budgets or {}))
        self._buckets = {
            normalize_class(cls): TokenBucket(rate, burst, clock=clock)
            for cls, (rate, burst) in budgets.items()}
        self._m_exhausted = _registry.counter("serve.hedge.budget_exhausted")

    def try_take(self, slo_class):
        bucket = self._buckets[normalize_class(slo_class)]
        if bucket.try_take(1.0):
            return True
        self._m_exhausted.inc()
        return False


# -- per-class accounting -----------------------------------------------------


def note_request(slo_class, rows=1, reg=None):
    """Count ``rows`` served samples for the class.  Callers must skip
    shadow/mirror traffic — mirrored evidence never counts as served."""
    reg = reg or _registry
    reg.counter("serve.tenant.%s.requests" % normalize_class(slo_class)).inc(rows)


def note_shed(slo_class, reg=None):
    """Count one shed (queue eviction, bound rejection, or over-quota
    admission reject) attributed to the class that LOST the capacity."""
    reg = reg or _registry
    reg.counter("serve.tenant.%s.shed" % normalize_class(slo_class)).inc()


def note_latency(slo_class, seconds, reg=None):
    reg = reg or _registry
    reg.histogram("serve.tenant.%s.latency_s" % normalize_class(slo_class),
                  ).observe(float(seconds))


def tenant_snapshot(reg=None):
    """Per-class block for ``serve_snapshot``: requests/shed counts and
    latency percentiles for every class that saw traffic."""
    from veles_tpu.observe.metrics import percentiles
    reg = reg or _registry
    out = {}
    for cls in SLO_CLASSES:
        block = {}
        for suffix in ("requests", "shed"):
            metric = reg.peek("serve.tenant.%s.%s" % (cls, suffix))
            if metric is not None and metric.value:
                block[suffix] = metric.value
        hist = reg.peek("serve.tenant.%s.latency_s" % cls)
        if hist is not None and hist.count:
            window = hist.window_values()
            if window:
                pcts = percentiles(window, (50, 99))
                block["latency_ms"] = {
                    "p50": round(pcts["p50"] * 1e3, 3),
                    "p99": round(pcts["p99"] * 1e3, 3),
                }
        if block:
            out[cls] = block
    return out
