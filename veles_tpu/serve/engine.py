"""AOT inference engine: a ladder of pre-compiled per-shape executables.

The reference's libVeles served a fixed workflow from a standalone C++
runtime: no tracing, no JIT, load-and-run.  The JAX analog is
ahead-of-time compilation — ``jax.jit(forward).lower(...).compile()``
against a small *ladder* of padded batch shapes (default 1/8/32/128),
so at serve time a request batch is padded up to the smallest fitting
rung and dispatched to an executable that already exists.  The old
``RESTfulAPI._compile`` path jit-compiled lazily on the first request
of each new batch shape, which put multi-second XLA compiles on the
latency path exactly when traffic changed — the failure mode the TPU
in-datacenter paper's latency-percentile framing punishes hardest.

Cold start is handled by the **persistent compilation cache**:
:func:`enable_persistent_cache` points ``jax_compilation_cache_dir`` at
a directory keyed by :func:`model_digest` (the architecture + shape
fingerprint, the same pattern as ``native.source_digest`` for the C++
runtime's build cache) and drops the min-compile-time/entry-size
floors so every rung persists.  A restarted server then *deserializes*
its ladder instead of rebuilding it: ``compile_receipt["new_compiles"]``
is 0, asserted via the ``compile.count`` / ``compile.cache_hits``
counters of :mod:`veles_tpu.observe.xla_introspect` (the backend-compile
monitoring event fires even on a cache hit, so the receipt subtracts
hits — see that module).

Numerics note (tests/test_serve.py): on XLA:CPU all rungs >= the vector
width (8 is safely past it) produce bit-identical per-row results, and
padding rows never leak into real rows (no cross-row reduction except
the per-row softmax), so continuous batching preserves bit-equality
with sequential serving *within* those rungs.  The rung-1 executable
lowers to a different vector-matrix kernel and may differ by ~1 ulp;
deployments that need strict batch-size-invariant bits should start
the ladder at 8.

Input donation is enabled only where the backend actually honors it
(TPU/GPU); XLA:CPU ignores donation with a warning, so ``donate="auto"``
skips it there.
"""

import hashlib
import os

import numpy

from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer

__all__ = ["AOTEngine", "model_digest", "enable_persistent_cache",
           "engine_digest_extra", "publish_quantized_state",
           "value_digest", "DEFAULT_LADDER"]


def publish_quantized_state(quantized):
    """Publish the process's served-arithmetic level: the
    ``serve.quantized`` gauge (serve_snapshot / healthz / heartbeats)
    and the MFU-ceiling dtype (``xla_introspect.set_step_dtype`` —
    int8 steps must not rate against the bf16 peak).

    Process-global, so it must track what the fleet actually SERVES:
    ``AOTEngine.compile`` publishes its own level (cold starts,
    standalone engines, new-digest reload warm-ups), and every
    transition that can change the live fleet without a compile —
    canary promote/rollback are swap-backs with 0 compiles by
    construction — republishes from the pool's live anchor engine, so
    a REJECTED quantized canary cannot leave an f32 fleet branded
    quantized (and rating MFU against the int8 peak) forever."""
    from veles_tpu.observe import xla_introspect
    _registry.gauge("serve.quantized").set(1 if quantized else 0)
    xla_introspect.set_step_dtype("int8" if quantized else "bf16")

#: default batch-shape ladder: singles stay latency-optimal, 128 is the
#: throughput rung (past it, padding waste beats batching gains for the
#: model sizes this repo serves)
DEFAULT_LADDER = (1, 8, 32, 128)


def model_digest(plans, params, sample_shape, extra=None):
    """Architecture fingerprint for the persistent-cache directory key.

    Hashes what determines the COMPILED PROGRAM — layer classes, static
    configs, parameter shapes/dtypes, the input sample shape, and the
    jax version — and deliberately NOT the weight values: retraining
    the same architecture must keep hitting the same cache (the HLO is
    identical), while any shape or topology change must miss.  Same
    role as ``native.source_digest`` for the C++ runtime's build cache.
    """
    import jax
    digest = hashlib.sha256()
    digest.update(("jax:%s" % jax.__version__).encode())
    digest.update(repr(tuple(sample_shape)).encode())
    if extra:
        digest.update(repr(extra).encode())
    for plan, entry in zip(plans, params):
        digest.update(plan.forward_cls.__name__.encode())
        digest.update(repr(sorted(plan.static.items())).encode())
        for key in sorted(entry):
            leaf = entry[key]
            if leaf is None:
                digest.update(("%s:none" % key).encode())
            else:
                digest.update(("%s:%s:%s" % (
                    key, tuple(leaf.shape),
                    numpy.dtype(leaf.dtype).str)).encode())
    return digest.hexdigest()[:16]


def engine_digest_extra(dtype):
    """The ``extra`` an AOTEngine mixes into :func:`model_digest`: the
    ladder's INPUT dtype.  Param shapes/dtypes already ride the digest
    (so an int8-quantized spec and its f32 source can never collide —
    the regression test in tests/test_quant.py), but the input dtype
    determines the compiled program too and lives nowhere in the
    params: two engines serving the same weights at f32 vs bf16 inputs
    would otherwise share one persistent-cache directory and one
    freshness last-good identity.  Shared by ``AOTEngine`` and the
    router's ``reload_replicas`` so their digests agree byte-for-byte."""
    return {"input_dtype": numpy.dtype(dtype).str}


def value_digest(params):
    """Fingerprint of the parameter VALUES — the complement of
    :func:`model_digest`, which deliberately excludes them.  Two
    snapshots of the same architecture share a model digest (same
    compiled program) but differ here unless their weights are
    bit-identical; the freshness loop uses this to name *which* weights
    a fleet serves (last-good identity, rollback-restored-the-right-
    thing assertions) without holding the arrays themselves up for
    comparison."""
    digest = hashlib.sha256()
    for entry in params:
        for key in sorted(entry):
            leaf = entry[key]
            digest.update(key.encode())
            if leaf is None:
                digest.update(b"none")
            else:
                arr = numpy.ascontiguousarray(numpy.asarray(leaf))
                digest.update(arr.dtype.str.encode())
                digest.update(repr(arr.shape).encode())
                digest.update(arr.tobytes())
    return digest.hexdigest()[:16]


def enable_persistent_cache(digest, cache_root=None):
    """Point JAX's persistent compilation cache at a digest-keyed dir
    and make it catch EVERYTHING; returns the directory.

    Overrides the generic cache ``backends._enable_persistent_compile_
    cache`` may have set: that one keeps jax's 1-second min-compile-time
    floor (tuned for 20-40 s conv-net compiles over a TPU tunnel),
    which silently refuses to persist the sub-second executables a
    small serving ladder compiles — exactly the ones a restarted server
    needs back.  Serving owns its process, so the global config flip is
    deliberate."""
    import jax
    root = cache_root or os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "veles_tpu", "serve_cache")
    path = os.path.join(root, digest)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # knob absent on old jax: size floor stays, cache still on
    # jax's cache SINGLETON binds to the directory at the process's
    # first compile and ignores later config updates ("cache is
    # disabled/not initialized"): any compile before this call —
    # device probing, another subsystem's jit — would silently strand
    # the ladder outside the digest dir.  Reset so the next use
    # re-initializes at the new path.
    try:
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception:
        pass  # private API drift: stale binding beats a crash
    return path


class AOTEngine(Logger):
    """Pre-compiled per-(model, batch-shape) executables + padded run.

    ``plans``/``params`` are the :mod:`veles_tpu.compiler` forward plan
    and the ``[{"weights", "bias"}]`` parameter list (host numpy or
    device arrays); ``sample_shape`` the per-sample input shape.  After
    :meth:`compile`, :meth:`run` dispatches a device batch on an exact
    rung and :meth:`infer` is the host-convenience (and sequential-
    reference) path: chunk, pad, run, slice.
    """

    def __init__(self, plans, params, sample_shape,
                 ladder=DEFAULT_LADDER, device=None, cache_root=None,
                 persistent_cache=False, donate="auto",
                 dtype=numpy.float32, **kwargs):
        super(AOTEngine, self).__init__(**kwargs)
        if not plans:
            raise ValueError("AOTEngine needs a non-empty plan list")
        self.plans = list(plans)
        self.params = [dict(entry) for entry in params]
        self.sample_shape = tuple(int(s) for s in sample_shape)
        self.ladder = tuple(sorted({int(b) for b in ladder}))
        if not self.ladder or self.ladder[0] < 1:
            raise ValueError("ladder must hold positive batch sizes")
        if device is None:
            from veles_tpu.backends import Device
            device = Device()
        self.device = device
        self.dtype = numpy.dtype(dtype)
        self.donate = donate
        # int8-quantized spec (docs/serving.md "Quantized ladder"): the
        # quantization pass's artifacts in the entries are the ONLY
        # flag — no side channel through snapshots/publishes needed
        from veles_tpu.quant.forward import is_quantized_params
        self.quantized = is_quantized_params(self.params)
        self.digest = model_digest(plans, self.params, self.sample_shape,
                                   extra=engine_digest_extra(self.dtype))
        self.cache_root = cache_root
        self.cache_dir = None
        if persistent_cache or cache_root is not None:
            self.cache_dir = enable_persistent_cache(
                self.digest, cache_root)
        self.compile_receipt = None
        self._compiled = {}
        self._params_dev = None
        #: per-rung dispatch counters, minted on first use — lets the
        #: request-trace device segment (observe/requests.py) be
        #: correlated with WHICH executable ran when a tail shows up
        self._dispatch_counters = {}

    @classmethod
    def from_workflow(cls, sw, **kwargs):
        """Build from a trained StandardWorkflow: extracts the forward
        plan + parameters exactly like the old ``RESTfulAPI._compile``
        did, plus the loader's sample shape, and inherits the
        workflow's device."""
        from veles_tpu.compiler import extract_state, workflow_plan
        plans = workflow_plan(sw)
        state = extract_state(sw)
        params = [{"weights": s["weights"], "bias": s["bias"]}
                  for s in state]
        loader = getattr(sw, "loader", None)
        if "sample_shape" in kwargs:
            sample_shape = kwargs.pop("sample_shape")
        elif loader is not None and loader.minibatch_data:
            sample_shape = tuple(loader.minibatch_data.shape[1:])
        else:
            raise ValueError("workflow has no loader shape; pass "
                             "sample_shape=")
        kwargs.setdefault("device", getattr(sw.forwards[0], "device",
                                            None))
        return cls(plans, params, sample_shape, **kwargs)

    # -- compilation --------------------------------------------------------

    @property
    def max_batch(self):
        return self.ladder[-1]

    def _donate_argnums(self):
        if self.donate == "auto":
            try:
                platform = self.device.jax_device.platform
            except Exception:
                platform = "cpu"
            # XLA:CPU ignores input-output aliasing for these programs
            # and warns per compile; donation only buys anything where
            # the backend honors it
            return (1,) if platform != "cpu" else ()
        return (1,) if self.donate else ()

    def compile(self):
        """Lower + compile every rung; returns the compile receipt.

        The receipt is the cold/warm-start proof (docs/serving.md):
        ``backend_compiles`` counts compile REQUESTS (jax's monitoring
        event fires even on a persistent-cache hit), ``cache_hits``
        the executables deserialized from disk, ``new_compiles`` their
        difference — 0 on a warm restart."""
        import time

        import jax

        from veles_tpu.compiler import build_forward
        from veles_tpu.observe import xla_introspect

        start = time.perf_counter()
        with xla_introspect.compile_delta() as delta:
            self._params_dev = self._put_params(self.params)
            if self.quantized:
                # the int8 ladder: same plans, the quantized forward
                # (quant/forward.py) over the int8 Pallas kernels —
                # "just another digest" to everything downstream
                from veles_tpu.quant.forward import \
                    build_quantized_forward
                forward = build_quantized_forward(self.plans)
            else:
                forward = build_forward(self.plans)
            donate = self._donate_argnums()
            for rung in self.ladder:
                x_aval = jax.ShapeDtypeStruct(
                    (rung,) + self.sample_shape, self.dtype)
                with _tracer.span("serve.compile", cat="serve",
                                  rung=rung):
                    jitted = jax.jit(forward, donate_argnums=donate)
                    self._compiled[rung] = jitted.lower(
                        self._params_dev, x_aval).compile()
        elapsed = time.perf_counter() - start
        requests = delta.receipt["backend_compiles"]
        hits = delta.receipt["cache_hits"]
        self.compile_receipt = dict(
            delta.receipt,
            rungs=list(self.ladder),
            seconds=round(elapsed, 4),
            cache_dir=self.cache_dir,
            quantized=self.quantized,
        )
        # the quantized-engine flag + int8 MFU-ceiling accounting
        # (docs/serving.md): serve_snapshot / healthz read the gauge,
        # and mfu_snapshot must not divide int8 steps by the bf16 peak
        publish_quantized_state(self.quantized)
        try:
            # tuned-schedule provenance beside the compile-cache
            # receipt: which road the kernel tiles took during this
            # warm-up (docs/kernels.md "Autotuning") — consult counters
            # plus the schedule-cache population
            from veles_tpu.tune.cache import tune_counters
            self.compile_receipt["tune"] = tune_counters()
        except Exception:
            pass  # a broken schedule cache must never fail a warm-up
        _registry.gauge("serve.aot_rungs").set(len(self.ladder))
        _registry.gauge("serve.compile_s").set(round(elapsed, 4))
        self.info(
            "AOT ladder %s compiled in %.2fs (%d compile requests, "
            "%d cache hits -> %d new backend compiles)%s",
            list(self.ladder), elapsed, requests, hits,
            self.compile_receipt["new_compiles"],
            " cache=%s" % self.cache_dir if self.cache_dir else "")
        return self.compile_receipt

    def _put_params(self, params):
        put = self.device.put
        return [
            {key: (None if leaf is None else put(numpy.asarray(leaf)))
             for key, leaf in entry.items()}
            for entry in params]

    def swap_params(self, params):
        """Hot-swap the weights under the SAME architecture: new device
        buffers, zero recompiles.

        The compiled executables are parameterized by the params
        argument (``run`` passes ``self._params_dev`` per dispatch, and
        donation covers only the batch input), so replacing the device
        buffer list is the entire snapshot-reload mechanism for a
        same-digest model: the list is built complete, then swapped in
        with ONE attribute assignment — an in-flight ``run`` holds a
        reference to whichever list it started with, so batches are
        never torn between old and new weights.  A digest mismatch
        (shape/topology change) is rejected here; that case needs a new
        engine + ladder warm-up (the router's reload path).
        """
        params = [dict(entry) for entry in params]
        digest = model_digest(self.plans, params, self.sample_shape,
                              extra=engine_digest_extra(self.dtype))
        if digest != self.digest:
            raise ValueError(
                "swap_params digest mismatch (%s != %s): architecture "
                "or shapes changed — build a new engine" %
                (digest, self.digest))
        if self._params_dev is None:
            raise RuntimeError("AOTEngine.compile() not called")
        params_dev = self._put_params(params)
        self.params = params
        self._params_dev = params_dev
        return digest

    # -- dispatch -----------------------------------------------------------

    def rung_for(self, n, cap=None):
        """Smallest ladder rung holding ``n`` samples (the largest rung
        when ``n`` overflows it — callers chunk).  ``cap`` bounds the
        answer (the batcher's OOM-degrade path)."""
        top = self.ladder[-1] if cap is None else cap
        for rung in self.ladder:
            if rung > top:
                break
            if rung >= n:
                return rung
        return min(top, self.ladder[-1])

    def run(self, x_dev, rung):
        """Dispatch one pre-compiled executable on an exact-rung device
        batch; returns the device-side output (no host sync).  Bumps
        ``serve.engine.dispatches.rung<r>`` so device-segment tails in
        the request traces attribute to the executable that ran."""
        counter = self._dispatch_counters.get(rung)
        if counter is None:
            counter = self._dispatch_counters[rung] = \
                _registry.counter(
                    "serve.engine.dispatches.rung%d" % rung)
        counter.inc()
        return self._compiled[rung](self._params_dev, x_dev)

    def infer(self, x):
        """Host-side convenience: pad/chunk ``x`` through the ladder
        and return the output rows as ONE numpy array.

        This is also the sequential reference path the batching
        bit-equality test compares against: a single sample goes
        through the smallest rung, exactly like a lone queued request
        would."""
        x = numpy.ascontiguousarray(x, self.dtype)
        if x.shape == self.sample_shape:
            x = x[None]
        if x.shape[1:] != self.sample_shape:
            raise ValueError("expected sample shape %s, got %s" %
                             (self.sample_shape, x.shape[1:]))
        if self._params_dev is None:
            raise RuntimeError("AOTEngine.compile() not called")
        out, i, n = [], 0, x.shape[0]
        while i < n:
            take = min(self.max_batch, n - i)
            rung = self.rung_for(take)
            if take == rung:
                chunk = x[i:i + rung]
            else:
                chunk = numpy.zeros((rung,) + self.sample_shape,
                                    self.dtype)
                chunk[:take] = x[i:i + take]
            result = self.run(self.device.put(chunk), rung)
            out.append(numpy.asarray(result)[:take])
            i += take
        return numpy.concatenate(out) if len(out) > 1 else out[0]
