"""Replica pool + request router: one AOT engine per chip.

The single-process serve stack (PR 7) has exactly one engine and one
batcher — fine for one chip, a hard ceiling for "millions of users".
The TensorFlow paper's serving recipe (PAPERS.md) is to replicate the
compiled function across devices behind one request stream; the TPU
in-datacenter paper adds the constraint: per-chip throughput under a
latency budget is the number that matters.  So the scale-out unit here
is a **replica** — an :class:`AOTEngine` compiled against one visible
device plus its own :class:`ContinuousBatcher` worker — and the
:class:`ReplicaPool` is the sharded front:

- **placement**: one replica per ``jax.local_devices()`` entry by
  default (``replicas=`` overrides; the CPU harness cycles devices),
  every engine keyed to the SAME model digest so the persistent
  compile cache makes a warm fleet restart compile NOTHING — the cold
  fleet start is the only one that pays, and pays per device because
  jax's cache key includes the device assignment;
- **routing**: each request goes to the least-loaded replica (queue
  depth at submit); an overloaded replica cascades the request to its
  siblings before the pool sheds with a 503-shaped
  :class:`ServeOverload` whose ``retry_after`` is the fleet's best
  offer;
- **observability**: per-replica ``serve.replica.N.*`` gauges next to
  the process-shared serve counters/histograms (which therefore
  aggregate across replicas by construction), ``serve.replicas`` and
  the aggregate ``serve.queue_depth`` for heartbeats/web-status, and
  per-replica ``serve.batch`` spans (the batcher worker threads give
  each replica its own track in merged traces);
- **snapshot hot-reload** (:meth:`ReplicaPool.reload`): a same-digest
  snapshot swaps device weight buffers in place — zero recompiles,
  receipted via ``xla_introspect.compile_delta`` — while a changed
  digest AOT-warms a full new ladder per replica in the background and
  cuts over atomically between batches; either way the queue is never
  dropped.
"""

import threading
import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.serve.batcher import ContinuousBatcher, ServeOverload
from veles_tpu.serve.engine import (
    AOTEngine, DEFAULT_LADDER, model_digest)

__all__ = ["Replica", "ReplicaPool", "local_devices",
           "reload_replicas"]


def local_devices(count=None):
    """Device handles for a replica fleet: one :class:`backends.Device`
    per visible jax device, cycled when ``count`` asks for more
    replicas than devices (the CPU harness measures router/transport
    scaling with several replicas on one host)."""
    import jax

    from veles_tpu.backends import Device
    jax_devices = jax.local_devices()
    backend = "cpu" if jax_devices[0].platform == "cpu" else "tpu"
    n = int(count) if count else len(jax_devices)
    if n < 1:
        raise ValueError("need at least one replica")
    return [Device(backend=backend,
                   device_index=i % len(jax_devices))
            for i in range(n)]


class Replica(object):
    """One engine+batcher pair bound to one device."""

    __slots__ = ("index", "device", "engine", "batcher")

    def __init__(self, index, device, engine, batcher):
        self.index = index
        self.device = device
        self.engine = engine
        self.batcher = batcher


def reload_replicas(replicas, params, plans=None, sample_shape=None,
                    ladder=None, engine_kwargs=None):
    """The ONE hot-reload state machine, shared by :class:`ReplicaPool`
    and the single-engine :class:`ServeService` (a list of one
    Replica-shaped entry).  Callers hold their own reload lock.

    Same digest: each entry's weights swap in place via
    ``AOTEngine.swap_params`` — zero new backend compiles, receipted
    via ``compile_delta``.  New digest (or ladder change): a full new
    engine per entry is AOT-warmed HERE, off the dispatch path, then
    each batcher cuts over between batches.  Returns the receipt."""
    from veles_tpu.observe import xla_introspect
    current = replicas[0].engine
    new_plans = list(plans) if plans is not None else current.plans
    new_shape = tuple(sample_shape) if sample_shape is not None \
        else current.sample_shape
    params = [dict(entry) for entry in params]
    new_digest = model_digest(new_plans, params, new_shape)
    same = (new_digest == current.digest and
            (ladder is None or
             tuple(sorted({int(b) for b in ladder})) == current.ladder))
    mode = "params" if same else "engine"
    start = time.perf_counter()
    with _tracer.span("serve.reload", cat="serve", mode=mode,
                      digest=new_digest):
        with xla_introspect.compile_delta() as delta:
            if same:
                for rep in replicas:
                    rep.engine.swap_params(params)
            else:
                kwargs = dict(engine_kwargs or {})
                if ladder is not None:
                    kwargs["ladder"] = ladder
                fresh = []
                for rep in replicas:
                    engine = AOTEngine(new_plans, params, new_shape,
                                       device=rep.device, **kwargs)
                    engine.compile()
                    fresh.append(engine)
                # warm-up done: atomic cutover, oldest first
                for rep, engine in zip(replicas, fresh):
                    rep.batcher.swap_engine(engine)
                    rep.engine = engine
    receipt = dict(
        delta.receipt, mode=mode, digest=new_digest,
        previous_digest=current.digest, replicas=len(replicas),
        seconds=round(time.perf_counter() - start, 4))
    _registry.counter("serve.reloads").inc()
    return receipt


class ReplicaPool(Logger):
    """N per-device serving replicas behind one least-loaded router.

    Duck-types the :class:`ContinuousBatcher` submit surface
    (``submit``/``submit_block``/``infer``/``start``/``stop``), so
    :class:`ServeService` and the binary transport drive a pool and a
    single batcher identically."""

    def __init__(self, plans, params, sample_shape, replicas=None,
                 ladder=DEFAULT_LADDER, devices=None, cache_root=None,
                 persistent_cache=False, dtype=numpy.float32,
                 **batcher_kwargs):
        super(ReplicaPool, self).__init__()
        if devices is None:
            devices = local_devices(replicas)
        elif replicas:
            devices = [devices[i % len(devices)]
                       for i in range(int(replicas))]
        self._engine_kwargs = dict(
            ladder=ladder, cache_root=cache_root,
            persistent_cache=persistent_cache, dtype=dtype)
        self._batcher_kwargs = dict(batcher_kwargs)
        self.replicas = []
        for i, device in enumerate(devices):
            engine = AOTEngine(plans, params, sample_shape,
                               device=device, **self._engine_kwargs)
            batcher = ContinuousBatcher(engine, replica=i,
                                        **self._batcher_kwargs)
            self.replicas.append(Replica(i, device, engine, batcher))
        self.compile_receipt = None
        self._reload_lock = threading.Lock()
        self._m_replicas = _registry.gauge("serve.replicas")
        self._m_replicas.set(len(self.replicas))
        self._m_depth = _registry.gauge("serve.queue_depth")
        self._m_cascades = _registry.counter("serve.router.cascades")

    # -- workflow plumbing --------------------------------------------------

    @staticmethod
    def _workflow_spec(sw, sample_shape=None):
        from veles_tpu.compiler import extract_state, workflow_plan
        plans = workflow_plan(sw)
        state = extract_state(sw)
        params = [{"weights": s["weights"], "bias": s["bias"]}
                  for s in state]
        if sample_shape is None:
            loader = getattr(sw, "loader", None)
            if loader is not None and loader.minibatch_data:
                sample_shape = tuple(loader.minibatch_data.shape[1:])
            else:
                raise ValueError("workflow has no loader shape; pass "
                                 "sample_shape=")
        return plans, params, tuple(sample_shape)

    @classmethod
    def from_workflow(cls, sw, **kwargs):
        """Build a pool from a trained StandardWorkflow, exactly like
        ``AOTEngine.from_workflow`` but fanned out per device."""
        plans, params, sample_shape = cls._workflow_spec(
            sw, kwargs.pop("sample_shape", None))
        return cls(plans, params, sample_shape, **kwargs)

    # -- lifecycle ----------------------------------------------------------

    @property
    def engine(self):
        """Replica 0's engine: the pool's metadata anchor (digest,
        ladder, sample shape, dtype) — LIVE across hot reloads."""
        return self.replicas[0].engine

    @property
    def digest(self):
        return self.engine.digest

    def compile(self):
        """Compile every replica's ladder; returns the aggregate
        receipt.  All replicas share the ONE digest-keyed persistent
        cache directory; jax's cache key includes the device
        assignment, so a cold fleet start writes one entry set per
        device — and a warm fleet RESTART deserializes every one of
        them: ``new_compiles == 0`` across all N replicas, asserted by
        tests/test_serve_router.py."""
        start = time.perf_counter()
        per = [rep.engine.compile() for rep in self.replicas]
        self.compile_receipt = {
            "replicas": len(per),
            "rungs": per[0]["rungs"],
            "backend_compiles": sum(
                r["backend_compiles"] for r in per),
            "cache_hits": sum(r["cache_hits"] for r in per),
            "new_compiles": sum(r["new_compiles"] for r in per),
            "seconds": round(time.perf_counter() - start, 4),
            "cache_dir": per[0]["cache_dir"],
            "per_replica": per,
        }
        return self.compile_receipt

    @property
    def running(self):
        return any(rep.batcher.running for rep in self.replicas)

    def start(self):
        for rep in self.replicas:
            rep.batcher.start()
        return self

    def stop(self):
        for rep in self.replicas:
            rep.batcher.stop()
        self._m_depth.set(0)

    # -- routing ------------------------------------------------------------

    def _update_depth(self):
        self._m_depth.set(sum(rep.batcher._q.qsize()
                              for rep in self.replicas))

    def _submit(self, fn):
        """Least-queue-depth pick with overload cascade: try replicas
        in depth order; only when EVERY replica sheds does the pool
        itself shed, with the smallest retry_after any replica offered
        (the fleet's best promise, not its worst)."""
        ranked = sorted(self.replicas,
                        key=lambda rep: rep.batcher._q.qsize())
        sheds = []
        for nth, rep in enumerate(ranked):
            try:
                req = fn(rep.batcher)
            except ServeOverload as exc:
                sheds.append(exc)
                continue
            if nth:
                self._m_cascades.inc()
            self._update_depth()
            return req
        self._update_depth()
        raise ServeOverload(
            "all %d replicas shedding (%s)" %
            (len(ranked), sheds[-1]),
            retry_after=min(exc.retry_after for exc in sheds))

    def submit(self, sample):
        return self._submit(lambda batcher: batcher.submit(sample))

    def submit_block(self, block):
        return self._submit(
            lambda batcher: batcher.submit_block(block))

    def infer(self, sample, timeout=30.0):
        """Blocking submit through the router (single sample)."""
        return self._wait(self.submit(sample), timeout)

    def infer_block(self, block, timeout=30.0):
        """Blocking whole-batch submit (the binary transport's path):
        one request, zero row copies, result is the 2-D block."""
        return self._wait(self.submit_block(block), timeout)

    @staticmethod
    def _wait(req, timeout):
        if not req.done.wait(timeout):
            raise TimeoutError("inference timed out after %.1fs"
                               % timeout)
        if req.error is not None:
            raise req.error
        return req.result

    # -- snapshot hot-reload ------------------------------------------------

    def reload(self, params, plans=None, sample_shape=None,
               ladder=None):
        """Swap the served model under load; returns the reload receipt.

        Same digest (retrained weights, identical architecture): each
        replica's device buffers are rebuilt and swapped in atomically
        — ZERO new backend compiles, receipted via ``compile_delta``
        (the acceptance assertion of docs/serving.md).  New digest (or
        a ladder change): a full new engine per replica is AOT-warmed
        here — off the dispatch path, requests keep batching on the old
        engines — then cut over between batches.  Either way no queued
        request is dropped or failed by the reload itself."""
        with self._reload_lock:
            receipt = reload_replicas(
                self.replicas, params, plans=plans,
                sample_shape=sample_shape, ladder=ladder,
                engine_kwargs=self._engine_kwargs)
            self.info(
                "hot reload (%s): %s -> %s in %.2fs, %d new compiles",
                receipt["mode"], receipt["previous_digest"],
                receipt["digest"], receipt["seconds"],
                receipt["new_compiles"])
            return receipt

    def reload_workflow(self, sw):
        """Reload from a (re)trained workflow / restored snapshot."""
        try:
            plans, params, shape = self._workflow_spec(sw)
        except ValueError:
            plans, params, shape = self._workflow_spec(
                sw, self.engine.sample_shape)
        return self.reload(params, plans=plans, sample_shape=shape)

    # -- observability ------------------------------------------------------

    def snapshot(self):
        """Plain-data pool state for /healthz and the dashboard."""
        return {
            "replicas": len(self.replicas),
            "digest": self.digest,
            "queue_depths": [rep.batcher._q.qsize()
                             for rep in self.replicas],
            "devices": [str(getattr(rep.device, "backend_name", "?"))
                        + ":%d" % getattr(rep.device, "device_index", 0)
                        for rep in self.replicas],
        }
