"""Replica pool + request router: one AOT engine per chip.

The single-process serve stack (PR 7) has exactly one engine and one
batcher — fine for one chip, a hard ceiling for "millions of users".
The TensorFlow paper's serving recipe (PAPERS.md) is to replicate the
compiled function across devices behind one request stream; the TPU
in-datacenter paper adds the constraint: per-chip throughput under a
latency budget is the number that matters.  So the scale-out unit here
is a **replica** — an :class:`AOTEngine` compiled against one visible
device plus its own :class:`ContinuousBatcher` worker — and the
:class:`ReplicaPool` is the sharded front:

- **placement**: one replica per ``jax.local_devices()`` entry by
  default (``replicas=`` overrides; the CPU harness cycles devices),
  every engine keyed to the SAME model digest so the persistent
  compile cache makes a warm fleet restart compile NOTHING — the cold
  fleet start is the only one that pays, and pays per device because
  jax's cache key includes the device assignment;
- **routing**: each request goes to the least-loaded replica (queue
  depth at submit); an overloaded replica cascades the request to its
  siblings before the pool sheds with a 503-shaped
  :class:`ServeOverload` whose ``retry_after`` is the fleet's best
  offer;
- **observability**: per-replica ``serve.replica.N.*`` gauges next to
  the process-shared serve counters/histograms (which therefore
  aggregate across replicas by construction), ``serve.replicas`` and
  the aggregate ``serve.queue_depth`` for heartbeats/web-status, and
  per-replica ``serve.batch`` spans (the batcher worker threads give
  each replica its own track in merged traces);
- **snapshot hot-reload** (:meth:`ReplicaPool.reload`): a same-digest
  snapshot swaps device weight buffers in place — zero recompiles,
  receipted via ``xla_introspect.compile_delta`` — while a changed
  digest AOT-warms a full new ladder per replica in the background and
  cuts over atomically between batches; either way the queue is never
  dropped.

One pool scales across one host's chips.  The next rung up is
:mod:`veles_tpu.serve.fleet`: a :class:`FleetRouter` front spanning
many serve HOSTS — each one of these pools behind its binary
transport — with the same least-loaded + cascade-then-503 semantics
lifted to host granularity, plus membership epochs and request
hedging (docs/serving.md "Multi-host tier").
"""

import threading
import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.serve.batcher import ContinuousBatcher, ServeOverload
from veles_tpu.serve.engine import (
    AOTEngine, DEFAULT_LADDER, engine_digest_extra, model_digest,
    publish_quantized_state)

__all__ = ["CanaryCutover", "Replica", "ReplicaPool", "local_devices",
           "reload_replicas"]


def local_devices(count=None):
    """Device handles for a replica fleet: one :class:`backends.Device`
    per visible jax device, cycled when ``count`` asks for more
    replicas than devices (the CPU harness measures router/transport
    scaling with several replicas on one host)."""
    import jax

    from veles_tpu.backends import Device
    jax_devices = jax.local_devices()
    backend = "cpu" if jax_devices[0].platform == "cpu" else "tpu"
    n = int(count) if count else len(jax_devices)
    if n < 1:
        raise ValueError("need at least one replica")
    return [Device(backend=backend,
                   device_index=i % len(jax_devices))
            for i in range(n)]


class Replica(object):
    """One engine+batcher pair bound to one device."""

    __slots__ = ("index", "device", "engine", "batcher", "canary")

    def __init__(self, index, device, engine, batcher):
        self.index = index
        self.device = device
        self.engine = engine
        self.batcher = batcher
        #: True while this replica serves a CANDIDATE digest under
        #: canary cutover (docs/serving.md "Freshness loop"): pulled
        #: from live rotation — never a routing pick, never a cascade
        #: target — and fed only mirrored shadow traffic
        self.canary = False


def reload_replicas(replicas, params, plans=None, sample_shape=None,
                    ladder=None, engine_kwargs=None):
    """The ONE hot-reload state machine, shared by :class:`ReplicaPool`
    and the single-engine :class:`ServeService` (a list of one
    Replica-shaped entry).  Callers hold their own reload lock.

    Same digest: each entry's weights swap in place via
    ``AOTEngine.swap_params`` — zero new backend compiles, receipted
    via ``compile_delta``.  New digest (or ladder change): a full new
    engine per entry is AOT-warmed HERE, off the dispatch path, then
    each batcher cuts over between batches.  Returns the receipt."""
    from veles_tpu.observe import xla_introspect
    current = replicas[0].engine
    new_plans = list(plans) if plans is not None else current.plans
    new_shape = tuple(sample_shape) if sample_shape is not None \
        else current.sample_shape
    params = [dict(entry) for entry in params]
    # the engines' own digest recipe, input dtype included — a reload
    # that changes only the arithmetic level (f32 -> int8 spec) must
    # compare as a DIFFERENT digest and take the new-engine road
    new_digest = model_digest(new_plans, params, new_shape,
                              extra=engine_digest_extra(current.dtype))
    same = (new_digest == current.digest and
            (ladder is None or
             tuple(sorted({int(b) for b in ladder})) == current.ladder))
    mode = "params" if same else "engine"
    start = time.perf_counter()
    with _tracer.span("serve.reload", cat="serve", mode=mode,
                      digest=new_digest):
        with xla_introspect.compile_delta() as delta:
            if same:
                for rep in replicas:
                    rep.engine.swap_params(params)
            else:
                kwargs = dict(engine_kwargs or {})
                if ladder is not None:
                    kwargs["ladder"] = ladder
                fresh = []
                for rep in replicas:
                    engine = AOTEngine(new_plans, params, new_shape,
                                       device=rep.device, **kwargs)
                    engine.compile()
                    fresh.append(engine)
                # warm-up done: atomic cutover, oldest first
                for rep, engine in zip(replicas, fresh):
                    rep.batcher.swap_engine(engine)
                    rep.engine = engine
    receipt = dict(
        delta.receipt, mode=mode, digest=new_digest,
        previous_digest=current.digest, replicas=len(replicas),
        seconds=round(time.perf_counter() - start, 4))
    _registry.counter("serve.reloads").inc()
    # the fleet's served arithmetic level may have changed (f32 <->
    # int8 reload); the same-digest road compiles nothing, so the
    # flag must be republished here, from what is live now
    publish_quantized_state(replicas[0].engine.quantized)
    return receipt


class CanaryCutover(Logger):
    """The canary state machine of the train-to-serve freshness loop
    (docs/serving.md "Freshness loop"): how a candidate digest enters a
    fleet, earns (or loses) its place, and how the fleet snaps back.

    States: ``idle`` -> ``canary`` (one replica serves the candidate,
    fed only mirrored shadow traffic) -> ``promoting`` (rolling
    between-batches cutover of the live replicas) -> ``idle``; or
    ``canary``/``promoting`` -> ``idle`` via :meth:`rollback`.

    The rollback cost contract: every transition that replaces a
    replica's engine SAVES the previous engine object (still compiled)
    and every same-digest params swap SAVES the previous params list,
    so :meth:`rollback` is swap-backs only — **zero new backend
    compiles by construction**, receipted via
    ``xla_introspect.compile_delta`` and asserted by
    tests/test_freshness.py.  The driving policy (watcher, mirroring
    fraction, comparator verdicts) lives in
    :mod:`veles_tpu.serve.freshness`; this class owns only the fleet
    mechanics."""

    def __init__(self, pool):
        super(CanaryCutover, self).__init__()
        self.pool = pool
        self.state = "idle"
        self.digest = None           # candidate digest under test
        self._canary_index = None
        self._saved_engines = {}     # replica index -> pre-cutover engine
        self._saved_params = {}      # replica index -> pre-swap params
        # the POOL's reload lock, shared on purpose: a cutover
        # transition and a ReplicaPool.reload must be mutually
        # exclusive, or a reload racing begin() could clobber the
        # canary engine mid-judgment and a later rollback would
        # restore a pre-reload engine onto one replica (mixed fleet)
        self._lock = pool._reload_lock
        self._m_promotions = _registry.counter(
            "serve.freshness.promotions")
        self._m_rollbacks = _registry.counter(
            "serve.freshness.rollbacks")

    @property
    def canary_replica(self):
        if self._canary_index is None:
            return None
        return self.pool.replicas[self._canary_index]

    @staticmethod
    def _await_engine(rep, engine, timeout=10.0):
        """Block until ``rep``'s WORKER adopted ``engine``: swaps apply
        between batches, so there is a window where the replica still
        serves the previous one.  The state machine must not treat a
        swap as done inside that window — a shadow mirrored before the
        canary engine lands would be scored against the OLD model, and
        a rolled-back replica rejoining rotation early would serve the
        REJECTED model to real clients.  (The idle worker applies a
        pending swap within its 0.2s queue poll.)"""
        deadline = time.monotonic() + timeout
        while rep.batcher.engine is not engine and \
                rep.batcher.running and time.monotonic() < deadline:
            time.sleep(0.01)
        return rep.batcher.engine is engine

    def begin(self, engine):
        """Enter ``canary``: the highest-index live replica swaps to
        the (already COMPILED) candidate ``engine`` between batches and
        leaves live rotation.  Replica 0 stays live on purpose — it is
        the pool's metadata anchor."""
        with self._lock:
            if self.state != "idle":
                raise RuntimeError(
                    "canary cutover already in state %r" % self.state)
            if engine.compile_receipt is None:
                raise RuntimeError(
                    "begin() needs a COMPILED candidate engine (warm "
                    "it off the dispatch path first)")
            live = self.pool._live()
            if len(live) < 2:
                raise RuntimeError(
                    "canary cutover needs >= 2 live replicas (one "
                    "keeps serving while one tests the candidate); "
                    "use ReplicaPool.reload for a single-replica fleet")
            rep = live[-1]
            self._saved_engines = {rep.index: rep.engine}
            self._saved_params = {}
            self._canary_index = rep.index
            saved = self._saved_engines[rep.index]
            rep.canary = True
            # drain BEFORE posting the swap: the replica is out of
            # rotation now (no new routed arrivals), but requests
            # already queued were promised the LIVE model — the worker
            # applies a pending engine at the top of its loop, ahead
            # of the queue, so swapping first would answer them with
            # the unjudged candidate
            deadline = time.monotonic() + 10.0
            while (rep.batcher._q.qsize() or
                   rep.batcher._carry is not None) and \
                    time.monotonic() < deadline:
                time.sleep(0.01)  # _carry holds a popped live request
            if rep.batcher._q.qsize() or \
                    rep.batcher._carry is not None:
                rep.canary = False
                self._saved_engines = {}
                self._canary_index = None
                raise RuntimeError(
                    "canary replica %d queue never drained; aborting "
                    "begin" % rep.index)
            rep.batcher.swap_engine(engine)
            rep.engine = engine
            if not self._await_engine(rep, engine):
                # the worker never adopted the candidate (wedged past
                # the timeout): un-begin — shadows scored against the
                # OLD model would be falsely-clean evidence
                rep.batcher.swap_engine(saved)
                rep.engine = saved
                rep.canary = False
                self._saved_engines = {}
                self._canary_index = None
                raise RuntimeError(
                    "canary replica %d did not adopt the candidate "
                    "engine within the swap window; aborting begin" %
                    rep.index)
            self.digest = engine.digest
            self.state = "canary"
            _tracer.instant("serve.canary", cat="serve", phase="begin",
                            replica=rep.index, digest=engine.digest)
            self.info("canary begun on replica %d: candidate digest %s",
                      rep.index, engine.digest)
            return rep

    def shadow(self, sample, trace=None):
        """Mirror one sample to the canary replica (best-effort; see
        ``ContinuousBatcher.submit_shadow``).  Returns the shadow
        request or None.  Deliberately LOCK-FREE (atomic attribute
        reads only): promote/rollback hold the state lock across
        engine compiles, and a client thread mirroring through here
        must never stall behind them — at worst a shadow lands just as
        a verdict executes, and shadows are discardable by design.
        ``trace`` tags the mirror with the PRIMARY request's trace id
        so a merged timeline shows the shadow leg, while the shadow
        flag keeps it out of tail exemplars and served counters."""
        rep = self.canary_replica if self.state == "canary" else None
        if rep is None:
            return None
        return rep.batcher.submit_shadow(sample, trace=trace)

    def promote(self):
        """Candidate judged healthy: roll it fleet-wide.  Live replicas
        already on the candidate's DIGEST swap params in place (zero
        recompiles); a digest change AOT-warms a fresh engine per
        replica off the dispatch path, then cuts over between batches —
        rolling, one replica at a time, so the fleet never has fewer
        than N-1 replicas serving.  The canary replica rejoins rotation
        last.  Returns the promotion receipt."""
        from veles_tpu.observe import xla_introspect
        with self._lock:
            if self.state != "canary":
                raise RuntimeError(
                    "promote() from state %r (need 'canary')" %
                    self.state)
            self.state = "promoting"
            pool = self.pool
            canary = self.canary_replica
            candidate = canary.engine
            start = time.perf_counter()
            try:
                with _tracer.span("serve.canary.promote", cat="serve",
                                  digest=candidate.digest):
                    with xla_introspect.compile_delta() as delta:
                        for rep in pool.replicas:
                            if rep.index == self._canary_index:
                                continue
                            if rep.engine.digest == candidate.digest:
                                # same architecture: the previous params
                                # reference is the rollback asset; the
                                # swap is synchronous (atomic buffer-
                                # list assignment), no adoption wait
                                self._saved_params.setdefault(
                                    rep.index, rep.engine.params)
                                rep.engine.swap_params(candidate.params)
                            else:
                                engine = AOTEngine(
                                    candidate.plans, candidate.params,
                                    candidate.sample_shape,
                                    device=rep.device,
                                    **dict(pool._engine_kwargs,
                                           ladder=candidate.ladder))
                                engine.compile()
                                self._saved_engines[rep.index] = \
                                    rep.engine
                                rep.batcher.swap_engine(engine)
                                rep.engine = engine
                                # symmetric with rollback: a wedged
                                # worker still serving the OLD model
                                # behind a "promoted" receipt would be
                                # an invisible mixed fleet
                                if not self._await_engine(rep, engine):
                                    raise RuntimeError(
                                        "replica %d never adopted the "
                                        "promoted engine" % rep.index)
            except Exception:
                # a failed mid-roll promotion must not strand a mixed
                # fleet: snap every already-cut replica back
                self.exception(
                    "promotion of %s failed mid-roll; rolling back",
                    candidate.digest)
                self.rollback(reason="promotion failed")
                raise
            canary.canary = False
            self._canary_index = None
            self._saved_engines = {}
            self._saved_params = {}
            self.digest = None
            self.state = "idle"
            self._m_promotions.inc()
            # the fleet now serves the candidate's arithmetic level
            publish_quantized_state(pool.engine.quantized)
            receipt = dict(
                delta.receipt, verdict="promoted",
                digest=candidate.digest, replicas=len(pool.replicas),
                seconds=round(time.perf_counter() - start, 4))
            _tracer.instant("serve.canary", cat="serve",
                            phase="promoted", digest=candidate.digest)
            self.info("canary PROMOTED fleet-wide: %s (%d new compiles, "
                      "%.2fs)", candidate.digest,
                      receipt["new_compiles"], receipt["seconds"])
            return receipt

    def rollback(self, reason=""):
        """Candidate judged bad (or promotion failed): restore the
        last-good digest everywhere it was displaced.  Swap-backs only
        — the saved engines are already compiled and saved params swap
        in place — so the receipt's ``new_compiles`` is 0 by
        construction (the acceptance assertion of the freshness
        soak)."""
        from veles_tpu.observe import xla_introspect
        with self._lock:
            if self.state not in ("canary", "promoting"):
                raise RuntimeError(
                    "rollback() from state %r (need 'canary' or "
                    "'promoting')" % self.state)
            pool = self.pool
            bad = self.digest
            start = time.perf_counter()
            with xla_introspect.compile_delta() as delta:
                for index, engine in self._saved_engines.items():
                    rep = pool.replicas[index]
                    rep.batcher.swap_engine(engine)
                    rep.engine = engine
                for index, params in self._saved_params.items():
                    pool.replicas[index].engine.swap_params(params)
            # the restored engines must be LIVE in their workers before
            # any replica rejoins rotation: a client request served by
            # the rejected candidate after "rollback" would make the
            # canary contract a lie.  A replica whose worker never
            # adopts (wedged past the timeout) STAYS out of rotation —
            # quarantined-by-flag — rather than rejoining with the
            # rejected engine still live
            unadopted = []
            for index, engine in self._saved_engines.items():
                if not self._await_engine(pool.replicas[index], engine):
                    unadopted.append(index)
            canary = self.canary_replica
            if canary is not None and canary.index not in unadopted:
                canary.canary = False
            for index in unadopted:
                pool.replicas[index].canary = True
                self.error(
                    "replica %d never adopted the restored engine; "
                    "LEAVING it out of live rotation (restart or "
                    "reload to recover it)", index)
            self._canary_index = None
            self._saved_engines = {}
            self._saved_params = {}
            self.digest = None
            self.state = "idle"
            self._m_rollbacks.inc()
            # rollback is swap-backs only (0 compiles by construction)
            # so nothing recompiled to republish the level: a rejected
            # quantized candidate's warm-up flipped the process-global
            # flag/MFU ceiling, and the restored fleet must flip it
            # back (regression: tests/test_quant.py)
            publish_quantized_state(pool.engine.quantized)
            receipt = dict(
                delta.receipt, verdict="rolled_back", digest=bad,
                restored_digest=pool.digest, reason=reason,
                seconds=round(time.perf_counter() - start, 4))
            if unadopted:
                receipt["unadopted_replicas"] = unadopted
            _tracer.instant("serve.canary", cat="serve",
                            phase="rolled_back", digest=bad,
                            reason=reason)
            self.warning(
                "canary ROLLED BACK: candidate %s rejected (%s); fleet "
                "restored to %s with %d new compiles", bad,
                reason or "unspecified", receipt["restored_digest"],
                receipt["new_compiles"])
            return receipt

    def snapshot(self):
        """Plain-data state for /healthz and the dashboard.  Lock-free
        like :meth:`shadow` — the IO loop must never wait out a
        promotion's compiles for a health read."""
        out = {"state": self.state}
        digest, index = self.digest, self._canary_index
        if digest is not None:
            out["candidate_digest"] = digest
        if index is not None:
            out["replica"] = index
        return out


class ReplicaPool(Logger):
    """N per-device serving replicas behind one least-loaded router.

    Duck-types the :class:`ContinuousBatcher` submit surface
    (``submit``/``submit_block``/``infer``/``start``/``stop``), so
    :class:`ServeService` and the binary transport drive a pool and a
    single batcher identically."""

    def __init__(self, plans, params, sample_shape, replicas=None,
                 ladder=DEFAULT_LADDER, devices=None, cache_root=None,
                 persistent_cache=False, dtype=numpy.float32,
                 **batcher_kwargs):
        super(ReplicaPool, self).__init__()
        if devices is None:
            devices = local_devices(replicas)
        elif replicas:
            devices = [devices[i % len(devices)]
                       for i in range(int(replicas))]
        self._engine_kwargs = dict(
            ladder=ladder, cache_root=cache_root,
            persistent_cache=persistent_cache, dtype=dtype)
        self._batcher_kwargs = dict(batcher_kwargs)
        self.replicas = []
        for i, device in enumerate(devices):
            engine = AOTEngine(plans, params, sample_shape,
                               device=device, **self._engine_kwargs)
            batcher = ContinuousBatcher(engine, replica=i,
                                        **self._batcher_kwargs)
            self.replicas.append(Replica(i, device, engine, batcher))
        self.compile_receipt = None
        # RLock: shared with CanaryCutover (see its __init__), whose
        # promote() re-enters via rollback() on a failed mid-roll
        self._reload_lock = threading.RLock()
        #: the canary state machine (docs/serving.md "Freshness loop")
        self.cutover = CanaryCutover(self)
        #: set by the freshness controller while a canary is live:
        #: called as ``hook(sample, primary_request)`` after every
        #: successful single-sample submit so a traffic slice can be
        #: mirrored to the canary replica
        self.mirror_hook = None
        self._m_replicas = _registry.gauge("serve.replicas")
        self._m_replicas.set(len(self.replicas))
        self._m_depth = _registry.gauge("serve.queue_depth")
        self._m_cascades = _registry.counter("serve.router.cascades")

    # -- workflow plumbing --------------------------------------------------

    @staticmethod
    def _workflow_spec(sw, sample_shape=None):
        from veles_tpu.compiler import workflow_plan
        plans = workflow_plan(sw)
        # read params through the HOST side, not extract_state's
        # devmem: a freshly-unpickled snapshot (restore_workflow, the
        # freshness watcher) has no device attached yet, so its Arrays'
        # devmem is None until someone re-initializes the workflow —
        # serving only needs the values, and host numpy is exactly what
        # AOTEngine wants to place per replica device anyway
        params = []
        for fwd in sw.forwards:
            entry = {}
            for key, arr in (("weights", fwd.weights),
                             ("bias", fwd.bias)):
                if arr:
                    arr.map_read()
                    entry[key] = numpy.array(arr.mem, copy=True)
                else:
                    entry[key] = None
            params.append(entry)
        if sample_shape is None:
            loader = getattr(sw, "loader", None)
            if loader is not None and loader.minibatch_data:
                sample_shape = tuple(loader.minibatch_data.shape[1:])
            else:
                raise ValueError("workflow has no loader shape; pass "
                                 "sample_shape=")
        return plans, params, tuple(sample_shape)

    @classmethod
    def from_workflow(cls, sw, **kwargs):
        """Build a pool from a trained StandardWorkflow, exactly like
        ``AOTEngine.from_workflow`` but fanned out per device."""
        plans, params, sample_shape = cls._workflow_spec(
            sw, kwargs.pop("sample_shape", None))
        return cls(plans, params, sample_shape, **kwargs)

    # -- lifecycle ----------------------------------------------------------

    @property
    def engine(self):
        """The first LIVE replica's engine: the pool's metadata anchor
        (digest, ladder, sample shape, dtype) — LIVE across hot reloads
        and canary cutovers (a replica testing a candidate digest must
        not change what /healthz says the fleet serves)."""
        for rep in self.replicas:
            if not rep.canary:
                return rep.engine
        return self.replicas[0].engine

    @property
    def digest(self):
        return self.engine.digest

    def compile(self):
        """Compile every replica's ladder; returns the aggregate
        receipt.  All replicas share the ONE digest-keyed persistent
        cache directory; jax's cache key includes the device
        assignment, so a cold fleet start writes one entry set per
        device — and a warm fleet RESTART deserializes every one of
        them: ``new_compiles == 0`` across all N replicas, asserted by
        tests/test_serve_router.py."""
        start = time.perf_counter()
        per = [rep.engine.compile() for rep in self.replicas]
        self.compile_receipt = {
            "replicas": len(per),
            "rungs": per[0]["rungs"],
            "backend_compiles": sum(
                r["backend_compiles"] for r in per),
            "cache_hits": sum(r["cache_hits"] for r in per),
            "new_compiles": sum(r["new_compiles"] for r in per),
            "seconds": round(time.perf_counter() - start, 4),
            "cache_dir": per[0]["cache_dir"],
            "per_replica": per,
        }
        return self.compile_receipt

    @property
    def running(self):
        return any(rep.batcher.running for rep in self.replicas)

    def start(self):
        for rep in self.replicas:
            rep.batcher.start()
        return self

    def stop(self):
        for rep in self.replicas:
            rep.batcher.stop()
        self._m_depth.set(0)

    def set_host_tag(self, tag):
        """Propagate the serving host's fleet identity to every
        replica's batcher, so request-scoped spans emitted here carry
        ``host=<tag>`` — two in-process hosts of one test fleet stay
        attributable after their traces are merged."""
        for rep in self.replicas:
            rep.batcher.set_host_tag(tag)

    # -- routing ------------------------------------------------------------

    def _update_depth(self):
        self._m_depth.set(sum(rep.batcher._q.qsize()
                              for rep in self.replicas))

    def _live(self):
        """Replicas in live rotation.  A canary replica is excluded
        from the routing pick AND from the overload cascade — mirrored
        shadow traffic is its only diet, so overflow landing there
        would both overload the measurement and serve real clients
        from an unjudged candidate — and the fleet's 503 retry_after
        is computed over the replicas that will actually serve the
        retry.  Falls back to all replicas if (impossibly) every one
        is canary."""
        live = [rep for rep in self.replicas if not rep.canary]
        return live or self.replicas

    def _submit(self, fn):
        """Least-queue-depth pick with overload cascade: try LIVE
        replicas in depth order; only when every live replica sheds
        does the pool itself shed, with the smallest retry_after any
        live replica offered (the fleet's best promise, not its
        worst)."""
        for _ in range(3):
            ranked = sorted(self._live(),
                            key=lambda rep: rep.batcher._q.qsize())
            sheds = []
            for nth, rep in enumerate(ranked):
                try:
                    req = fn(rep.batcher)
                except ServeOverload as exc:
                    sheds.append(exc)
                    continue
                if rep.canary:
                    # lost the race with CanaryCutover.begin(): the
                    # pick was live at ranking time but the replica
                    # turned canary before the enqueue landed — that
                    # request would be answered by the unjudged
                    # candidate.  Cancel it (the worker drops
                    # cancelled requests at dispatch) and re-route.
                    req.cancelled = True
                    continue
                if nth:
                    self._m_cascades.inc()
                self._update_depth()
                return req
            self._update_depth()
            if sheds:
                raise ServeOverload(
                    "all %d live replicas shedding (%s)" %
                    (len(ranked), sheds[-1]),
                    retry_after=min(exc.retry_after
                                    for exc in sheds))
            # every pick raced a cutover transition: re-rank and retry
        raise ServeOverload("fleet reconfiguring", retry_after=0.1)

    def submit(self, sample, slo_class=None, trace=None):
        req = self._submit(
            lambda batcher: batcher.submit(sample, slo_class=slo_class,
                                           trace=trace))
        hook = self.mirror_hook
        if hook is not None:
            try:
                hook(sample, req)
            except Exception:
                # mirroring is an observation: it must never fail (or
                # slow) the request it observes
                self.exception("canary mirror hook failed")
        return req

    def submit_block(self, block, slo_class=None, trace=None):
        return self._submit(
            lambda batcher: batcher.submit_block(
                block, slo_class=slo_class, trace=trace))

    def infer(self, sample, timeout=30.0, slo_class=None, trace=None):
        """Blocking submit through the router (single sample)."""
        return self._wait(
            self.submit(sample, slo_class=slo_class, trace=trace),
            timeout)

    def infer_block(self, block, timeout=30.0, slo_class=None,
                    trace=None):
        """Blocking whole-batch submit (the binary transport's path):
        one request, zero row copies, result is the 2-D block."""
        return self._wait(
            self.submit_block(block, slo_class=slo_class, trace=trace),
            timeout)

    @staticmethod
    def _wait(req, timeout):
        if not req.done.wait(timeout):
            raise TimeoutError("inference timed out after %.1fs"
                               % timeout)
        if req.error is not None:
            raise req.error
        return req.result

    # -- snapshot hot-reload ------------------------------------------------

    def reload(self, params, plans=None, sample_shape=None,
               ladder=None):
        """Swap the served model under load; returns the reload receipt.

        Same digest (retrained weights, identical architecture): each
        replica's device buffers are rebuilt and swapped in atomically
        — ZERO new backend compiles, receipted via ``compile_delta``
        (the acceptance assertion of docs/serving.md).  New digest (or
        a ladder change): a full new engine per replica is AOT-warmed
        here — off the dispatch path, requests keep batching on the old
        engines — then cut over between batches.  Either way no queued
        request is dropped or failed by the reload itself."""
        with self._reload_lock:
            # checked INSIDE the shared lock: cutover transitions hold
            # it too, so the state cannot flip between check and swap
            if self.cutover.state != "idle":
                raise RuntimeError(
                    "hot-reload refused: canary cutover in progress "
                    "(state %r) — promote or roll back first, or "
                    "route new models through the freshness loop" %
                    self.cutover.state)
            receipt = reload_replicas(
                self.replicas, params, plans=plans,
                sample_shape=sample_shape, ladder=ladder,
                engine_kwargs=self._engine_kwargs)
            # a full-fleet reload re-homogenizes every replica, so a
            # rollback-quarantined one (canary flag left True because
            # its worker never adopted the restored engine) is
            # recovered here — the quarantine error message promises
            # exactly this
            for rep in self.replicas:
                rep.canary = False
            self.info(
                "hot reload (%s): %s -> %s in %.2fs, %d new compiles",
                receipt["mode"], receipt["previous_digest"],
                receipt["digest"], receipt["seconds"],
                receipt["new_compiles"])
            return receipt

    def reload_workflow(self, sw):
        """Reload from a (re)trained workflow / restored snapshot."""
        try:
            plans, params, shape = self._workflow_spec(sw)
        except ValueError:
            plans, params, shape = self._workflow_spec(
                sw, self.engine.sample_shape)
        return self.reload(params, plans=plans, sample_shape=shape)

    # -- observability ------------------------------------------------------

    def snapshot(self):
        """Plain-data pool state for /healthz and the dashboard."""
        out = {
            "replicas": len(self.replicas),
            "digest": self.digest,
            "queue_depths": [rep.batcher._q.qsize()
                             for rep in self.replicas],
            "devices": [str(getattr(rep.device, "backend_name", "?"))
                        + ":%d" % getattr(rep.device, "device_index", 0)
                        for rep in self.replicas],
        }
        if self.cutover.state != "idle":
            out["canary"] = self.cutover.snapshot()
        # single-host serving evaluates the process-global alert
        # manager (heartbeat cadence — observe/profile.py); surface
        # what is burning next to the queue depths it burns about
        from veles_tpu.observe.alerts import alerts
        active = alerts.active()
        if alerts.rules or active:
            out["alerts_active"] = sorted(r["alert"] for r in active)
        return out
