"""Production inference serving (docs/serving.md).

The libVeles role of the reference — a standalone, load-and-run
inference runtime — rebuilt TPU-idiomatically in three layers:

- :mod:`veles_tpu.serve.engine` — :class:`AOTEngine`: ahead-of-time
  compiled executables over a ladder of padded batch shapes, backed by
  a persistent, model-digest-keyed XLA compilation cache so a restarted
  server performs 0 new backend compiles (receipt:
  ``engine.compile_receipt`` via the ``compile.count`` /
  ``compile.cache_hits`` counters);
- :mod:`veles_tpu.serve.batcher` — :class:`ContinuousBatcher`: a worker
  thread draining the request queue into the largest fitting rung with
  a bounded queue-delay, ping-pong host staging (the PR 1 machinery),
  load shedding (``ServeOverload`` -> HTTP 503 + retry_after) and
  p50/p99 latency SLO tripwires;
- :mod:`veles_tpu.serve.router` — :class:`ReplicaPool`: one
  engine+batcher replica per visible device behind a least-loaded
  router with overload cascade, shared persistent compile cache (warm
  fleet start = one compile set), and snapshot hot-reload (same digest
  = zero-recompile buffer swap; new digest = background AOT warm-up +
  atomic cutover, queue never dropped);
- :mod:`veles_tpu.serve.transport` — the binary frame listener beside
  the JSON front: ``network_common``'s ``!IIB`` framing + HMAC with a
  fixed dtype/shape/raw-bytes tensor codec (the serve port never
  unpickles) and a same-host :class:`ShmChannel` payload bypass;
- :mod:`veles_tpu.serve.service` — :class:`ServeService`: the tornado
  front (``/infer``, ``/healthz``, ``/metrics.json``, ``/reload``,
  ``/publish``), async handlers so concurrent clients actually
  co-batch;
- :mod:`veles_tpu.serve.freshness` — the train-to-serve freshness
  loop: :class:`SnapshotWatcher` (manifest-verified pickup of the
  trainer's published snapshots), :class:`FreshnessController`
  (finite gate, background warm-up, mirrored canary judgment via
  :class:`CanaryComparator`) over the router's canary state machine —
  promote fleet-wide or auto-roll back to the last-good digest with
  zero new compiles;
- :mod:`veles_tpu.serve.qos` — multi-tenant QoS: SLO classes
  (``interactive`` / ``batch`` / ``best_effort``), per-tenant
  token-bucket admission quotas, class-ordered shedding
  (:data:`~veles_tpu.serve.qos.SHED_ORDER`), per-class hedge budgets
  and the seeded per-class ``retry_after`` jitter — the serve tier
  degrades selectively under overload instead of uniformly;
- :mod:`veles_tpu.serve.fleet` — the multi-host tier:
  :class:`FleetRouter` dispatches over many serve HOSTS (pipelined
  binary links, membership epochs via ``elastic.FleetView``,
  throughput-EMA weighted least-loaded routing with host-granular
  overload cascade) and hedges stragglers — re-dispatch past the
  power-corrected threshold, first result wins, loser cancelled over
  the wire — with exactly-once completion under host loss (a SIGKILL
  mid-stream costs bounded p99, never a failed request).

``python -m veles_tpu.serve --snapshot model.pickle`` serves a trained
snapshot; ``scripts/serve_load.py`` is the closed-loop load generator
behind ``BENCH_serve.json``.
"""

from veles_tpu.serve.qos import (  # noqa: F401
    DEFAULT_CLASS, HedgeBudget, RetryJitter, SHED_ORDER, SLO_CLASSES,
    TenantQuota, TokenBucket, normalize_class, parse_quota_spec)
from veles_tpu.serve.batcher import (  # noqa: F401
    ContinuousBatcher, ServeOverload, serve_snapshot)
from veles_tpu.serve.engine import (  # noqa: F401
    AOTEngine, DEFAULT_LADDER, enable_persistent_cache, model_digest,
    value_digest)
from veles_tpu.serve.fleet import (  # noqa: F401
    FleetRequest, FleetRouter, HostLink)
from veles_tpu.serve.freshness import (  # noqa: F401
    CanaryComparator, FleetCanaryController, FreshnessController,
    LocalHostControl, SnapshotWatcher, export_model_spec)
from veles_tpu.serve.router import (  # noqa: F401
    CanaryCutover, Replica, ReplicaPool, local_devices)
from veles_tpu.serve.service import (  # noqa: F401
    ServeService, format_result)
from veles_tpu.serve.transport import (  # noqa: F401
    BinaryTransportClient, BinaryTransportServer, decode_tensor,
    encode_tensor)

__all__ = ["AOTEngine", "BinaryTransportClient",
           "BinaryTransportServer", "CanaryComparator",
           "CanaryCutover", "ContinuousBatcher", "FleetCanaryController",
           "FleetRequest", "FleetRouter", "FreshnessController",
           "HedgeBudget", "HostLink", "LocalHostControl", "Replica",
           "ReplicaPool", "RetryJitter", "ServeOverload",
           "ServeService", "SnapshotWatcher", "TenantQuota",
           "TokenBucket", "DEFAULT_CLASS", "DEFAULT_LADDER",
           "SHED_ORDER", "SLO_CLASSES", "decode_tensor",
           "enable_persistent_cache", "encode_tensor",
           "export_model_spec", "format_result", "local_devices",
           "model_digest", "normalize_class", "parse_quota_spec",
           "serve_snapshot", "value_digest"]
