"""Production inference serving (docs/serving.md).

The libVeles role of the reference — a standalone, load-and-run
inference runtime — rebuilt TPU-idiomatically in three layers:

- :mod:`veles_tpu.serve.engine` — :class:`AOTEngine`: ahead-of-time
  compiled executables over a ladder of padded batch shapes, backed by
  a persistent, model-digest-keyed XLA compilation cache so a restarted
  server performs 0 new backend compiles (receipt:
  ``engine.compile_receipt`` via the ``compile.count`` /
  ``compile.cache_hits`` counters);
- :mod:`veles_tpu.serve.batcher` — :class:`ContinuousBatcher`: a worker
  thread draining the request queue into the largest fitting rung with
  a bounded queue-delay, ping-pong host staging (the PR 1 machinery),
  load shedding (``ServeOverload`` -> HTTP 503 + retry_after) and
  p50/p99 latency SLO tripwires;
- :mod:`veles_tpu.serve.service` — :class:`ServeService`: the tornado
  front (``/infer``, ``/healthz``, ``/metrics.json``), async handlers
  so concurrent clients actually co-batch.

``python -m veles_tpu.serve --snapshot model.pickle`` serves a trained
snapshot; ``scripts/serve_load.py`` is the closed-loop load generator
behind ``BENCH_serve.json``.
"""

from veles_tpu.serve.batcher import (  # noqa: F401
    ContinuousBatcher, ServeOverload, serve_snapshot)
from veles_tpu.serve.engine import (  # noqa: F401
    AOTEngine, DEFAULT_LADDER, enable_persistent_cache, model_digest)
from veles_tpu.serve.service import (  # noqa: F401
    ServeService, format_result)

__all__ = ["AOTEngine", "ContinuousBatcher", "ServeOverload",
           "ServeService", "DEFAULT_LADDER", "enable_persistent_cache",
           "format_result", "model_digest", "serve_snapshot"]
