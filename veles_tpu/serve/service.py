"""HTTP front for the serving subsystem.

``POST <path> {"input": sample-or-batch}`` answers like the original
``RESTfulAPI`` contract (``{"result": label(s), "probabilities":
[...]}``) but the handler is *async*: the tornado IO loop hands the
blocking batcher wait to a thread pool and keeps accepting requests,
so concurrent clients actually co-batch — a synchronous handler would
serialize the queue and continuous batching could never see more than
one request at a time.

Besides inference the service exposes the operational surface:

- ``GET /healthz`` — the serve health block (queue depth, SLO
  violations, latency percentiles), the engine's compile receipt and
  the model digest; what a load balancer or the web-status dashboard
  polls;
- ``GET /metrics.json`` — the full metrics-registry snapshot.

Overload answers ``503`` with a ``retry_after`` hint (the blacklist
protocol's shape); per-request wall time lands in the ``http.request_s``
histogram and a per-request ``serve.request`` span via the
``http_util.RequestTimer`` mixin (perf_counter, not tornado's
``time.time``-based ``request_time``).
"""

import json
import threading

import numpy

from veles_tpu.http_util import BackgroundHTTPServer, RequestTimer
from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.serve.batcher import ContinuousBatcher, ServeOverload
from veles_tpu.serve.batcher import serve_snapshot

__all__ = ["ServeService", "format_result"]


def format_result(probs, labels_mapping=None):
    """Shape a probability block into the REST response contract:
    argmax label(s) mapped through the loader's reverse mapping, plus
    the raw probabilities."""
    probs = numpy.asarray(probs)
    single = probs.ndim == 1
    block = probs[None] if single else probs
    labels = block.argmax(axis=1)
    mapping = labels_mapping or {}
    named = [mapping.get(int(l), int(l)) for l in labels]
    return {"result": named[0] if single or len(named) == 1 else named,
            "probabilities": block.tolist()}


class ServeService(Logger):
    """Tornado service over an :class:`AOTEngine` + batcher.

    ``batcher`` may be shared (the RESTful unit passes its own); when
    None one is built from ``batcher_kwargs`` and owned (started and
    stopped with the service)."""

    def __init__(self, engine, batcher=None, port=0, path="/infer",
                 labels_mapping=None, executor_workers=64,
                 **batcher_kwargs):
        super(ServeService, self).__init__()
        self.engine = engine
        self._owns_batcher = batcher is None
        self.batcher = batcher if batcher is not None else \
            ContinuousBatcher(engine, **batcher_kwargs)
        self.path = path
        self.labels_mapping = labels_mapping or {}
        self.samples_served = 0
        self._served_lock = threading.Lock()
        self._executor = None
        self._executor_workers = int(executor_workers)
        self._server = None
        self._port = port

    @property
    def port(self):
        return self._server.port if self._server is not None \
            else self._port

    # -- request handling (executor thread) ---------------------------------

    def infer_payload(self, sample):
        """Blocking inference for one payload: a single sample or a
        batch.  Batch payloads are submitted row-by-row, so their rows
        co-batch with every other in-flight request — a large payload
        does not monopolize a rung.  A payload that sheds partway
        through submission cancels its already-queued rows (the worker
        drops them at dispatch) so a 503'd request never leaves orphan
        work computing for nobody."""
        x = numpy.asarray(sample, self.engine.dtype)
        if x.shape == self.engine.sample_shape:
            x = x[None]
        requests = []
        try:
            for row in x:
                requests.append(self.batcher.submit(row))
        except Exception:
            for req in requests:
                req.cancelled = True
            raise
        probs = []
        for req in requests:
            if not req.done.wait(30.0):
                raise TimeoutError("inference timed out")
            if req.error is not None:
                raise req.error
            probs.append(req.result)
        with self._served_lock:
            self.samples_served += len(probs)
        return format_result(numpy.stack(probs), self.labels_mapping)

    # -- HTTP ---------------------------------------------------------------

    def _make_app(self):
        import tornado.web

        svc = self

        class InferHandler(RequestTimer, tornado.web.RequestHandler):
            async def post(self):
                import asyncio
                try:
                    body = json.loads(self.request.body)
                    payload = body["input"]
                except Exception as exc:
                    self.set_status(400)
                    self.write({"error": "bad request: %s" % exc})
                    return
                loop = asyncio.get_event_loop()
                try:
                    answer = await loop.run_in_executor(
                        svc._executor, svc.infer_payload, payload)
                except ServeOverload as exc:
                    # the blacklist protocol's transient-reject shape
                    self.set_status(503)
                    self.set_header("Retry-After",
                                    "%.3f" % exc.retry_after)
                    self.write({"error": str(exc),
                                "retry_after": exc.retry_after})
                except (ValueError, TypeError) as exc:
                    self.set_status(400)
                    self.write({"error": str(exc)})
                except Exception as exc:
                    self.set_status(500)
                    self.write({"error": str(exc)})
                else:
                    self.write(answer)

        class HealthHandler(RequestTimer, tornado.web.RequestHandler):
            def get(self):
                self.write({
                    "status": "ok",
                    "model_digest": svc.engine.digest,
                    "ladder": list(svc.engine.ladder),
                    "compile": svc.engine.compile_receipt,
                    "serve": serve_snapshot(),
                })

        class MetricsHandler(RequestTimer, tornado.web.RequestHandler):
            def get(self):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(_registry.snapshot(),
                                      default=repr))

        return tornado.web.Application([
            (self.path, InferHandler),
            (r"/healthz", HealthHandler),
            (r"/metrics.json", MetricsHandler),
        ])

    def start_background(self):
        from concurrent.futures import ThreadPoolExecutor
        # waiting requests only block on an Event, so workers are
        # cheap; the pool bounds in-flight HTTP requests, the batcher's
        # max_queue bounds admitted ones
        self._executor = ThreadPoolExecutor(
            max_workers=self._executor_workers,
            thread_name_prefix="serve-http")
        if self._owns_batcher:
            self.batcher.start()
        self._server = BackgroundHTTPServer(self._make_app(),
                                            port=self._port)
        thread = self._server.start()
        self.info("serve endpoint on http://127.0.0.1:%d%s "
                  "(healthz, metrics.json)", self.port, self.path)
        return thread

    def stop(self):
        # order matters: close the listener (no new work), fail the
        # batcher's pending requests (unblocks executor tasks), THEN
        # join the executor so no worker thread outlives the service
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._owns_batcher:
            self.batcher.stop()
        if self._executor is not None:
            self._executor.shutdown(wait=self._owns_batcher)
            self._executor = None
