"""HTTP front for the serving subsystem.

``POST <path> {"input": sample-or-batch}`` answers like the original
``RESTfulAPI`` contract (``{"result": label(s), "probabilities":
[...]}``) but the handler is *async*: the tornado IO loop hands the
blocking batcher wait to a thread pool and keeps accepting requests,
so concurrent clients actually co-batch — a synchronous handler would
serialize the queue and continuous batching could never see more than
one request at a time.

Besides inference the service exposes the operational surface:

- ``GET /healthz`` — the serve health block (queue depth, SLO
  violations, latency percentiles), the engine's compile receipt and
  the model digest; what a load balancer or the web-status dashboard
  polls;
- ``GET /metrics.json`` — the full metrics-registry snapshot.

Overload answers ``503`` with a ``retry_after`` hint (the blacklist
protocol's shape); per-request wall time lands in the ``http.request_s``
histogram and a per-request ``serve.request`` span via the
``http_util.RequestTimer`` mixin (perf_counter, not tornado's
``time.time``-based ``request_time``).
"""

import json
import threading
import time

import numpy

from veles_tpu.http_util import BackgroundHTTPServer, RequestTimer
from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.serve import qos
from veles_tpu.serve.batcher import ContinuousBatcher, ServeOverload
from veles_tpu.serve.batcher import serve_snapshot

__all__ = ["ServeService", "format_result"]


def format_result(probs, labels_mapping=None):
    """Shape a probability block into the REST response contract:
    argmax label(s) mapped through the loader's reverse mapping, plus
    the raw probabilities.

    Vectorized once per payload: ``probs`` arrives as (a view of) the
    batcher's per-batch host buffer — never re-copied here — and the
    float boxing the JSON front must pay happens in exactly ONE
    C-level ``tolist`` over the whole block, not per element through
    ``numpy.asarray`` round-trips per request (the pre-PR-10 shape of
    this function)."""
    if not isinstance(probs, numpy.ndarray):
        probs = numpy.asarray(probs)
    single = probs.ndim == 1
    block = probs[None] if single else probs  # [None] is a view
    labels = block.argmax(axis=1)
    if labels_mapping:
        named = [labels_mapping.get(int(label), int(label))
                 for label in labels]
    else:
        named = labels.tolist()  # one vectorized box, no dict probes
    return {"result": named[0] if single or len(named) == 1 else named,
            "probabilities": block.tolist()}


class ServeService(Logger):
    """Tornado service over an :class:`AOTEngine` + batcher, or a
    whole :class:`ReplicaPool`.

    ``engine`` may be a single AOT engine (``batcher`` optionally
    shared — the RESTful unit passes its own; when None one is built
    from ``batcher_kwargs`` and owned) or a :class:`ReplicaPool`, in
    which case every request rides the pool's least-loaded router and
    ``/healthz`` carries the per-replica state.  ``transport_port``
    additionally opens the binary frame listener
    (:mod:`veles_tpu.serve.transport`) beside the JSON front — same
    batcher/pool, so JSON and binary clients co-batch."""

    def __init__(self, engine, batcher=None, port=0, path="/infer",
                 labels_mapping=None, executor_workers=64,
                 transport_port=None, transport_secret=None,
                 freshness=None, quota=None, retry_jitter=None,
                 **batcher_kwargs):
        super(ServeService, self).__init__()
        #: per-tenant admission quota (qos.TenantQuota), shared with
        #: the binary transport when one is opened; None disables
        #: quota — legacy behavior, nothing is rejected here
        self.quota = quota
        self.retry_jitter = retry_jitter if retry_jitter is not None \
            else qos.RetryJitter()
        from veles_tpu.serve.fleet import FleetRouter
        from veles_tpu.serve.router import ReplicaPool
        self._is_fleet = isinstance(engine, FleetRouter)
        if isinstance(engine, (ReplicaPool, FleetRouter)):
            # a pool of local replicas or a FRONT over remote serve
            # hosts (docs/serving.md "Multi-host tier") — both speak
            # the batcher submit contract, so /infer and the binary
            # transport drive them identically
            self.router = engine
            self._engine = None
            self._owns_batcher = True
            self.batcher = engine  # same submit contract
        else:
            self.router = None
            self._engine = engine
            self._owns_batcher = batcher is None
            self.batcher = batcher if batcher is not None else \
                ContinuousBatcher(engine, **batcher_kwargs)
        self.path = path
        self.labels_mapping = labels_mapping or {}
        self.samples_served = 0
        self.last_reload = None
        self._served_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._executor = None
        self._executor_workers = int(executor_workers)
        self._server = None
        self._port = port
        self._transport = None
        self._transport_port = transport_port
        self._transport_secret = transport_secret
        #: optional FreshnessController (docs/serving.md "Freshness
        #: loop"): referenced, not owned — the caller manages its
        #: lifecycle; the service adds the ``POST /publish`` push
        #: front and the /healthz freshness block
        self.freshness = freshness

    @property
    def engine(self):
        """The (replica 0) engine — LIVE across hot reloads."""
        return self.router.engine if self.router is not None \
            else self._engine

    @property
    def compile_receipt(self):
        source = self.router if self.router is not None else self.engine
        return source.compile_receipt

    @property
    def port(self):
        return self._server.port if self._server is not None \
            else self._port

    @property
    def transport_port(self):
        return self._transport.port if self._transport is not None \
            else self._transport_port

    # -- request handling (executor thread) ---------------------------------

    def infer_payload(self, sample, tenant=None, slo_class=None,
                      trace=None):
        """Blocking inference for one payload: a single sample or a
        batch.  Batch payloads are submitted row-by-row, so their rows
        co-batch with every other in-flight request — a large payload
        does not monopolize a rung.  A payload that sheds partway
        through submission cancels its already-queued rows (the worker
        drops them at dispatch) so a 503'd request never leaves orphan
        work computing for nobody.

        ``tenant``/``slo_class`` are the QoS identity (docs/serving.md
        "Multi-tenant QoS"): the tenant's token-bucket quota is charged
        per SAMPLE here — one admission decision covers the payload —
        and the class labels every row for class-ordered shedding;
        un-labelled legacy payloads serve as class ``batch``.

        ``trace`` is the request trace id (docs/observability.md
        "Request tracing"): a client-supplied id is validated through
        ``normalize_trace_id`` (bounded plain string — the trust
        boundary is unchanged), an absent one is minted here so every
        admitted payload is attributable; all rows of one payload
        share the id.  The answer echoes it as ``"trace"``."""
        from veles_tpu.observe import requests as reqtrace
        slo_class = qos.normalize_class(slo_class)
        if reqtrace.enabled:
            trace = reqtrace.normalize_trace_id(trace) or \
                reqtrace.mint_trace_id()
            t_admit = time.perf_counter()
        else:
            trace = None
            t_admit = None
        x = numpy.asarray(sample, self.engine.dtype)
        if x.shape == self.engine.sample_shape:
            x = x[None]
        if self.quota is not None:
            wait = self.quota.admit(tenant, cost=float(x.shape[0]))
            if wait is not None:
                qos.note_shed(slo_class)
                raise ServeOverload(
                    "tenant %r over quota" % (tenant,),
                    retry_after=self.retry_jitter.apply(
                        max(wait, 0.05), slo_class))
        requests = []
        try:
            for row in x:
                req = self.batcher.submit(row, slo_class=slo_class,
                                          trace=trace)
                if t_admit is not None and \
                        getattr(req, "marks", 0) is None:
                    # front-door admission segment (decode + quota
                    # charge); the worker appends the queue/batch
                    # marks behind it at completion
                    req.marks = [("admit", t_admit,
                                  req.enqueued - t_admit)]
                requests.append(req)
        except Exception:
            for req in requests:
                req.cancelled = True
            raise
        probs = []
        for req in requests:
            if not req.done.wait(30.0):
                raise TimeoutError("inference timed out")
            if req.error is not None:
                raise req.error
            probs.append(req.result)
        with self._served_lock:
            self.samples_served += len(probs)
        # the results are views of per-batch host buffers (no
        # per-request copies anywhere behind us); a single-row payload
        # needs no stack at all — [None] is a view
        block = probs[0][None] if len(probs) == 1 \
            else numpy.stack(probs)
        answer = format_result(block, self.labels_mapping)
        if trace is not None:
            answer["trace"] = trace
        return answer

    # -- snapshot hot-reload ------------------------------------------------

    def reload_snapshot(self, path):
        """Swap the served model for a trained-workflow snapshot (the
        crash-consistent pickles ``snapshotter.py`` writes) WITHOUT
        dropping the queue; returns the reload receipt.  Triggered by
        ``POST /reload {"snapshot": path}`` or SIGHUP (serve CLI)."""
        from veles_tpu.workflow import restore_workflow
        return self.reload_workflow(restore_workflow(path))

    def reload_workflow(self, sw):
        if self.router is not None:
            receipt = self.router.reload_workflow(sw)
        else:
            from veles_tpu.serve.router import ReplicaPool
            try:
                plans, params, shape = ReplicaPool._workflow_spec(sw)
            except ValueError:
                plans, params, shape = ReplicaPool._workflow_spec(
                    sw, self.engine.sample_shape)
            receipt = self.reload(params, plans=plans,
                                  sample_shape=shape)
        self.last_reload = receipt
        return receipt

    def reload(self, params, plans=None, sample_shape=None):
        """Snapshot hot-reload through the ONE shared state machine
        (:func:`veles_tpu.serve.router.reload_replicas`): a same-digest
        snapshot swaps weight buffers in place (zero recompiles), a
        changed digest AOT-warms a new engine off the dispatch path
        and cuts the batcher over between batches.  The single-engine
        service is simply a fleet of one entry — same receipt, same
        lock discipline, and the replacement engine inherits the
        current one's ladder/dtype/cache_root so a later warm restart
        still hits the configured cache."""
        if self.router is not None:
            receipt = self.router.reload(
                params, plans=plans, sample_shape=sample_shape)
            self.last_reload = receipt
            return receipt
        from veles_tpu.serve.router import Replica, reload_replicas
        with self._reload_lock:
            current = self.engine
            entry = Replica(0, current.device, current, self.batcher)
            receipt = reload_replicas(
                [entry], params, plans=plans,
                sample_shape=sample_shape,
                engine_kwargs=dict(
                    ladder=current.ladder, dtype=current.dtype,
                    cache_root=current.cache_root,
                    persistent_cache=current.cache_dir is not None))
            self._engine = entry.engine
        self.last_reload = receipt
        return receipt

    # -- HTTP ---------------------------------------------------------------

    def _make_app(self):
        import tornado.web

        svc = self

        class InferHandler(RequestTimer, tornado.web.RequestHandler):
            async def post(self):
                import asyncio
                try:
                    body = json.loads(self.request.body)
                    payload = body["input"]
                except Exception as exc:
                    self.set_status(400)
                    self.write({"error": "bad request: %s" % exc})
                    return
                # QoS identity: body fields win over headers; both
                # optional — un-labelled legacy clients serve as
                # tenant None / class "batch"
                tenant = body.get("tenant") or \
                    self.request.headers.get("X-Tenant")
                slo_class = body.get("slo_class") or \
                    self.request.headers.get("X-SLO-Class")
                # request trace id (docs/observability.md "Request
                # tracing"): body field wins over header; invalid or
                # absent ids are re-minted inside infer_payload
                trace = body.get("trace") or \
                    self.request.headers.get("X-Trace-Id")
                loop = asyncio.get_event_loop()
                try:
                    answer = await loop.run_in_executor(
                        svc._executor,
                        lambda: svc.infer_payload(
                            payload, tenant=tenant,
                            slo_class=slo_class, trace=trace))
                except ServeOverload as exc:
                    # the blacklist protocol's transient-reject shape
                    self.set_status(503)
                    self.set_header("Retry-After",
                                    "%.3f" % exc.retry_after)
                    self.write({"error": str(exc),
                                "retry_after": exc.retry_after})
                except (ValueError, TypeError) as exc:
                    self.set_status(400)
                    self.write({"error": str(exc)})
                except Exception as exc:
                    self.set_status(500)
                    self.write({"error": str(exc)})
                else:
                    self.write(answer)

        class HealthHandler(RequestTimer, tornado.web.RequestHandler):
            def get(self):
                health = {
                    "status": "ok",
                    "model_digest": svc.engine.digest,
                    "ladder": list(svc.engine.ladder),
                    "compile": svc.compile_receipt,
                    "serve": serve_snapshot(),
                }
                if svc.router is not None:
                    health["fleet" if svc._is_fleet else
                           "replicas"] = svc.router.snapshot()
                if svc.transport_port is not None:
                    health["transport_port"] = svc.transport_port
                if svc.last_reload is not None:
                    health["last_reload"] = svc.last_reload
                if svc.freshness is not None:
                    health["freshness"] = svc.freshness.snapshot()
                # the alert-history ring (observe/alerts.py): a
                # fleet front reports its router's OWN manager (the
                # one sweeping fleet rollups); everything else the
                # process-global one
                manager = getattr(svc.router, "alerts", None) \
                    if svc.router is not None else None
                if manager is None:
                    from veles_tpu.observe.alerts import alerts \
                        as manager
                health["alerts"] = manager.snapshot()
                self.write(health)

        class MetricsHandler(RequestTimer, tornado.web.RequestHandler):
            def get(self):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(_registry.snapshot(),
                                      default=repr))

        class ReloadHandler(RequestTimer, tornado.web.RequestHandler):
            async def post(self):
                import asyncio
                try:
                    body = json.loads(self.request.body or b"{}")
                    snapshot = body["snapshot"]
                except Exception as exc:
                    self.set_status(400)
                    self.write({"error": "bad request (need "
                                "{\"snapshot\": path}): %s" % exc})
                    return
                loop = asyncio.get_event_loop()
                try:
                    # blocking restore+reload off the IO loop: requests
                    # keep serving while the new weights warm up
                    receipt = await loop.run_in_executor(
                        svc._executor, svc.reload_snapshot, snapshot)
                except FileNotFoundError as exc:
                    self.set_status(404)
                    self.write({"error": str(exc)})
                except Exception as exc:
                    self.set_status(500)
                    self.write({"error": str(exc)})
                else:
                    self.write(receipt)

        class PublishHandler(RequestTimer, tornado.web.RequestHandler):
            def post(self):
                """Freshness push: a trainer (or CI) announces a new
                publish instead of waiting out the poll interval.  The
                body's ``snapshot`` path is ADVISORY — the watcher
                still reads LATEST and verifies the manifest before
                unpickling; a push can never bypass the gate."""
                if svc.freshness is None:
                    self.set_status(409)
                    self.write({"error": "no freshness loop attached "
                                "(start the service with a "
                                "FreshnessController / --watch-dir)"})
                    return
                try:
                    body = json.loads(self.request.body or b"{}")
                except Exception as exc:
                    self.set_status(400)
                    self.write({"error": "bad request: %s" % exc})
                    return
                svc.freshness.notify(body.get("snapshot"))
                self.write({"status": "notified",
                            "freshness": svc.freshness.snapshot()})

        return tornado.web.Application([
            (self.path, InferHandler),
            (r"/healthz", HealthHandler),
            (r"/metrics.json", MetricsHandler),
            (r"/reload", ReloadHandler),
            (r"/publish", PublishHandler),
        ])

    def start_background(self):
        from concurrent.futures import ThreadPoolExecutor
        # waiting requests only block on an Event, so workers are
        # cheap; the pool bounds in-flight HTTP requests, the batcher's
        # max_queue bounds admitted ones
        self._executor = ThreadPoolExecutor(
            max_workers=self._executor_workers,
            thread_name_prefix="serve-http")
        if self._owns_batcher:
            self.batcher.start()
        if self._transport_port is not None:
            from veles_tpu.serve.transport import BinaryTransportServer
            self._transport = BinaryTransportServer(
                self.batcher, port=self._transport_port,
                secret=self._transport_secret, quota=self.quota,
                retry_jitter=self.retry_jitter)
            self._transport.start_background()
        self._server = BackgroundHTTPServer(self._make_app(),
                                            port=self._port)
        thread = self._server.start()
        self.info("serve endpoint on http://127.0.0.1:%d%s "
                  "(healthz, metrics.json%s)", self.port, self.path,
                  "; binary transport :%d" % self.transport_port
                  if self._transport is not None else "")
        return thread

    def stop(self):
        # order matters: close the listeners (no new work), fail the
        # batcher's pending requests (unblocks executor tasks), THEN
        # join the executor so no worker thread outlives the service
        if self._transport is not None:
            self._transport.stop()
            self._transport = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._owns_batcher:
            self.batcher.stop()
        if self._executor is not None:
            self._executor.shutdown(wait=self._owns_batcher)
            self._executor = None
