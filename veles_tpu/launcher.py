"""Launcher — owns a workflow and the run session.

TPU-native counterpart of reference veles/launcher.py:100.  Modes:

- ``standalone`` (default): initialize + run on the local device(s).
- ``master`` / ``slave``: job-farming control plane over TCP/JSON
  (veles_tpu.server / veles_tpu.client) — used by genetics/ensemble task
  parallelism and elastic loaders.  On-pod tensor exchange does NOT use
  this path: SPMD steps compile collectives over ICI (veles_tpu.parallel).

Instead of the reference's SSH/paramiko node spawning, multi-host TPU
jobs are expected to be launched by the cluster scheduler with
``jax.distributed.initialize`` (veles_tpu.parallel.mesh); the launcher
keeps job-level spawn hooks for genetics/ensemble child processes.
"""

import os
import threading
import time

from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.logger import Logger

__all__ = ["Launcher"]


class Launcher(Logger, metaclass=CommandLineArgumentsRegistry):
    """Session owner: holds the workflow, device, and optional control
    plane endpoints."""

    def __init__(self, interactive=False, **kwargs):
        super(Launcher, self).__init__(**kwargs)
        from veles_tpu.config import root
        cfg = root.common.launcher
        self.master_address = kwargs.get(
            "master_address", cfg.get("master_address", ""))
        self.listen_address = kwargs.get(
            "listen_address", cfg.get("listen_address", ""))
        self.matplotlib_backend = kwargs.get("matplotlib_backend", "")
        self.interactive = interactive
        self.web_status_url = kwargs.get(
            "web_status", cfg.get("web_status", ""))
        # the cadence knob lives under root.common.web (config.py
        # defaults block), the same place the reference kept it
        self.notification_interval = float(kwargs.get(
            "notification_interval",
            root.common.web.get("notification_interval", 1)))
        # telemetry (docs/observability.md): --trace / --metrics-* land
        # in root.common.observe via apply_args; kwargs override for
        # programmatic use
        obs = root.common.observe
        self.trace_path = kwargs.get("trace", obs.get("trace", ""))
        self.metrics_interval = float(kwargs.get(
            "metrics_interval", obs.get("metrics_interval", 0)) or 0)
        self.metrics_path = kwargs.get(
            "metrics_path", obs.get("metrics_path", ""))
        self.profile_dir = kwargs.get(
            "profile", obs.get("profile", "")) or \
            os.environ.get("VELES_PROFILE", "")
        # --alerts: install the stock serve/train alert rule set on
        # the process-global manager; the heartbeat then evaluates it
        # every interval (docs/observability.md "Fleet telemetry")
        self.alerts_enabled = bool(kwargs.get(
            "alerts", obs.get("alerts", False)))
        self._workflow = None
        self.device = None
        self.stopped = False
        self.initialized = False
        self._agent = None  # Server or Client when distributed
        self._finished_event = threading.Event()
        self._reporter_stop = threading.Event()
        self._reporter_thread = None
        self.start_time = None

    @classmethod
    def init_parser(cls, parser):
        parser.add_argument(
            "-l", "--listen-address", default="",
            help="run as master, listening on host:port")
        parser.add_argument(
            "-m", "--master-address", default="",
            help="run as slave of the given master host:port")
        parser.add_argument(
            "--web-status", default="",
            help="URL of a WebStatusServer to post periodic session "
                 "status to (reference launcher.py:852-885)")
        parser.add_argument(
            "--trace", default="", metavar="PATH",
            help="write a Chrome/Perfetto trace of this run (unit runs, "
                 "fused steps, prefetcher stages, snapshot writes, "
                 "protocol events) to PATH; zero overhead when unset")
        parser.add_argument(
            "--metrics-interval", type=float, default=0, metavar="N",
            help="emit a JSONL telemetry heartbeat every N seconds "
                 "(step-time percentiles, throughput, health counters); "
                 "0 disables")
        parser.add_argument(
            "--metrics-path", default="", metavar="PATH",
            help="heartbeat JSONL destination (default: <trace>."
                 "heartbeat.jsonl next to --trace, else "
                 "veles_heartbeat.jsonl)")
        parser.add_argument(
            "--alerts", action="store_true", default=False,
            help="arm the stock burn-rate + anomaly alert rules "
                 "(observe/alerts.py) on this process; evaluated at "
                 "the --metrics-interval heartbeat cadence, firings "
                 "dump the flight recorder and land in /healthz")
        parser.add_argument(
            "--profile", default="", metavar="DIR",
            help="capture a jax.profiler trace into DIR around a "
                 "window of fused train steps (also VELES_PROFILE=DIR; "
                 "window via VELES_PROFILE_WINDOW=start:stop, "
                 "default 5:25)")
        parser.add_argument(
            "--grad-bucket-mb", type=float, default=None, metavar="MB",
            help="SPMD data plane: target size of the gradient "
                 "all-reduce buckets overlapped with the backward "
                 "pass (default ~25; 'inf' = one flat bucket; "
                 "docs/distributed.md)")
        parser.add_argument(
            "--grad-compress", default=None, choices=["bf16"],
            help="compress gradient all-reduce wire traffic; guarded "
                 "by the numerics watchdog with automatic f32 "
                 "fallback on a poisoned step")
        parser.add_argument(
            "--resume", default="", metavar="auto|PATH",
            help="restore the workflow from a snapshot before "
                 "initialize: 'auto' resumes from the newest validated "
                 "_current target in the snapshot directory (fresh "
                 "start when none exists); a path resumes from that "
                 "snapshot (with previous-good fallback if corrupt)")
        return parser

    @classmethod
    def apply_args(cls, args):
        from veles_tpu.config import root
        root.common.launcher.update({
            "listen_address": getattr(args, "listen_address", ""),
            "master_address": getattr(args, "master_address", ""),
            "web_status": getattr(args, "web_status", ""),
        })
        root.common.observe.update({
            "trace": getattr(args, "trace", ""),
            "metrics_interval": getattr(args, "metrics_interval", 0),
            "metrics_path": getattr(args, "metrics_path", ""),
            "profile": getattr(args, "profile", ""),
            "alerts": getattr(args, "alerts", False),
        })
        train_cfg = {}
        if getattr(args, "grad_bucket_mb", None) is not None:
            train_cfg["grad_bucket_mb"] = args.grad_bucket_mb
        if getattr(args, "grad_compress", None) is not None:
            train_cfg["grad_compress"] = args.grad_compress
        if train_cfg:
            root.common.train.update(train_cfg)
        if getattr(args, "resume", ""):
            root.common.snapshot.update({"resume": args.resume})

    # -- workflow ownership (Unit.workflow protocol) -----------------------

    def add_ref(self, workflow):
        self._workflow = workflow

    def del_ref(self, workflow):
        if self._workflow is workflow:
            self._workflow = None

    @property
    def workflow(self):
        return self._workflow

    @property
    def workflow_mode(self):
        if self.master_address:
            return "slave"
        if self.listen_address:
            return "master"
        return "standalone"

    @property
    def is_master(self):
        return self.workflow_mode == "master"

    @property
    def is_slave(self):
        return self.workflow_mode == "slave"

    @property
    def is_standalone(self):
        return self.workflow_mode == "standalone"

    # -- lifecycle ---------------------------------------------------------

    @staticmethod
    def init_multihost():
        """Multi-host topology discovery: replaces the reference's SSH
        node spawn + socket handshake (launcher.py:808-906).  The
        cluster scheduler sets VELES_COORDINATOR (host:port),
        VELES_NUM_PROCESSES and VELES_PROCESS_ID; after
        jax.distributed.initialize every process sees the global
        device list and meshes span the pod/slice."""
        import os
        coordinator = os.environ.get("VELES_COORDINATOR")
        if not coordinator:
            return False
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(os.environ.get("VELES_NUM_PROCESSES", 1)),
            process_id=int(os.environ.get("VELES_PROCESS_ID", 0)))
        return True

    def _maybe_resume(self):
        """Honor ``--resume`` / ``root.common.snapshot.resume``: swap
        the attached workflow for the validated snapshot BEFORE
        initialize, so slaves reconnecting to a restarted master are
        re-admitted at the restored epoch.  Slaves never restore (their
        state arrives from the master); an already-restored workflow
        (``-w``) is left alone."""
        from veles_tpu.config import root
        spec = root.common.snapshot.get("resume") or ""
        if not spec or self.is_slave or \
                getattr(self._workflow, "restored_from_snapshot_", False):
            return
        from veles_tpu.snapshotter import SnapshotterBase
        path = SnapshotterBase.resolve_resume(spec)
        if path is None:
            self.info("--resume auto: no snapshot found; starting fresh")
            return
        self.info("resuming workflow from snapshot %s", path)
        from veles_tpu.workflow import restore_workflow
        restored = restore_workflow(path, self)
        # add_ref re-homed it; make the swap explicit regardless of
        # launcher add_ref semantics
        self._workflow = restored

    def initialize(self, device=None, **kwargs):
        if self._workflow is None:
            raise RuntimeError("no workflow attached to the launcher")
        self._maybe_resume()
        self.init_multihost()
        if device is None or isinstance(device, str):
            from veles_tpu.backends import Device
            # backend=None lets Device resolve VELES_BACKEND /
            # root.common.engine.backend (where the CLI's -d lands)
            # before falling back to auto
            device = Device(backend=device)
        self.device = device
        self.info("initializing workflow %s on %s (%s mode)",
                  self._workflow.name, device, self.workflow_mode)
        if not self.is_master:
            self._workflow.initialize(device=device, **kwargs)
        else:
            # Master initializes too (it owns canonical state) but will
            # not run the hot loop itself.
            self._workflow.initialize(device=device, **kwargs)
        if self.is_master:
            from veles_tpu.server import Server
            self._agent = Server(self.listen_address, self._workflow,
                                 launcher=self)
        elif self.is_slave:
            from veles_tpu.client import Client
            self._agent = Client(self.master_address, self._workflow,
                                 launcher=self)
        self.initialized = True

    def on_fleet_change(self, info):
        """Server reshard hook (docs/distributed.md, "Elasticity
        contract"): every membership change lands in the structured
        event stream, so the dashboard's event browser shows
        joins/leaves/reshards next to the health events they often
        explain (a loss spike right after half the fleet left is not
        divergence)."""
        self.event("fleet.reshard", "instant", **{
            k: v for k, v in info.items() if v is not None})
        self.info("fleet change: %s -> membership epoch %s, %s live, "
                  "unserved remainder %s", info.get("reason"),
                  info.get("epoch"), info.get("live"),
                  info.get("remaining"))

    def _start_status_reporter(self):
        """Periodic status posts to the web-status service while the
        session runs — slaves stay silent, like the reference
        (launcher.py:852-885 posted from the master/standalone side)."""
        if not self.web_status_url or self.is_slave:
            return
        import collections
        import json
        import uuid

        from veles_tpu.logger import add_event_hook, remove_event_hook
        from veles_tpu.web_status import StatusReporter
        reporter = StatusReporter(
            self.web_status_url,
            "%s-%s" % (self._workflow.name, uuid.uuid4().hex[:8]),
            self._workflow)
        self._reporter_stop.clear()
        # Logger.event records ride along with the status posts (the
        # reference streamed them to MongoDB for the dashboard's event
        # browser); the hook only enqueues — posting happens on the
        # reporter thread, never on the traced thread
        pending_events = collections.deque(maxlen=200)
        dropped = [0]

        def hook(record):
            if len(pending_events) == pending_events.maxlen:
                dropped[0] += 1  # logged from the reporter thread
            pending_events.append(record)

        add_event_hook(hook)

        def drain_events(limit=50):
            # peek-then-pop: a failed post leaves the record queued for
            # the next cycle instead of losing it; the per-tick limit
            # bounds how long a drain can hold the reporter thread
            sent = 0
            while pending_events and sent < limit:
                reporter.post_event(json.dumps(
                    pending_events[0], default=repr))
                pending_events.popleft()
                sent += 1
            if dropped[0]:
                self.debug("%d trace events dropped (queue full)",
                           dropped[0])
                dropped[0] = 0

        def loop():
            try:
                while not self._reporter_stop.wait(
                        self.notification_interval):
                    try:
                        reporter.post()
                        drain_events()
                    except Exception as exc:
                        self.debug("status post failed: %s", exc)
                try:
                    reporter.post()  # final state after the run ends
                    drain_events()
                except Exception as exc:
                    self.debug("final status post failed: %s", exc)
            finally:
                remove_event_hook(hook)

        self._reporter_thread = threading.Thread(
            target=loop, daemon=True, name="status-reporter")
        self._reporter_thread.start()

    def _start_telemetry(self):
        """Run-scoped observability (docs/observability.md): the span
        tracer behind ``--trace``, the heartbeat behind
        ``--metrics-interval``, and the jax.profiler window behind
        ``--profile`` / VELES_PROFILE.  Returns the heartbeat (or
        None); everything else is process-global."""
        from veles_tpu import observe
        # the always-on flight recorder dumps next to --trace when one
        # is set (otherwise its cwd default); the XLA compile listener
        # installs here so even pre-run compiles are counted
        if self.trace_path:
            observe.flight.base_path = self.trace_path + ".flight"
        try:
            from veles_tpu.observe import xla_introspect
            xla_introspect.ensure_installed()
        except Exception:
            pass
        if self.trace_path:
            observe.tracer.start()
            if observe.tracer.label is None:
                observe.tracer.label = self.workflow_mode
        if self.profile_dir:
            observe.install_profiler(
                observe.ProfilerHook(self.profile_dir))
        if self.alerts_enabled:
            from veles_tpu.observe.alerts import alerts, default_rules
            if not alerts.rules:
                alerts.configure([r.spec() for r in default_rules()])
            self.info("alerting armed: %d rules (%s)",
                      len(alerts.rules),
                      ", ".join(r.name for r in alerts.rules))
            if self.metrics_interval <= 0:
                self.warning("--alerts without --metrics-interval: "
                             "rules are armed but nothing evaluates "
                             "them (the heartbeat is the evaluator)")
        if self.metrics_interval > 0:
            path = self.metrics_path or (
                self.trace_path + ".heartbeat.jsonl"
                if self.trace_path else "veles_heartbeat.jsonl")
            heartbeat = observe.Heartbeat(
                path, self.metrics_interval, workflow=self._workflow)
            heartbeat.start()
            self.info("telemetry heartbeat -> %s every %.3g s",
                      path, self.metrics_interval)
            return heartbeat
        return None

    def _stop_telemetry(self, heartbeat):
        from veles_tpu import observe
        if heartbeat is not None:
            heartbeat.stop()
        if self.profile_dir:
            observe.uninstall_profiler()
        if self.trace_path:
            observe.tracer.stop()
            try:
                observe.tracer.save(self.trace_path)
                self.info("trace written to %s (%d events)",
                          self.trace_path, len(observe.tracer.events))
            except OSError as exc:
                self.error("failed to write trace %s: %s",
                           self.trace_path, exc)
            self._write_merged_trace()

    def _write_merged_trace(self):
        """Master only: stitch this process's trace with the chunks
        its slaves shipped back into ``<trace>.merged.json`` — one
        Perfetto timeline with per-process tracks and offset-corrected
        timestamps (docs/observability.md)."""
        collector = getattr(self._agent, "trace_collector", None)
        if collector is None or not collector.keys():
            return
        try:
            import json

            from veles_tpu.observe import merge, tracer
            with open(self.trace_path) as fin:
                master_doc = json.load(fin)
            merged = merge.merge_run(
                master_doc, collector,
                trace_id=getattr(self._agent, "trace_id", None),
                master_label=tracer.label or "master")
            merged_path = self.trace_path + ".merged.json"
            tmp = merged_path + ".tmp"
            with open(tmp, "w") as fout:
                json.dump(merged, fout)
            os.replace(tmp, merged_path)
            self.info("merged cluster trace written to %s "
                      "(%d slave track(s))", merged_path,
                      len(collector.keys()))
        except Exception as exc:
            self.error("failed to write merged trace: %s", exc)

    def _install_fatal_signal_hook(self):
        """SIGTERM dumps the flight ring and saves the --trace buffer
        BEFORE the process dies: a scheduler kill must not take the
        black box down with the plane.  Only the main thread may set
        signal handlers; elsewhere (tests, embedded runs) this is a
        silent no-op.  Returns an uninstall callable."""
        import signal

        def on_term(signum, frame):
            from veles_tpu import observe
            observe.flight.dump(reason="signal-%d" % signum)
            if self.trace_path:
                observe.tracer.stop()
                try:
                    observe.tracer.save(self.trace_path)
                except OSError:
                    pass
            signal.signal(signum, previous or signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        try:
            previous = signal.signal(signal.SIGTERM, on_term)
        except ValueError:
            return lambda: None

        def uninstall():
            try:
                signal.signal(signal.SIGTERM, previous)
            except (ValueError, TypeError):
                pass

        return uninstall

    def run(self):
        if not self.initialized:
            self.initialize()
        self.start_time = time.time()
        self._finished_event.clear()
        self.stopped = False
        from veles_tpu.thread_pool import ThreadPool
        ThreadPool.sigint_hook = self.stop
        heartbeat = None
        uninstall_signals = self._install_fatal_signal_hook()
        try:
            # inside the try: a failure here must still reach the
            # finally that stops the heartbeat/tracer and writes the
            # --trace file, not leak them enabled into the process
            heartbeat = self._start_telemetry()
            self._start_status_reporter()
            if self._agent is not None:
                self._agent.run()  # blocks until the session ends
            else:
                self._workflow.run()
                self._finished_event.set()
        except BaseException:
            # black-box dump on ANY escaping failure (including chaos
            # crashes, which derive from BaseException); the finally
            # below still saves the --trace buffer, so a crashed run
            # leaves both a flame graph and a flight timeline
            from veles_tpu import observe
            observe.flight.dump(reason="exception")
            raise
        finally:
            uninstall_signals()
            ThreadPool.sigint_hook = None
            self.stopped = True
            if self._reporter_thread is not None:
                self._reporter_stop.set()
                self._reporter_thread.join(timeout=5)
                self._reporter_thread = None
            self._stop_telemetry(heartbeat)
        elapsed = time.time() - self.start_time
        self.info("session finished in %.1f s", elapsed)
        self._workflow.print_stats()
        self._workflow.write_results()

    def on_workflow_finished(self):
        if self.is_slave:
            return  # per-job pass completion; the master ends the session
        self._finished_event.set()
        if self._agent is not None:
            self._agent.on_workflow_finished()

    def stop(self):
        self.stopped = True
        if self._workflow is not None:
            self._workflow.stop()
        if self._agent is not None:
            self._agent.stop()
        self._finished_event.set()

    def pause(self):
        if self._agent is not None:
            self._agent.pause()

    def resume(self):
        if self._agent is not None:
            self._agent.resume()
