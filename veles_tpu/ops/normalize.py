"""Mean/dispersion normalization kernel.

TPU-native counterpart of reference ocl/mean_disp_normalizer.cl:12-20 /
cuda equivalent: ``out = (x - mean) * rdisp`` broadcast over samples,
with an on-the-fly cast from the storage dtype (the reference normalises
uint8 image data straight out of the dataset).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from veles_tpu.ops.common import interpret_for, kernel_cast, pad_to

__all__ = ["mean_disp_normalize"]


def _normalize_kernel(x_ref, mean_ref, rdisp_ref, out_ref):
    x = kernel_cast(x_ref[:], out_ref.dtype)
    out_ref[:] = (x - mean_ref[:]) * rdisp_ref[:]


@functools.partial(jax.jit, static_argnames=("out_dtype", "block"))
def mean_disp_normalize(x, mean, rdisp, out_dtype=jnp.float32, block=256):
    """(B, F) storage-dtype x, (F,) mean, (F,) reciprocal dispersion."""
    batch = x.shape[0]
    sample_shape = x.shape[1:]
    flat = x.reshape(batch, -1)
    width = flat.shape[1]
    mean = mean.reshape(1, width).astype(out_dtype)
    rdisp = rdisp.reshape(1, width).astype(out_dtype)
    bm = min(block, batch if batch % 8 == 0 else batch + 8 - batch % 8)
    flat = pad_to(flat, (bm, 128))
    mean = pad_to(mean, (None, 128))
    rdisp = pad_to(rdisp, (None, 128))
    mp, wp = flat.shape
    out = pl.pallas_call(
        _normalize_kernel,
        grid=(mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, wp), lambda i: (i, 0)),
            pl.BlockSpec((1, wp), lambda i: (0, 0)),
            pl.BlockSpec((1, wp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, wp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, wp), out_dtype),
        interpret=interpret_for(flat),
    )(flat, mean, rdisp)
    return out[:batch, :width].reshape((batch,) + sample_shape)
