"""Device-side random number generation.

TPU-native counterpart of reference ocl/random.cl:42-125 /
cuda/random.cu — the xorshift128+ and xorshift1024* generators (16 u64
words of state per stream, interleaved output) used by the Uniform
accelerated unit and, downstream, dropout.

TPUs have no native uint64, so the generators run on (hi, lo) uint32
pairs with explicit carry emulation — bit-exact against the u64
reference semantics (tests compare against a numpy u64 oracle, the same
role the reference's numpy fallback plays at prng/uniform.py:129-163).

For new code the idiomatic path is ``hardware_uniform`` (Pallas
``pltpu.prng_random_bits``) or ``jax.random``; the xorshift family is
kept for reference-parity workloads.
"""

import functools

import jax
import jax.numpy as jnp
import numpy
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.common import interpret_mode

__all__ = ["xorshift128plus", "xorshift1024star", "uniform_from_bits",
           "hardware_uniform", "numpy_xorshift128plus",
           "numpy_xorshift1024star"]

U32 = jnp.uint32


# -- u64 emulation on (hi, lo) uint32 pairs -------------------------------

def _shl(hi, lo, k):
    if k == 0:
        return hi, lo
    if k >= 32:
        return (lo << (k - 32)).astype(U32), jnp.zeros_like(lo)
    return ((hi << k) | (lo >> (32 - k))).astype(U32), (lo << k).astype(U32)


def _shr(hi, lo, k):
    if k == 0:
        return hi, lo
    if k >= 32:
        return jnp.zeros_like(hi), (hi >> (k - 32)).astype(U32)
    return (hi >> k).astype(U32), ((lo >> k) | (hi << (32 - k))).astype(U32)


def _xor(a, b):
    return (a[0] ^ b[0], a[1] ^ b[1])


def _add(a, b):
    lo = (a[1] + b[1]).astype(U32)
    carry = (lo < a[1]).astype(U32)
    hi = (a[0] + b[0] + carry).astype(U32)
    return hi, lo


def _mul(a, konst):
    """(hi, lo) * constant mod 2**64 via 16-bit limbs (products fit u32)."""
    a_limbs = [(a[1] & 0xffff), (a[1] >> 16), (a[0] & 0xffff),
               (a[0] >> 16)]
    k_limbs = [U32((konst >> (16 * i)) & 0xffff) for i in range(4)]
    r = [jnp.zeros_like(a[1]) for _ in range(4)]
    for i in range(4):
        for j in range(4 - i):
            r[i + j] = (r[i + j] + a_limbs[i] * k_limbs[j]).astype(U32)
            # carry into the next limb (r slots hold up to 32 bits)
            if i + j + 1 < 4:
                carry = r[i + j] >> 16
                r[i + j] = r[i + j] & 0xffff
                r[i + j + 1] = (r[i + j + 1] + carry).astype(U32)
    lo = (r[0] | (r[1] << 16)).astype(U32)
    hi = ((r[2] & 0xffff) | (r[3] << 16)).astype(U32)
    return hi, lo


# -- xorshift128+ ----------------------------------------------------------

def _xs128_step(state):
    """xorshift128+ with the reference's constants 23/17/26
    (ocl/random.cl:104-112): x <- s[0], y <- s[1]; s' = (y, new);
    out = new + y.  state: ((hi, lo), (hi, lo)); returns (state, out64)."""
    x, y = state[0], state[1]
    x = _xor(x, _shl(*x, 23))
    new1 = _xor(_xor(x, y), _xor(_shr(*x, 17), _shr(*y, 26)))
    out = _add(new1, y)
    return (y, new1), out


@functools.partial(jax.jit, static_argnames=("count",))
def xorshift128plus(state, count):
    """Generate ``count`` u64 outputs per stream.

    state: uint32 array (2, 2, S) = (word, hi/lo, streams).
    Returns (new_state, bits) with bits uint32 (count, 2, S).
    """
    def body(carry, _):
        st, out = _xs128_step(((carry[0, 0], carry[0, 1]),
                               (carry[1, 0], carry[1, 1])))
        new = jnp.stack([jnp.stack(st[0]), jnp.stack(st[1])])
        return new, jnp.stack(out)

    new_state, outs = jax.lax.scan(body, state, None, length=count)
    return new_state, outs


def numpy_xorshift128plus(state, count):
    """u64 oracle with identical bitstream (host fallback)."""
    s = (state[:, 0].astype(numpy.uint64) << numpy.uint64(32)) | \
        state[:, 1].astype(numpy.uint64)
    outs = numpy.empty((count,) + s.shape[1:], dtype=numpy.uint64)
    with numpy.errstate(over="ignore"):
        for i in range(count):
            x, y = s[0], s[1]
            x = x ^ ((x << numpy.uint64(23)) & numpy.uint64(0xffffffffffffffff))
            new1 = x ^ y ^ (x >> numpy.uint64(17)) ^ (y >> numpy.uint64(26))
            outs[i] = (new1 + y) & numpy.uint64(0xffffffffffffffff)
            s = numpy.stack([y, new1])
    hi = (s >> numpy.uint64(32)).astype(numpy.uint32)
    lo = (s & numpy.uint64(0xffffffff)).astype(numpy.uint32)
    return numpy.stack([hi, lo], axis=1), outs


# -- xorshift1024* ---------------------------------------------------------

_XS1024_MULT = 1181783497276652981


def _xs1024_step(state_hi, state_lo, p):
    """One step over (16, S) hi/lo state arrays; returns new arrays,
    new p, and the (hi, lo) output."""
    s0 = (state_hi[p], state_lo[p])
    p1 = (p + 1) & 15
    s1 = (state_hi[p1], state_lo[p1])
    s1 = _xor(s1, _shl(*s1, 31))
    new = _xor(_xor(s1, s0), _xor(_shr(*s1, 11), _shr(*s0, 30)))
    state_hi = state_hi.at[p1].set(new[0])
    state_lo = state_lo.at[p1].set(new[1])
    out = _mul(new, _XS1024_MULT)
    return state_hi, state_lo, p1, out


@functools.partial(jax.jit, static_argnames=("count",))
def xorshift1024star(state_hi, state_lo, p, count):
    """state_hi/lo: uint32 (16, S); p: int32 scalar; count outputs."""
    def body(carry, _):
        hi, lo, pp = carry
        hi, lo, pp, out = _xs1024_step(hi, lo, pp)
        return (hi, lo, pp), jnp.stack(out)

    (state_hi, state_lo, p), outs = jax.lax.scan(
        body, (state_hi, state_lo, p), None, length=count)
    return state_hi, state_lo, p, outs


def numpy_xorshift1024star(state, p, count):
    """u64 oracle: state uint64 (16, S)."""
    s = state.astype(numpy.uint64).copy()
    outs = numpy.empty((count,) + s.shape[1:], dtype=numpy.uint64)
    mask = numpy.uint64(0xffffffffffffffff)
    with numpy.errstate(over="ignore"):
        for i in range(count):
            s0 = s[p]
            p = (p + 1) & 15
            s1 = s[p]
            s1 = s1 ^ ((s1 << numpy.uint64(31)) & mask)
            new = s1 ^ s0 ^ (s1 >> numpy.uint64(11)) ^ \
                (s0 >> numpy.uint64(30))
            s[p] = new
            outs[i] = (new * numpy.uint64(_XS1024_MULT)) & mask
    return s, p, outs


# -- bits -> floats --------------------------------------------------------

@jax.jit
def uniform_from_bits(hi_bits, vmin=0.0, vmax=1.0):
    """Map uint32 bits to floats in [vmin, vmax) using the top 24 bits
    (exactly representable in float32)."""
    u = (hi_bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    return vmin + u * (vmax - vmin)


# -- idiomatic hardware PRNG path -----------------------------------------

def _hw_uniform_kernel(seed_ref, out_ref):
    pltpu.prng_seed(seed_ref[0])
    bits = pltpu.bitcast(pltpu.prng_random_bits(out_ref.shape),
                         jnp.uint32)
    # top 24 bits; values < 2**24 fit int32, which Mosaic can cast to
    # float (unsigned -> float is not lowerable directly)
    top = (bits >> 8).astype(jnp.int32)
    out_ref[:] = top.astype(jnp.float32) * (1.0 / (1 << 24))


@functools.partial(jax.jit, static_argnames=("shape",))
def hardware_uniform(seed, shape):
    """Uniform [0,1) floats from the TPU hardware PRNG (Pallas).

    Falls back to jax.random on the CPU interpreter (where the hardware
    generator doesn't exist); both paths are deterministic per seed.
    """
    if interpret_mode():
        return jax.random.uniform(jax.random.PRNGKey(seed), shape)
    return pl.pallas_call(
        _hw_uniform_kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
    )(jnp.asarray([seed], jnp.int32))
