"""Tiled Pallas matmul with precision levels.

TPU-native counterpart of the reference's flagship kernel family
(reference: ocl/matrix_multiplication.cl:1, matrix_multiplication_precise
.cl:47-185, cuda equivalents).  The reference tiles into shared memory
with BLOCK_SIZE x BLOCK_SIZE tiles and offers PRECISION_LEVEL
0 (plain) / 1 (Kahan) / 2 (multi-partial) accumulation.

Design mapping (SURVEY.md section 7, hard part 7):

- Tiling targets the MXU through ``jnp.dot(..., preferred_element_type=
  float32)`` over VMEM-resident blocks; the grid walks (M/bm, N/bn) with
  the K loop inside the kernel accumulating in an f32 VMEM scratch.
- PRECISION_LEVEL 0 ("plain", fastest): f32 inputs run a bf16x3
  decomposition (a_hi@b_hi + a_hi@b_lo + a_lo@b_hi) — f32-class
  products (~5e-7 max rel err measured on chip vs an f64 oracle) at
  ~2x the throughput of the MXU's 6-pass true-f32 path (53 vs 25
  TFLOP/s measured on v5e at 3001^2); accumulation is always f32.
- Level 1 pays for true-f32 products (HIGHEST) plus Kahan
  compensation across K-tile partial sums.
- Level 2 adds Neumaier (improved Kahan) compensation, the analog of
  the reference's multi-partial summation.  The speed/digits ladder
  mirrors the reference's (config.py:245-248: each level costs more).

Tile sizes come from the per-chip autotune table
(veles_tpu.backends.DeviceInfo), the analog of devices/device_infos.json.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops import common as _common
from veles_tpu.ops.common import (ceil_mult, interpret_for,
                                   mxu_partial_dot, pad_to,
                                   tpu_compiler_params, unpad)

__all__ = ["matmul", "matmul_benchmark", "autotune_matmul",
           "MATMUL_KERNEL_VERSION"]

_DEFAULT_BLOCKS = (512, 512, 512)

#: bump when the kernel's algorithm changes: persisted autotune tables
#: and measured-ceiling entries are only valid for the algorithm they
#: were measured on (v2 = bf16x3 level-0 f32 path; v1 entries in old
#: caches are ignored, not silently served)
MATMUL_KERNEL_VERSION = 2


def _matmul_kernel(a_ref, b_ref, out_ref, acc_ref, comp_ref,
                   *, n_k, precision_level):
    """One (i, j, k) grid step: acc += A[i,k] @ B[k,j].

    ``acc_ref`` is the f32 accumulator scratch; ``comp_ref`` carries the
    Kahan/Neumaier compensation for precision levels 1/2.
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        if precision_level > 0:
            comp_ref[:] = jnp.zeros_like(comp_ref)

    # f32 multiply precision maps the reference's speed/accuracy ladder
    # onto the MXU's pass structure (the PRODUCT step is the shared
    # common.mxu_partial_dot, so the conv-VJP wgrad kernel and this one
    # cannot drift): level 0 ("plain", fastest) = bf16x3 decomposition
    # for f32 inputs, levels 1/2 pay for HIGHEST = 6 passes (true-f32
    # products) plus Kahan/Neumaier accumulation — like the reference,
    # each level trades speed for digits (config.py:245-248).
    partial = mxu_partial_dot(a_ref[:], b_ref[:], precision_level)
    if precision_level == 0:
        acc_ref[:] += partial
    elif precision_level == 1:
        # Kahan: y = partial - c; t = acc + y; c = (t - acc) - y
        y = partial - comp_ref[:]
        t = acc_ref[:] + y
        comp_ref[:] = (t - acc_ref[:]) - y
        acc_ref[:] = t
    else:
        # Neumaier: compensation works for |partial| > |acc| too
        acc = acc_ref[:]
        t = acc + partial
        big = jnp.abs(acc) >= jnp.abs(partial)
        comp_ref[:] += jnp.where(big, (acc - t) + partial,
                                 (partial - t) + acc)
        acc_ref[:] = t

    @pl.when(k == n_k - 1)
    def _store():
        total = acc_ref[:]
        if precision_level == 2:
            total = total + comp_ref[:]
        out_ref[:] = total.astype(out_ref.dtype)


def matmul(a, b, precision_level=0, blocks=None, out_dtype=None):
    """``a @ b`` through the Pallas tiled kernel.

    a: (M, K), b: (K, N).  Inputs may be float32 or bfloat16; the MXU
    accumulates in float32 regardless.

    ``precision_level`` trades digits for speed (the reference's
    PRECISION_LEVEL ladder).  Level 0 (default, fastest) computes
    float32 products via a bf16x3 decomposition on the MXU: ~5e-7 max
    relative error vs an f64 oracle (f32-class results) at ~2x the
    true-f32 throughput, BUT operands with |x| >= bf16 max (~3.39e38)
    or inf land outside the decomposition's domain and produce NaN.
    For inputs that large — or when bit-exact f32 products matter —
    use level 1 (true-f32 HIGHEST products + Kahan accumulation) or
    level 2 (adds Neumaier compensation).  bfloat16 inputs are
    unaffected: they always take single-pass MXU products.

    A thin eager wrapper around the jitted kernel: the interpret-mode
    decision needs the CONCRETE operand placement (CPU-committed arrays
    on a TPU-default host must interpret), which is invisible once
    everything is a tracer inside one jit.

    Debug guard (docs/health.md): set ``VELES_DEBUG_NONFINITE=1`` and
    every eager call validates its output, raising FloatingPointError
    with per-operand stats when inf/NaN appears — the level-0 bf16x3
    decomposition silently maps ``|x| >= bf16-max`` (and inf) to NaN,
    which otherwise surfaces only steps later as a skipped update.
    The check forces a device sync per call, so it is opt-in and for
    debugging only.
    """
    out = _matmul_jit(a, b, precision_level, blocks, out_dtype,
                      interpret_for(a, b))
    # read live from ops.common — ONE patch point for every kernel's
    # guard (conv_vjp reads the same flag), per common.py's contract
    if _common.DEBUG_NONFINITE:
        _debug_check_finite(a, b, out, precision_level)
    return out


def _operand_stats(name, x):
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return "%s: %s %s" % (name, x.shape, x.dtype)
    finite = jnp.isfinite(x)
    n_bad = int(jnp.sum(~finite))
    finite_abs = jnp.where(finite, jnp.abs(x), 0.0)
    return ("%s: %s %s, %d non-finite, max|finite| %.6g" %
            (name, x.shape, x.dtype, n_bad, float(jnp.max(finite_abs))
             if x.size else 0.0))


def _debug_check_finite(a, b, out, precision_level):
    if not bool(jnp.isfinite(out).all()):
        bf16_max = float(jnp.finfo(jnp.bfloat16).max)
        hint = ""
        if (precision_level == 0 and jnp.asarray(a).dtype ==
                jnp.float32 and bool(jnp.isfinite(a).all()) and
                bool(jnp.isfinite(b).all())):
            hint = (" — operands are finite, so this is the level-0 "
                    "bf16x3 domain limit (|x| >= %.4g maps to NaN); "
                    "use precision_level >= 1 for operands this large"
                    % bf16_max)
        raise FloatingPointError(
            "matmul produced non-finite output (%s)%s" % (
                "; ".join((_operand_stats("lhs", a),
                           _operand_stats("rhs", b),
                           _operand_stats("out", out))), hint))


@functools.partial(
    jax.jit, static_argnames=("precision_level", "blocks", "out_dtype",
                              "interpret"))
def _matmul_jit(a, b, precision_level, blocks, out_dtype, interpret):
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError("matmul expects 2-D operands")
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError("shape mismatch: %s @ %s" % (a.shape, b.shape))
    out_dtype = out_dtype or a.dtype
    if m == 0 or n == 0 or k == 0:
        return jnp.zeros((m, n), out_dtype)
    bm, bn, bk = blocks or _DEFAULT_BLOCKS
    bm, bn, bk = (min(bm, ceil_mult(m, 8)), min(bn, ceil_mult(n, 128)),
                  min(bk, ceil_mult(k, 128)))
    a = pad_to(a, (bm, bk))
    b = pad_to(b, (bk, bn))
    mp, kp = a.shape
    _, np_ = b.shape
    n_k = kp // bk
    grid = (mp // bm, np_ // bn, n_k)

    out = pl.pallas_call(
        functools.partial(_matmul_kernel, n_k=n_k,
                          precision_level=precision_level),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((bm, bn), jnp.float32),
            pltpu.VMEM((bm, bn), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)
    return unpad(out, (m, n))


def _chain_slope(mm, a, repeats):
    """One (chain(repeats+1) - chain(1)) / repeats slope sample over
    dependent ``acc = mm(acc)`` chains ended by a scalar fetch — the
    single shared definition of the matmul timing methodology (the
    benchmark facade and the autotuner must never drift apart)."""
    import time

    def chain(n):
        start = time.perf_counter()
        acc = a
        for _ in range(n):
            acc = mm(acc)
        float(acc[0, 0].astype(jnp.float32))
        return time.perf_counter() - start

    return (chain(repeats + 1) - chain(1)) / repeats


def matmul_benchmark(size=3001, dtype=jnp.float32, precision_level=0,
                     repeats=10, blocks=None, samples=1):
    """Time the kernel on an NxN self-multiply — the same measurement the
    reference's autotuner and DeviceBenchmark unit make
    (reference: ocl/benchmark.cl:1-11, accelerated_units.py:706).

    Measured as the slope between a 1-long and an (repeats+1)-long
    DEPENDENT chain, each ended by a scalar fetch: dispatch/tunnel
    latency cancels, pure device time per matmul remains.  With
    ``samples`` > 1 the median of that many slopes is returned — single
    slopes are noisy enough on tunneled devices to go non-positive, so
    rank-sensitive callers (the autotuner) raise it; the one-shot
    default keeps the client power-rating handshake cheap.

    Returns the RAW slope, which may be zero or negative when tunnel
    jitter swamps the chain delta.  Callers must validate and discard
    non-positive samples (never clamp: a floored nonsense slope once
    crowned the wrong autotune tile and published an impossible rate).
    """
    import numpy
    a = jnp.asarray(
        (numpy.random.RandomState(13).rand(size, size) - 0.5) * 0.01,
        dtype=dtype)

    def mm(x):
        return matmul(x, a, precision_level=precision_level,
                      blocks=blocks)

    float(mm(a)[0, 0])  # compile + warmup

    slopes = sorted(_chain_slope(mm, a, repeats)
                    for _ in range(samples))
    mid = samples // 2
    return (slopes[mid] if samples % 2
            else (slopes[mid - 1] + slopes[mid]) / 2.0)


def autotune_matmul(device_info, size=2048, dtype=jnp.float32,
                    precision_level=0):
    """Pick the best block config for this chip and persist it
    (analog of reference backends.py:672-731 _find_optimal_bs_vo)."""
    # the key carries the tuning size (tile optima don't transfer
    # between shapes) and the kernel version (optima measured on an
    # old algorithm must never serve a new one)
    key = "matmul:v%d:%s:pl%d:s%d" % (
        MATMUL_KERNEL_VERSION, jnp.dtype(dtype).name,
        precision_level, size)
    cached = device_info.get(key)
    if cached is not None:
        return tuple(cached)
    # deep-K tiles matter most on the MXU: K is the "arbitrary" grid
    # axis, so a bigger bk means fewer accumulator round-trips.  Tiles
    # whose VMEM footprint exceeds the chip fail to compile and are
    # skipped (measured on v5e: bf16 best = (512, 512, 1024), ~1.7x
    # over (256, 256, 256)).
    candidates = [(256, 256, 256), (512, 512, 512), (512, 512, 1024),
                  (512, 512, 2048), (256, 256, 1024), (512, 1024, 512),
                  (1024, 512, 512), (256, 512, 1024)]
    if jnp.dtype(dtype) == jnp.float32 and precision_level in (0, 1):
        # taller-M / wider-N tiles for the f32 paths (level 0's three
        # bf16 dots per K-step and level 1's six-pass HIGHEST products
        # + Kahan both shift the VMEM/compute balance away from the
        # square default): a (768, 512, 512) tile measured ~1.25x over
        # (512, 512, 512) at 3001^2 on v5e for level 0, round-robin-
        # validated against congestion.  bf16/level 2 skip them — each
        # extra tile costs a fresh compile + 5 timing samples on a
        # cold cache.
        candidates += [(768, 512, 512), (640, 512, 512),
                       (512, 640, 512), (512, 640, 640)]
    # at small sizes several tiles clamp to the same effective blocks
    # inside the kernel — benchmark each distinct clamped shape once
    seen, distinct = set(), []
    for bm, bn, bk in candidates:
        clamped = (min(bm, ceil_mult(size, 8)),
                   min(bn, ceil_mult(size, 128)),
                   min(bk, ceil_mult(size, 128)))
        if clamped not in seen:
            seen.add(clamped)
            distinct.append((bm, bn, bk))
    # ROUND-ROBIN measurement: whole-chip congestion drifts minute to
    # minute (measured ~1.4x swings with tight within-run spreads), so
    # timing each tile's samples back to back lets a congestion window
    # crown the wrong tile.  Interleaving one sample of every tile per
    # round spreads the drift across all candidates equally; the
    # median over rounds then ranks honestly.  Operands are built once
    # — a per-sample host->device upload would dominate the chains on
    # a tunneled chip.
    import numpy as _numpy
    a = jnp.asarray(
        (_numpy.random.RandomState(13).rand(size, size) - 0.5) * 0.01,
        dtype=dtype)

    def make_mm(blocks):
        def mm(x):
            return matmul(x, a, precision_level=precision_level,
                          blocks=blocks)
        return mm

    # repeats=24: short chains (~8) can INVERT tile rankings on a
    # tunneled chip — a config measured 192 TF over 20-step chains
    # sustained only 86 TF over 100-step ones while the true winner
    # sustained 135
    repeats, rounds = 24, 5
    mms = {}
    for blocks in distinct:
        try:
            mm = make_mm(blocks)
            float(mm(a)[0, 0].astype(jnp.float32))  # compile + warm;
            mms[blocks] = mm   # VMEM-overflow tiles fail here
        except Exception:
            continue
    samples = {blocks: [] for blocks in mms}
    for _ in range(rounds):
        for blocks, mm in mms.items():
            try:
                samples[blocks].append(_chain_slope(mm, a, repeats))
            except Exception:
                continue
    best, best_time = None, float("inf")
    for blocks, slopes in samples.items():
        # the median runs over ALL samples and must be positive with a
        # positive MAJORITY: filtering negatives first would let a
        # jitter-swamped tile win on its two tiny surviving samples —
        # the nonsense-slope crowning this function exists to prevent
        positive = sum(1 for s in slopes if s > 0)
        if not slopes or positive < len(slopes) // 2 + 1:
            continue
        med = float(_numpy.median(slopes))
        if med <= 0:
            continue
        if med < best_time:
            best, best_time = blocks, med
    if best is None:
        import logging
        logging.getLogger("veles_tpu.autotune").warning(
            "autotune_matmul: no tile produced a positive timing "
            "slope (size=%d dtype=%s); falling back to %s and NOT "
            "persisting", size, jnp.dtype(dtype).name, _DEFAULT_BLOCKS)
        return _DEFAULT_BLOCKS
    device_info.put(key, list(best))
    return best
