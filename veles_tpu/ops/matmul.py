"""Tiled Pallas matmul with precision levels.

TPU-native counterpart of the reference's flagship kernel family
(reference: ocl/matrix_multiplication.cl:1, matrix_multiplication_precise
.cl:47-185, cuda equivalents).  The reference tiles into shared memory
with BLOCK_SIZE x BLOCK_SIZE tiles and offers PRECISION_LEVEL
0 (plain) / 1 (Kahan) / 2 (multi-partial) accumulation.

Design mapping (SURVEY.md section 7, hard part 7):

- Tiling targets the MXU through ``jnp.dot(..., preferred_element_type=
  float32)`` over VMEM-resident blocks; the grid walks (M/bm, N/bn) with
  the K loop inside the kernel accumulating in an f32 VMEM scratch.
- PRECISION_LEVEL 0 ("plain", fastest): f32 inputs run a bf16x3
  decomposition (a_hi@b_hi + a_hi@b_lo + a_lo@b_hi) — f32-class
  products (~5e-7 max rel err measured on chip vs an f64 oracle) at
  ~2x the throughput of the MXU's 6-pass true-f32 path (53 vs 25
  TFLOP/s measured on v5e at 3001^2); accumulation is always f32.
- Level 1 pays for true-f32 products (HIGHEST) plus Kahan
  compensation across K-tile partial sums.
- Level 2 adds Neumaier (improved Kahan) compensation, the analog of
  the reference's multi-partial summation.  The speed/digits ladder
  mirrors the reference's (config.py:245-248: each level costs more).

Tile sizes come from the per-chip autotune table
(veles_tpu.backends.DeviceInfo), the analog of devices/device_infos.json.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops import common as _common
from veles_tpu.ops.common import (ceil_mult, interpret_for,
                                   mxu_partial_dot, pad_to,
                                   tpu_compiler_params, unpad)

__all__ = ["matmul", "matmul_benchmark", "autotune_matmul",
           "MATMUL_KERNEL_VERSION"]

_DEFAULT_BLOCKS = (512, 512, 512)

#: bump when the kernel's algorithm changes: persisted autotune tables
#: and measured-ceiling entries are only valid for the algorithm they
#: were measured on (v2 = bf16x3 level-0 f32 path; v1 entries in old
#: caches are ignored, not silently served)
MATMUL_KERNEL_VERSION = 2


def _matmul_kernel(a_ref, b_ref, out_ref, acc_ref, comp_ref,
                   *, n_k, precision_level):
    """One (i, j, k) grid step: acc += A[i,k] @ B[k,j].

    ``acc_ref`` is the f32 accumulator scratch; ``comp_ref`` carries the
    Kahan/Neumaier compensation for precision levels 1/2.
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        if precision_level > 0:
            comp_ref[:] = jnp.zeros_like(comp_ref)

    # f32 multiply precision maps the reference's speed/accuracy ladder
    # onto the MXU's pass structure (the PRODUCT step is the shared
    # common.mxu_partial_dot, so the conv-VJP wgrad kernel and this one
    # cannot drift): level 0 ("plain", fastest) = bf16x3 decomposition
    # for f32 inputs, levels 1/2 pay for HIGHEST = 6 passes (true-f32
    # products) plus Kahan/Neumaier accumulation — like the reference,
    # each level trades speed for digits (config.py:245-248).
    partial = mxu_partial_dot(a_ref[:], b_ref[:], precision_level)
    if precision_level == 0:
        acc_ref[:] += partial
    elif precision_level == 1:
        # Kahan: y = partial - c; t = acc + y; c = (t - acc) - y
        y = partial - comp_ref[:]
        t = acc_ref[:] + y
        comp_ref[:] = (t - acc_ref[:]) - y
        acc_ref[:] = t
    else:
        # Neumaier: compensation works for |partial| > |acc| too
        acc = acc_ref[:]
        t = acc + partial
        big = jnp.abs(acc) >= jnp.abs(partial)
        comp_ref[:] += jnp.where(big, (acc - t) + partial,
                                 (partial - t) + acc)
        acc_ref[:] = t

    @pl.when(k == n_k - 1)
    def _store():
        total = acc_ref[:]
        if precision_level == 2:
            total = total + comp_ref[:]
        out_ref[:] = total.astype(out_ref.dtype)


def matmul(a, b, precision_level=0, blocks=None, out_dtype=None):
    """``a @ b`` through the Pallas tiled kernel.

    a: (M, K), b: (K, N).  Inputs may be float32 or bfloat16; the MXU
    accumulates in float32 regardless.

    ``precision_level`` trades digits for speed (the reference's
    PRECISION_LEVEL ladder).  Level 0 (default, fastest) computes
    float32 products via a bf16x3 decomposition on the MXU: ~5e-7 max
    relative error vs an f64 oracle (f32-class results) at ~2x the
    true-f32 throughput, BUT operands with |x| >= bf16 max (~3.39e38)
    or inf land outside the decomposition's domain and produce NaN.
    For inputs that large — or when bit-exact f32 products matter —
    use level 1 (true-f32 HIGHEST products + Kahan accumulation) or
    level 2 (adds Neumaier compensation).  bfloat16 inputs are
    unaffected: they always take single-pass MXU products.

    A thin eager wrapper around the jitted kernel: the interpret-mode
    decision needs the CONCRETE operand placement (CPU-committed arrays
    on a TPU-default host must interpret), which is invisible once
    everything is a tracer inside one jit.

    ``blocks=None`` consults the tuned schedule cache (docs/kernels.md
    "Autotuning": digest-keyed per padded shape/dtype/precision/device)
    before falling back to the static ``_DEFAULT_BLOCKS`` — tiles
    change the SCHEDULE, never the math, and a corrupt cache entry
    degrades to the static table with a warning.

    Debug guard (docs/health.md): set ``VELES_DEBUG_NONFINITE=1`` and
    every eager call validates its output, raising FloatingPointError
    with per-operand stats when inf/NaN appears — the level-0 bf16x3
    decomposition silently maps ``|x| >= bf16-max`` (and inf) to NaN,
    which otherwise surfaces only steps later as a skipped update.
    The check forces a device sync per call, so it is opt-in and for
    debugging only.
    """
    if blocks is None:
        blocks = _tuned_blocks(a, b, precision_level)
    out = _matmul_jit(a, b, precision_level, blocks, out_dtype,
                      interpret_for(a, b))
    # read live from ops.common — ONE patch point for every kernel's
    # guard (conv_vjp reads the same flag), per common.py's contract
    if _common.DEBUG_NONFINITE:
        _debug_check_finite(a, b, out, precision_level)
    return out


def _tuned_blocks(a, b, precision_level):
    """Schedule-cache consult for a ``blocks=None`` call: the tuned
    (bm, bn, bk) for this (padded shape, dtype, precision, device) or
    None (-> ``_DEFAULT_BLOCKS``).  Works on tracers too — only shapes
    and dtypes are read — so the consult happens at TRACE time inside
    an outer jit (e.g. the fused train step's lowering, which is how
    ``tune/walk.py`` records the shapes a step actually uses)."""
    if (getattr(a, "ndim", None) != 2 or getattr(b, "ndim", None) != 2
            or a.shape[1] != b.shape[0]):
        return None
    m, k = a.shape
    n = b.shape[1]
    if not (m and k and n):
        return None
    from veles_tpu.tune.cache import schedule_for
    from veles_tpu.tune.spec import matmul_spec, valid_schedule
    spec = matmul_spec(m, k, n, jnp.dtype(a.dtype).name,
                       precision_level)
    schedule = schedule_for(spec["op"], spec["shape"], spec["dtype"],
                            spec["precision_level"], spec["extra"],
                            raw=spec["raw"])
    if schedule is None:
        return None
    normalized = valid_schedule("matmul", schedule)
    return tuple(normalized["blocks"]) if normalized else None


def _operand_stats(name, x):
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return "%s: %s %s" % (name, x.shape, x.dtype)
    finite = jnp.isfinite(x)
    n_bad = int(jnp.sum(~finite))
    finite_abs = jnp.where(finite, jnp.abs(x), 0.0)
    return ("%s: %s %s, %d non-finite, max|finite| %.6g" %
            (name, x.shape, x.dtype, n_bad, float(jnp.max(finite_abs))
             if x.size else 0.0))


def _debug_check_finite(a, b, out, precision_level):
    if not bool(jnp.isfinite(out).all()):
        bf16_max = float(jnp.finfo(jnp.bfloat16).max)
        hint = ""
        if (precision_level == 0 and jnp.asarray(a).dtype ==
                jnp.float32 and bool(jnp.isfinite(a).all()) and
                bool(jnp.isfinite(b).all())):
            hint = (" — operands are finite, so this is the level-0 "
                    "bf16x3 domain limit (|x| >= %.4g maps to NaN); "
                    "use precision_level >= 1 for operands this large"
                    % bf16_max)
        raise FloatingPointError(
            "matmul produced non-finite output (%s)%s" % (
                "; ".join((_operand_stats("lhs", a),
                           _operand_stats("rhs", b),
                           _operand_stats("out", out))), hint))


@functools.partial(
    jax.jit, static_argnames=("precision_level", "blocks", "out_dtype",
                              "interpret"))
def _matmul_jit(a, b, precision_level, blocks, out_dtype, interpret):
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError("matmul expects 2-D operands")
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError("shape mismatch: %s @ %s" % (a.shape, b.shape))
    out_dtype = out_dtype or a.dtype
    if m == 0 or n == 0 or k == 0:
        return jnp.zeros((m, n), out_dtype)
    bm, bn, bk = blocks or _DEFAULT_BLOCKS
    bm, bn, bk = (min(bm, ceil_mult(m, 8)), min(bn, ceil_mult(n, 128)),
                  min(bk, ceil_mult(k, 128)))
    a = pad_to(a, (bm, bk))
    b = pad_to(b, (bk, bn))
    mp, kp = a.shape
    _, np_ = b.shape
    n_k = kp // bk
    grid = (mp // bm, np_ // bn, n_k)

    out = pl.pallas_call(
        functools.partial(_matmul_kernel, n_k=n_k,
                          precision_level=precision_level),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((bm, bn), jnp.float32),
            pltpu.VMEM((bm, bn), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)
    return unpad(out, (m, n))


def _chain_slope(mm, a, repeats):
    """One (chain(repeats+1) - chain(1)) / repeats slope sample over
    dependent ``acc = mm(acc)`` chains ended by a scalar fetch — the
    benchmark facade's sampling.  The autotuner runs the SAME chains
    through ``tune/measure.py`` (``slope_sample`` over the matmul
    family's dependent-chain runner), so the two cannot drift on
    methodology; this local helper only serves ``matmul_benchmark``'s
    one-shot power-rating path."""
    import time

    def chain(n):
        start = time.perf_counter()
        acc = a
        for _ in range(n):
            acc = mm(acc)
        float(acc[0, 0].astype(jnp.float32))
        return time.perf_counter() - start

    return (chain(repeats + 1) - chain(1)) / repeats


def matmul_benchmark(size=3001, dtype=jnp.float32, precision_level=0,
                     repeats=10, blocks=None, samples=1):
    """Time the kernel on an NxN self-multiply — the same measurement the
    reference's autotuner and DeviceBenchmark unit make
    (reference: ocl/benchmark.cl:1-11, accelerated_units.py:706).

    Measured as the slope between a 1-long and an (repeats+1)-long
    DEPENDENT chain, each ended by a scalar fetch: dispatch/tunnel
    latency cancels, pure device time per matmul remains.  With
    ``samples`` > 1 the median of that many slopes is returned — single
    slopes are noisy enough on tunneled devices to go non-positive, so
    rank-sensitive callers (the autotuner) raise it; the one-shot
    default keeps the client power-rating handshake cheap.

    Returns the RAW slope, which may be zero or negative when tunnel
    jitter swamps the chain delta.  Callers must validate and discard
    non-positive samples (never clamp: a floored nonsense slope once
    crowned the wrong autotune tile and published an impossible rate).
    """
    import numpy
    a = jnp.asarray(
        (numpy.random.RandomState(13).rand(size, size) - 0.5) * 0.01,
        dtype=dtype)

    def mm(x):
        return matmul(x, a, precision_level=precision_level,
                      blocks=blocks)

    float(mm(a)[0, 0])  # compile + warmup

    slopes = sorted(_chain_slope(mm, a, repeats)
                    for _ in range(samples))
    mid = samples // 2
    return (slopes[mid] if samples % 2
            else (slopes[mid - 1] + slopes[mid]) / 2.0)


def autotune_matmul(device_info, size=2048, dtype=jnp.float32,
                    precision_level=0):
    """Pick the best block config for this chip and persist it
    (analog of reference backends.py:672-731 _find_optimal_bs_vo).

    Rewired onto the shared tune machinery (ONE measurement
    discipline, ONE persistence path, docs/kernels.md "Autotuning"):
    the curated candidate list lives in
    ``tune.spec.matmul_seed_candidates`` — where it also seeds the
    GA's population — and the sweep runs through
    ``tune.autotune.sweep_candidates``: round-robin interleaved
    chain-slope samples (whole-chip congestion drifts minute to
    minute, ~1.4x swings measured; timing each tile's samples back to
    back lets a congestion window crown the wrong tile), ranked under
    the positive-majority-median rule (a floor-clamped nonsense slope
    once crowned the wrong tile and published an impossible rate).
    VMEM-overflow tiles fail at the warm-up compile and are skipped.
    The winner persists in the digest-keyed ScheduleCache — the SAME
    entry ``matmul()`` consults for ``blocks=None`` calls of this
    padded shape — keyed by padded shape (tile optima don't transfer
    between shapes) and kernel version (optima measured on an old
    algorithm must never serve a new one).  When every tile's timing
    is jitter-swamped: fall back to ``_DEFAULT_BLOCKS`` and do NOT
    persist."""
    from veles_tpu.tune.autotune import sweep_candidates
    from veles_tpu.tune.cache import cache_for, schedule_key
    from veles_tpu.tune.spec import (matmul_seed_candidates,
                                     matmul_spec, valid_schedule)

    dtype_name = jnp.dtype(dtype).name
    spec = matmul_spec(size, size, size, dtype_name, precision_level)
    kind = device_info.device_kind
    digest, payload = schedule_key(
        spec["op"], spec["shape"], spec["dtype"],
        spec["precision_level"], kind, spec["extra"])
    cache = cache_for()
    entry = cache.get(digest)
    if entry is not None:
        normalized = valid_schedule("matmul", entry["schedule"])
        if normalized is not None:
            return tuple(normalized["blocks"])
    # the shipped per-chip table (devices/device_infos.json, the old
    # persistence path) still holds measured winners for the headline
    # sizes — migrate a hit into the schedule cache instead of paying
    # a fresh sweep on every fresh host
    legacy = device_info.get("matmul:v%d:%s:pl%d:s%d" % (
        MATMUL_KERNEL_VERSION, dtype_name, precision_level, size))
    if legacy is not None:
        normalized = valid_schedule(
            "matmul", {"blocks": [int(b) for b in legacy]})
        if normalized is not None:
            cache.put(digest, payload, normalized,
                      source="device_info")
            return tuple(normalized["blocks"])
    candidates = [{"blocks": list(c)} for c in
                  matmul_seed_candidates(dtype_name, precision_level)]
    # repeats=24: short chains (~8) can INVERT tile rankings on a
    # tunneled chip — a config measured 192 TF over 20-step chains
    # sustained only 86 TF over 100-step ones while the true winner
    # sustained 135
    best, _ranking = sweep_candidates(
        spec, candidates, repeats=24, rounds=5, device_kind=kind,
        cache=cache)
    if best is None:
        import logging
        logging.getLogger("veles_tpu.autotune").warning(
            "autotune_matmul: no tile produced a positive timing "
            "slope (size=%d dtype=%s); falling back to %s and NOT "
            "persisting", size, dtype_name, _DEFAULT_BLOCKS)
        return _DEFAULT_BLOCKS
    return tuple(best["blocks"])
