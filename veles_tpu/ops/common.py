"""Shared helpers for the Pallas kernel set.

This module is also the kernels' ONE env contract: the interpret-mode
decision (``interpret_mode``/``interpret_for``), the opt-in non-finite
debug guard (``DEBUG_NONFINITE`` <- ``VELES_DEBUG_NONFINITE``), and the
hand-scheduled-backward knob (``PALLAS_BWD_ENV`` <-
``VELES_PALLAS_BWD``) all live here so matmul, conv-VJP and pool-bwd
kernels cannot drift apart on how they read the environment.  All env
vars are read ONCE at import; tests monkeypatch the module flags
directly.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as _pltpu

__all__ = ["interpret_mode", "interpret_for", "pad_to", "unpad", "kernel_cast",
           "ceil_mult", "tpu_compiler_params", "mxu_partial_dot",
           "mxu_int8_dot", "pallas_bwd_enabled", "DEBUG_NONFINITE",
           "PALLAS_BWD_ENV"]

#: opt-in per-call output validation (docs/health.md); the check forces
#: a device sync per eager kernel call, so it is for debugging only
DEBUG_NONFINITE = os.environ.get(
    "VELES_DEBUG_NONFINITE", "") not in ("", "0")

#: VELES_PALLAS_BWD: "" / "auto" -> hand-scheduled backward on real TPU
#: backends only; "0" -> always the stock autodiff backward (bit-exact
#: fallback contract, docs/kernels.md); anything else -> always on
#: (CPU parity tests run the kernels through the Pallas interpreter)
PALLAS_BWD_ENV = os.environ.get("VELES_PALLAS_BWD", "")


def pallas_bwd_enabled():
    """One resolution of the VELES_PALLAS_BWD knob for every caller
    (models/conv.py, models/pooling.py, the gd units, compiler.py).

    Reads the module flag, not the environment — the env was read once
    at import, and tests flip ``common.PALLAS_BWD_ENV`` directly."""
    env = PALLAS_BWD_ENV
    if env in ("", "auto"):
        try:
            return jax.default_backend() == "tpu"
        except Exception:
            return False
    return env != "0"

#: jax renamed TPUCompilerParams -> CompilerParams across releases;
#: resolve whichever this jax ships so the kernels run on both
tpu_compiler_params = getattr(
    _pltpu, "CompilerParams", None) or getattr(
    _pltpu, "TPUCompilerParams")


def kernel_cast(x, dtype):
    """dtype cast safe inside Mosaic kernels: narrow ints widen to int32
    first (Mosaic has no direct narrow-int -> float lowering)."""
    if (jnp.issubdtype(x.dtype, jnp.integer) and
            jnp.issubdtype(dtype, jnp.floating) and
            x.dtype.itemsize < 4):
        x = x.astype(jnp.int32)
    return x.astype(dtype)


@functools.lru_cache(maxsize=None)
def interpret_mode():
    """True when running on a backend without Mosaic (CPU tests): Pallas
    kernels then execute in interpreter mode, same numerics."""
    return jax.default_backend() == "cpu"


def interpret_for(*arrays):
    """Per-call interpret decision: Pallas needs the interpreter whenever
    the operand actually lives on CPU, whatever the process default
    backend is (a TPU host can still run CPU-device workflows).  Tracers
    carry no placement — fall back to the default-backend rule."""
    for x in arrays:
        devices = getattr(x, "devices", None)
        if devices is None:
            continue
        try:
            return any(d.platform == "cpu" for d in devices())
        except Exception:
            continue
    return interpret_mode()


def mxu_partial_dot(a, b, precision_level):
    """One MXU tile product ``a @ b`` -> f32 partial, the single
    definition of the precision ladder's PRODUCT step shared by the
    matmul kernel and the conv-VJP wgrad kernel (the ACCUMULATION step
    — plain / Kahan / Neumaier — stays with each kernel's scratch).

    Level 0 on f32 inputs runs the bf16x3 decomposition (a_hi@b_hi +
    a_hi@b_lo + a_lo@b_hi): ~5e-7 max rel err vs an f64 oracle at ~2x
    the MXU's 6-pass true-f32 throughput.  |x| >= bf16-max (~3.39e38)
    and inf map to NaN — out of the decomposition's domain.  Levels
    1/2 pay for HIGHEST (true-f32) products.  bf16 inputs always take
    single-pass DEFAULT products (Mosaic rejects HIGHEST for bf16)."""
    if a.dtype == jnp.float32 and precision_level == 0:
        a_hi = a.astype(jnp.bfloat16)
        b_hi = b.astype(jnp.bfloat16)
        a_lo = (a - a_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        b_lo = (b - b_hi.astype(jnp.float32)).astype(jnp.bfloat16)

        def bf16_dot(x, y):
            return jnp.dot(x, y, preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.DEFAULT)

        return (bf16_dot(a_hi, b_hi) + bf16_dot(a_hi, b_lo)
                + bf16_dot(a_lo, b_hi))
    precision = (jax.lax.Precision.DEFAULT if a.dtype == jnp.bfloat16
                 else jax.lax.Precision.HIGHEST)
    return jnp.dot(a, b, preferred_element_type=jnp.float32,
                   precision=precision)


def mxu_int8_dot(a, b):
    """One MXU tile product ``a @ b`` for int8 operands -> int32
    partial: the quantized level BELOW the f32/bf16 precision ladder
    (docs/kernels.md), shared by the int8 matmul kernel and the int8
    conv forward exactly like :func:`mxu_partial_dot` is shared by the
    f32/bf16 kernels.

    Integer products and sums are exact, so — unlike the float levels —
    tile grouping can never change the result: any schedule of this
    product step accumulates to bit-identical int32 totals, which is
    what makes the int8 kernels' tuned-vs-static and Pallas-vs-
    reference parity contracts *bit*-equalities rather than ULP
    bounds."""
    return jnp.dot(a, b, preferred_element_type=jnp.int32)


def ceil_mult(value, mult):
    """Round ``value`` up to the next multiple of ``mult``."""
    rem = value % mult
    return value if rem == 0 else value + mult - rem


def pad_to(x, multiples):
    """Zero-pad trailing dims of ``x`` up to the given multiples."""
    pads = []
    for dim, mult in zip(x.shape, multiples):
        if mult is None:
            pads.append((0, 0))
        else:
            rem = dim % mult
            pads.append((0, 0 if rem == 0 else mult - rem))
    if any(p[1] for p in pads):
        x = jnp.pad(x, pads)
    return x


def unpad(x, shape):
    if x.shape == tuple(shape):
        return x
    return x[tuple(slice(0, s) for s in shape)]
