"""Shared helpers for the Pallas kernel set."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as _pltpu

__all__ = ["interpret_mode", "interpret_for", "pad_to", "unpad", "kernel_cast",
           "ceil_mult", "tpu_compiler_params"]

#: jax renamed TPUCompilerParams -> CompilerParams across releases;
#: resolve whichever this jax ships so the kernels run on both
tpu_compiler_params = getattr(
    _pltpu, "CompilerParams", None) or getattr(
    _pltpu, "TPUCompilerParams")


def kernel_cast(x, dtype):
    """dtype cast safe inside Mosaic kernels: narrow ints widen to int32
    first (Mosaic has no direct narrow-int -> float lowering)."""
    if (jnp.issubdtype(x.dtype, jnp.integer) and
            jnp.issubdtype(dtype, jnp.floating) and
            x.dtype.itemsize < 4):
        x = x.astype(jnp.int32)
    return x.astype(dtype)


@functools.lru_cache(maxsize=None)
def interpret_mode():
    """True when running on a backend without Mosaic (CPU tests): Pallas
    kernels then execute in interpreter mode, same numerics."""
    return jax.default_backend() == "cpu"


def interpret_for(*arrays):
    """Per-call interpret decision: Pallas needs the interpreter whenever
    the operand actually lives on CPU, whatever the process default
    backend is (a TPU host can still run CPU-device workflows).  Tracers
    carry no placement — fall back to the default-backend rule."""
    for x in arrays:
        devices = getattr(x, "devices", None)
        if devices is None:
            continue
        try:
            return any(d.platform == "cpu" for d in devices())
        except Exception:
            continue
    return interpret_mode()


def ceil_mult(value, mult):
    """Round ``value`` up to the next multiple of ``mult``."""
    rem = value % mult
    return value if rem == 0 else value + mult - rem


def pad_to(x, multiples):
    """Zero-pad trailing dims of ``x`` up to the given multiples."""
    pads = []
    for dim, mult in zip(x.shape, multiples):
        if mult is None:
            pads.append((0, 0))
        else:
            rem = dim % mult
            pads.append((0, 0 if rem == 0 else mult - rem))
    if any(p[1] for p in pads):
        x = jnp.pad(x, pads)
    return x


def unpad(x, shape):
    if x.shape == tuple(shape):
        return x
    return x[tuple(slice(0, s) for s in shape)]
