"""Int8 quantized matmul + conv forward: the MXU's native 8-bit level.

"In-Datacenter Performance Analysis of a TPU" (PAPERS.md) is the
motivation: production inference is a hard-latency, throughput-per-chip
game the MXU wins with 8-bit multipliers — the original TPU's 92 TOPS
were *int8* TOPS.  This module is that level of the precision ladder
(docs/kernels.md): int8 operands, **int32 accumulation** (exact — no
Kahan/Neumaier machinery needed, integer sums cannot lose digits), and
a **fused dequant-rescale epilogue** in the same kernel store that
writes the output tile, so the f32 result never round-trips through
HBM as raw int32.

Layout mirrors ``ops/matmul.py``: the grid walks (M/bm, N/bn) with the
K loop innermost accumulating into an int32 VMEM scratch; the PRODUCT
step is the shared :func:`veles_tpu.ops.common.mxu_int8_dot` (this
kernel and the conv forward cannot drift on it).  Int8 changes the
MXU-legal tile quanta: the minimum native tile is (32, 128) — sublane
32 on the second-minor axis vs f32's 8 — so tiles and padding here
quantize to 32/128 multiples, and the schedule-cache family
(``tune/spec.py`` ``matmul_int8``) carries its own ``kernel_version``
so f32 tiles can never serve an int8 call.

``conv2d_int8`` lowers the conv forward onto the SAME kernel: per-tap
strided slices of the zero-padded input (pure data movement, exact in
the int8 domain) stack into an im2col patch matrix, one
``matmul_int8`` contraction produces the (P, Cout) output, and the
per-output-channel dequant scales + bias ride the shared epilogue.

Numerics contract (tests/test_quant.py): integer accumulation is exact
and the epilogue is the same f32 expression as
:func:`matmul_int8_reference`, so the Pallas kernel (interpret mode on
CPU, Mosaic on TPU) matches the reference **bit-exactly** — the
acceptance bound the quantized serve engine's parity receipt
(QUANT.json) is anchored to.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.common import (ceil_mult, interpret_for,
                                   mxu_int8_dot, pad_to,
                                   tpu_compiler_params, unpad)

__all__ = ["matmul_int8", "matmul_int8_reference", "conv2d_int8",
           "MATMUL_INT8_KERNEL_VERSION", "INT8_SUBLANE"]

#: int8's native MXU tile is (32, 128): the sublane quantum is 32 (vs
#: f32's 8) because four int8 rows pack one 32-bit sublane register
INT8_SUBLANE = 32

#: smaller default M-tile than the f32 kernel: int8 operand tiles are
#: 4x denser per byte, so the VMEM balance shifts toward the f32/int32
#: accumulator, which scales with bm*bn only
_DEFAULT_BLOCKS = (256, 512, 512)

#: bump when the kernel's algorithm changes — persisted tuned schedules
#: are only valid for the algorithm they were measured on (the same
#: contract as MATMUL_KERNEL_VERSION, docs/kernels.md "Autotuning")
MATMUL_INT8_KERNEL_VERSION = 1


def _matmul_int8_kernel(a_ref, b_ref, scale_ref, bias_ref, out_ref,
                        acc_ref, *, n_k):
    """One (i, j, k) grid step: acc += A[i,k] @ B[k,j] in int32; the
    last K step dequantizes: out = f32(acc) * scale[j] + bias[j].

    ``scale_ref``/``bias_ref`` are (1, bn) blocks of the per-output-
    channel dequant scale (activation scale x per-channel weight
    scale) and the f32 bias — fused into the store so the int32
    accumulator never leaves VMEM."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += mxu_int8_dot(a_ref[:], b_ref[:])

    @pl.when(k == n_k - 1)
    def _store():
        total = acc_ref[:].astype(jnp.float32) * scale_ref[:]
        total = total + bias_ref[:]
        out_ref[:] = total.astype(out_ref.dtype)


def matmul_int8(a, b, scale, bias=None, blocks=None,
                out_dtype=jnp.float32):
    """``dequant(a @ b)`` through the int8 Pallas kernel.

    a: (M, K) int8, b: (K, N) int8.  ``scale`` is the combined dequant
    factor — a scalar or an (N,) per-output-channel vector (activation
    scale x per-channel weight scale); ``bias`` an optional (N,) f32
    vector added AFTER dequant (biases stay f32 in post-training
    quantization: they are tiny and quantizing them buys nothing).
    Products accumulate in int32 (exact); the epilogue computes
    ``f32(acc) * scale + bias`` and casts to ``out_dtype``.

    ``blocks=None`` consults the tuned schedule cache under the
    ``matmul_int8`` family (its own kernel version and int8 tile
    quanta — an f32 schedule can never serve this kernel) before the
    static default.  Like :func:`veles_tpu.ops.matmul.matmul` this is
    a thin eager wrapper: the interpret-mode decision needs concrete
    operand placement, so CPU tests run the identical kernel through
    the Pallas interpreter.
    """
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if a.dtype != jnp.int8 or b.dtype != jnp.int8:
        raise TypeError("matmul_int8 expects int8 operands, got %s @ %s"
                        % (a.dtype, b.dtype))
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError("matmul_int8 expects 2-D operands")
    n = b.shape[1]
    scale = jnp.asarray(scale, jnp.float32)
    if scale.ndim == 0:
        scale = jnp.full((n,), scale, jnp.float32)
    if scale.shape != (n,):
        raise ValueError("scale must be scalar or (N,)=(%d,), got %s"
                         % (n, scale.shape))
    if bias is None:
        bias = jnp.zeros((n,), jnp.float32)
    else:
        bias = jnp.asarray(bias, jnp.float32)
        if bias.shape != (n,):
            raise ValueError("bias must be (N,)=(%d,), got %s"
                             % (n, bias.shape))
    if blocks is None:
        blocks = _tuned_blocks(a, b)
    return _matmul_int8_jit(a, b, scale, bias, blocks,
                            jnp.dtype(out_dtype).name,
                            interpret_for(a, b))


def _tuned_blocks(a, b):
    """Schedule-cache consult for a ``blocks=None`` call (tracer-safe:
    shapes only) — the tuned (bm, bn, bk) for this padded int8 shape
    or None (-> ``_DEFAULT_BLOCKS``)."""
    if (getattr(a, "ndim", None) != 2 or getattr(b, "ndim", None) != 2
            or a.shape[1] != b.shape[0]):
        return None
    m, k = a.shape
    n = b.shape[1]
    if not (m and k and n):
        return None
    from veles_tpu.tune.cache import schedule_for
    from veles_tpu.tune.spec import matmul_int8_spec, valid_schedule
    spec = matmul_int8_spec(m, k, n)
    schedule = schedule_for(spec["op"], spec["shape"], spec["dtype"],
                            spec["precision_level"], spec["extra"],
                            raw=spec["raw"])
    if schedule is None:
        return None
    normalized = valid_schedule("matmul_int8", schedule)
    return tuple(normalized["blocks"]) if normalized else None


@functools.partial(
    jax.jit, static_argnames=("blocks", "out_dtype", "interpret"))
def _matmul_int8_jit(a, b, scale, bias, blocks, out_dtype, interpret):
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError("shape mismatch: %s @ %s" % (a.shape, b.shape))
    if m == 0 or n == 0 or k == 0:
        return jnp.broadcast_to(bias[None, :], (m, n)).astype(out_dtype)
    bm, bn, bk = blocks or _DEFAULT_BLOCKS
    bm = min(bm, ceil_mult(m, INT8_SUBLANE))
    bn = min(bn, ceil_mult(n, 128))
    bk = min(bk, ceil_mult(k, 128))
    a = pad_to(a, (bm, bk))
    b = pad_to(b, (bk, bn))
    scale2 = pad_to(scale[None, :], (None, bn))
    bias2 = pad_to(bias[None, :], (None, bn))
    mp, kp = a.shape
    _, np_ = b.shape
    n_k = kp // bk
    grid = (mp // bm, np_ // bn, n_k)

    out = pl.pallas_call(
        functools.partial(_matmul_int8_kernel, n_k=n_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b, scale2, bias2)
    return unpad(out, (m, n))


def matmul_int8_reference(a, b, scale, bias=None,
                          out_dtype=jnp.float32):
    """The untiled reference the kernel must match BIT-exactly: one
    int32 dot, the identical f32 dequant expression.  Integer
    accumulation is exact under any tile grouping and the epilogue
    applies the same elementwise ops in the same order, so equality is
    bitwise, not a ULP bound (tests/test_quant.py asserts it).

    Compare under ``jax.jit``: XLA contracts the epilogue's mul+add
    into an FMA inside compiled programs (the kernel always runs
    compiled), so the JITTED reference is the bit-exact twin; the
    eager reference can differ by 1 ulp where the FMA rounds once."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    n = b.shape[1]
    scale = jnp.asarray(scale, jnp.float32)
    if scale.ndim == 0:
        scale = jnp.full((n,), scale, jnp.float32)
    if bias is None:
        bias = jnp.zeros((n,), jnp.float32)
    acc = mxu_int8_dot(a, b)
    total = acc.astype(jnp.float32) * scale[None, :]
    total = total + jnp.asarray(bias, jnp.float32)[None, :]
    return total.astype(out_dtype)


def conv2d_int8(x, w, scale, bias=None, padding=(0, 0, 0, 0),
                sliding=(1, 1), blocks=None, out_dtype=jnp.float32):
    """Int8 conv forward through the SAME shared product step: per-tap
    strided slices of the zero-padded input stack into an im2col patch
    matrix (pure data movement — exact in the int8 domain; the f32
    conv's zero padding quantizes to int8 zero, so semantics match),
    then ONE ``matmul_int8`` contraction with the per-Cout dequant
    scales and bias fused into its epilogue.

    x: (N, H, W, Cin) int8, w: (ky, kx, Cin, Cout) int8 (HWIO, the
    layout ``models/conv.py`` trains in); ``scale`` scalar or (Cout,);
    ``padding`` = (left, top, right, bottom), ``sliding`` = (sx, sy) —
    the Conv unit's static config, verbatim.  Returns (N, OH, OW,
    Cout) in ``out_dtype``.  The tap loop unrolls at trace time into
    ky*kx slices, mirroring how ``ops/conv_vjp.py`` walks taps in its
    wgrad grid."""
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    if x.ndim == 3:
        x = x[..., None]
    if x.dtype != jnp.int8 or w.dtype != jnp.int8:
        raise TypeError("conv2d_int8 expects int8 operands, got %s / %s"
                        % (x.dtype, w.dtype))
    n, h, w_sp, ci = x.shape
    ky, kx, ci2, cout = w.shape
    if ci != ci2:
        raise ValueError("channel mismatch: x %s vs w %s" %
                         (x.shape, w.shape))
    left, top, right, bottom = padding
    sx, sy = sliding
    xp = jnp.pad(x, ((0, 0), (top, bottom), (left, right), (0, 0)))
    oh = (h + top + bottom - ky) // sy + 1
    ow = (w_sp + left + right - kx) // sx + 1
    taps = []
    for dy in range(ky):
        for dx in range(kx):
            taps.append(xp[:, dy:dy + (oh - 1) * sy + 1:sy,
                           dx:dx + (ow - 1) * sx + 1:sx, :])
    patches = jnp.concatenate(taps, axis=-1)      # tap-major, then Cin
    patches = patches.reshape(n * oh * ow, ky * kx * ci)
    z = matmul_int8(patches, w.reshape(ky * kx * ci, cout), scale,
                    bias=bias, blocks=blocks, out_dtype=out_dtype)
    return z.reshape(n, oh, ow, cout)
