"""Minibatch gather from a device-resident dataset.

TPU-native counterpart of reference ocl/fullbatch_loader.cl:5-50 /
cuda/fullbatch_loader.cu: ``minibatch[i] = dataset[indices[i]]`` with an
on-the-fly dtype cast, plus label gathering.  Implemented with
``PrefetchScalarGridSpec`` — the shuffled indices are scalar-prefetched so
the BlockSpec index_map can route each grid step's DMA straight to the
right dataset row, which is the idiomatic TPU version of the reference's
index-chasing kernel.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.common import interpret_for, kernel_cast

__all__ = ["gather_minibatch", "gather_labels"]


def _gather_kernel(idx_ref, data_ref, out_ref):
    out_ref[:] = kernel_cast(data_ref[:], out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def gather_minibatch(dataset, indices, out_dtype=None):
    """Gather rows: (N, F...) x (B,) -> (B, F...) with dtype cast.

    ``dataset`` stays in HBM/ANY; each grid step DMAs one sample row into
    VMEM addressed by the prefetched index.
    """
    out_dtype = out_dtype or dataset.dtype
    batch = indices.shape[0]
    sample_shape = dataset.shape[1:]
    flat = dataset.reshape(dataset.shape[0], -1)
    width = flat.shape[1]
    if width % 128:
        # Padding the whole dataset per call would be an O(N*F) copy per
        # step; lane-unaligned sample widths take XLA's native gather
        # instead.  FullBatchLoader stores its dataset lane-aligned so
        # the DMA path below is the common case.
        return jnp.take(flat, indices, axis=0).astype(out_dtype).reshape(
            (batch,) + sample_shape)
    wp = width
    flat = flat.reshape(flat.shape[0], 1, wp)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch,),
        in_specs=[
            pl.BlockSpec((1, 1, wp),
                         lambda i, idx_ref: (idx_ref[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, wp), lambda i, idx_ref: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, 1, wp), out_dtype),
        interpret=interpret_for(flat),
    )(indices.astype(jnp.int32), flat)
    return out[:, 0, :width].reshape((batch,) + sample_shape)


@jax.jit
def gather_labels(labels, indices):
    """Label gather; labels are small, XLA's native gather is optimal."""
    return jnp.take(labels, indices, axis=0)
