"""Accelerated ops: Pallas TPU kernels for the reference's kernel set.

Reference kernel inventory (SURVEY.md section 2.4, ocl/ + cuda/ sources)
and its TPU-native disposition:

===========================  ===========================================
reference kernel              here
===========================  ===========================================
matrix_multiplication (.cl)   ops.matmul — tiled Pallas matmul, MXU,
                              precision levels 0/1/2
gemm.cl                       ops.blas.gemm — alpha*A*B + beta*C facade
matrix_reduce.cl              ops.reduce — row/col tree reductions
fullbatch_loader.cl           ops.gather — minibatch index gather
random.cl (xorshift)          ops.random — xorshift128+/1024* bit-exact,
                              plus idiomatic hardware PRNG path
mean_disp_normalizer.cl       ops.normalize
join.jcl                      ops.join
benchmark.cl                  ops.benchmark (autotune + power rating)
(gradient kernels, new)       ops.conv_vjp — fused conv-VJP family
                              (epilogue+bias+wgrad Pallas kernel,
                              lhs-dilated dgrad); ops.pool_bwd —
                              max-pool select-and-scatter backward
                              (docs/kernels.md, VELES_PALLAS_BWD)
===========================  ===========================================
"""

from veles_tpu.ops.matmul import matmul  # noqa: F401
from veles_tpu.ops.conv_vjp import conv_act, fused_conv_vjp  # noqa: F401
from veles_tpu.ops.pool_bwd import max_pool, max_pool_bwd  # noqa: F401
from veles_tpu.ops.blas import gemm  # noqa: F401
from veles_tpu.ops.reduce import reduce_rows, reduce_cols  # noqa: F401
from veles_tpu.ops.gather import gather_minibatch, gather_labels  # noqa: F401
from veles_tpu.ops.normalize import mean_disp_normalize  # noqa: F401
from veles_tpu.ops.join import join  # noqa: F401
