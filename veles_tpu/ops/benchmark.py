"""Device benchmark: matmul self-multiply timing.

TPU-native counterpart of reference ocl/benchmark.cl:1-11 and the
DeviceBenchmark unit (reference: accelerated_units.py:706,768-778) used
for (a) kernel autotuning and (b) the "computing power" rating that load-
balances job farming across heterogeneous workers.
"""

from veles_tpu.ops.matmul import autotune_matmul, matmul_benchmark

__all__ = ["estimate_computing_power", "matmul_benchmark",
           "autotune_matmul"]


def estimate_computing_power(size=1024, repeats=3):
    """1000 / avg-matmul-seconds, the reference's arbitrary power unit."""
    elapsed = matmul_benchmark(size=size, repeats=repeats)
    return 1000.0 / max(elapsed, 1e-9)
