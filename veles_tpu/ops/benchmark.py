"""Device benchmark: matmul self-multiply timing.

TPU-native counterpart of reference ocl/benchmark.cl:1-11 and the
DeviceBenchmark unit (reference: accelerated_units.py:706,768-778) used
for (a) kernel autotuning and (b) the "computing power" rating that load-
balances job farming across heterogeneous workers.
"""

from veles_tpu.ops.matmul import autotune_matmul, matmul_benchmark

__all__ = ["estimate_computing_power", "matmul_benchmark",
           "autotune_matmul"]


def estimate_computing_power(size=1024, repeats=3):
    """1000 / avg-matmul-seconds, the reference's arbitrary power unit.

    An implausible slope (tunnel jitter swamping the chain delta) is
    remeasured with a longer chain; if it never becomes credible the
    rating fails loudly — a clamped nonsense rating would skew the
    master's load balancing invisibly.  Credible means implying a
    rate below 1 PFLOP/s for the measured shape: a bare ``> 0`` check
    passes microsecond jitter slopes and publishes the same invisible
    skew the loud-failure path exists to prevent."""
    min_credible_s = 2.0 * size ** 3 / 1e15
    for scale in (1, 4, 16):
        elapsed = matmul_benchmark(size=size, repeats=repeats * scale)
        if elapsed >= min_credible_s:
            return 1000.0 / elapsed
    raise RuntimeError(
        "estimate_computing_power: matmul timing slope stayed below "
        "the minimum credible time (%.3g s for a %d^3 matmul) after "
        "remeasurement; refusing to publish a power rating from "
        "noise" % (min_credible_s, size))
