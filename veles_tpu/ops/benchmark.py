"""Device benchmark: matmul self-multiply timing.

TPU-native counterpart of reference ocl/benchmark.cl:1-11 and the
DeviceBenchmark unit (reference: accelerated_units.py:706,768-778) used
for (a) kernel autotuning and (b) the "computing power" rating that load-
balances job farming across heterogeneous workers.
"""

from veles_tpu.ops.matmul import autotune_matmul, matmul_benchmark

__all__ = ["estimate_computing_power", "matmul_benchmark",
           "autotune_matmul"]


def estimate_computing_power(size=1024, repeats=3):
    """1000 / avg-matmul-seconds, the reference's arbitrary power unit.

    A non-positive slope (tunnel jitter swamping the chain delta) is
    remeasured with a longer chain; if it stays non-positive the
    rating fails loudly — a clamped nonsense rating would skew the
    master's load balancing invisibly."""
    for scale in (1, 4, 16):
        elapsed = matmul_benchmark(size=size, repeats=repeats * scale)
        if elapsed > 0:
            return 1000.0 / elapsed
    raise RuntimeError(
        "estimate_computing_power: matmul timing slope stayed "
        "non-positive after remeasurement; refusing to publish a "
        "power rating from noise")
