"""Matrix row/column reductions.

TPU-native counterpart of reference ocl/matrix_reduce.cl:1-69 (shared-
memory tree reduction templated over row/column mode).  On TPU the VPU
reduces a VMEM block natively; the kernel tiles the reduced axis and
accumulates partials in scratch, which is the same two-stage tree the
reference builds by hand.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.common import (ceil_mult, interpret_for, pad_to,
                                   tpu_compiler_params)

__all__ = ["reduce_rows", "reduce_cols"]


def _reduce_cols_kernel(in_ref, out_ref, acc_ref, *, n_k):
    """Sum over rows (axis 0): out[j] = sum_i in[i, j]."""
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.sum(in_ref[:], axis=0, keepdims=True,
                          dtype=jnp.float32)

    @pl.when(k == n_k - 1)
    def _store():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block",))
def reduce_cols(x, block=512):
    """Column sums: (M, N) -> (1, N)."""
    m, n = x.shape
    bm = min(block, ceil_mult(m, 8))
    x = pad_to(x, (bm, 128))
    mp, np_ = x.shape
    n_k = mp // bm
    out = pl.pallas_call(
        functools.partial(_reduce_cols_kernel, n_k=n_k),
        grid=(n_k,),
        in_specs=[pl.BlockSpec((bm, np_), lambda k: (k, 0))],
        out_specs=pl.BlockSpec((1, np_), lambda k: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, np_), x.dtype),
        scratch_shapes=[pltpu.VMEM((1, np_), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret_for(x),
    )(x)
    return out[:, :n]


def _reduce_rows_kernel(in_ref, out_ref, acc_ref, *, n_k):
    """Sum over columns (axis 1): out[i] = sum_j in[i, j]."""
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.sum(in_ref[:], axis=1, keepdims=True,
                          dtype=jnp.float32)

    @pl.when(k == n_k - 1)
    def _store():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block",))
def reduce_rows(x, block=512):
    """Row sums: (M, N) -> (M, 1)."""
    m, n = x.shape
    bn = min(block, ceil_mult(n, 128))
    x = pad_to(x, (8, bn))
    mp, np_ = x.shape
    n_k = np_ // bn
    out = pl.pallas_call(
        functools.partial(_reduce_rows_kernel, n_k=n_k),
        grid=(n_k,),
        in_specs=[pl.BlockSpec((mp, bn), lambda k: (0, k))],
        out_specs=pl.BlockSpec((mp, 1), lambda k: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, 1), x.dtype),
        scratch_shapes=[pltpu.VMEM((mp, 1), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret_for(x),
    )(x)
    return out[:m]


