"""GEMM facade over the tiled Pallas matmul.

TPU-native counterpart of reference ocl/gemm.cl:1-14 and the OCLBLAS
CUBLAS-compatible wrapper (reference: veles/ocl_blas.py:77,187-236):
``C = alpha * op(A) @ op(B) + beta * C`` with transpose flags.
Kernel compilation caching per (transA, transB, shapes, dtype) is XLA's
jit cache — no hand-rolled binary cache is needed on TPU.

Numerics: the default ``precision_level=0`` computes f32 products via
the kernel's bf16x3 decomposition (~5e-7 max rel err vs f64, ~2x
faster than true-f32 MXU passes); pass ``precision_level=1`` for
CUBLAS-equivalent true-f32 products.
"""

import functools

import jax
import jax.numpy as jnp

from veles_tpu.ops.matmul import matmul

__all__ = ["gemm", "veles_gemm"]


@functools.partial(
    jax.jit,
    static_argnames=("trans_a", "trans_b", "precision_level"))
def gemm(a, b, c=None, alpha=1.0, beta=0.0, trans_a=False, trans_b=False,
         precision_level=0):
    """alpha * op(a) @ op(b) + beta * c (BLAS GEMM facade).

    ``precision_level`` follows ops.matmul: the default level 0
    computes f32 products via the fast bf16x3 MXU decomposition —
    f32-class accuracy, but operands with |x| >= bf16 max (~3.39e38)
    or inf produce NaN; pass precision_level=1 for true-f32 products
    when operands can be that large."""
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = matmul(a, b, precision_level=precision_level,
                 out_dtype=jnp.float32)
    out = alpha * out
    if c is not None:
        out = out + beta * c.astype(jnp.float32)
    return out.astype(a.dtype)


#: reference naming alias (veles/ocl_blas.py:187 veles_gemm)
veles_gemm = gemm
