"""Flash-style Pallas attention — the transformer workload's MXU
kernel (docs/kernels.md "The attention kernel").

No reference behavior to match (the 2015 platform predates attention);
this is the ops layer's third hand-scheduled family after matmul and
conv-VJP, built to the same contracts:

- **Forward** is the online-softmax tiled formulation: the grid walks
  (batch-head, q-tile, k-tile) with the k loop innermost; an f32
  scoped-VMEM accumulator carries the running (max, sum, output) triple
  and each k-tile rescales it by ``exp(m_prev - m_new)`` — softmax
  without ever materializing the (T, T) score matrix in HBM.  The
  PRODUCT steps (q@k^T and p@v, plus every backward contraction) are
  the shared :func:`veles_tpu.ops.common.mxu_partial_dot`, so precision
  levels 0-2 mean exactly what they mean in matmul/conv-VJP: level 0
  bf16x3 decomposition for f32 operands, levels 1/2 true-f32 HIGHEST
  products.  (The ACCUMULATION is the online-softmax rescale chain —
  there is no Kahan ladder here; the rescale IS the accumulation
  algorithm, and the levels only change the product precision.)
- **Backward** is a custom_vjp over two more Pallas kernels (the
  ``conv_vjp.py`` pattern): dq accumulates over k-tiles, dk/dv over
  q-tiles, both recomputing the probability tiles from the saved
  logsumexp instead of storing them — flash attention's
  recompute-over-store memory shape.
- **Interpret mode on CPU** (``common.interpret_for``), so tier-1
  parity runs everywhere; masking uses a -1e30 finite floor (never
  -inf), so padded rows/columns contribute EXACT zeros to every
  gradient instead of NaN-poisoning the accumulators.
- ``blocks=None`` consults the ``attention`` ScheduleCache family
  (tune/spec.py) exactly like matmul's consult — tiles change the
  SCHEDULE, never the math.

The ``VELES_PALLAS_BWD`` contract (docs/kernels.md): the model layer
(models/transformer.py) routes to :func:`flash_attention` only when the
knob resolves on; knob off runs :func:`attention_reference` — plain jnp
softmax attention over the same ``mxu_partial_dot`` product step — with
stock autodiff, which IS the fallback path (bit-exact by construction).
On single-tile shapes the kernel executes the reference's exact op
sequence, so flash-vs-reference is bit-exact there — PROVIDED the
zero-padding to the lane width does not regroup XLA's reductions
(measured: T <= 32 and multiples of 64 are bit-exact; in-between
lengths land at ~2e-7 because padding the row-sum/contraction from T
to 128 changes the reduce tree) — and ULP-bounded on multi-tile
shapes (tile accumulation order; tests/test_transformer.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops import common as _common
from veles_tpu.ops.common import (ceil_mult, interpret_for,
                                   mxu_partial_dot, pad_to,
                                   tpu_compiler_params, unpad)

__all__ = ["flash_attention", "attention_reference",
           "ATTENTION_KERNEL_VERSION"]

#: bump when the kernel's algorithm changes: tuned schedules in the
#: cache are only valid for the algorithm they were measured on
ATTENTION_KERNEL_VERSION = 1

_DEFAULT_BLOCKS = (256, 256)  # (bq, bk)

#: finite -inf stand-in for score masking: exp(-1e30 - m) underflows to
#: an exact 0.0 for any realistic row max m, while (-1e30) - (-1e30)
#: stays 0 — so fully-masked (padded) rows produce finite garbage that
#: the unpad slices away, and padded contributions to dk/dv are exact
#: zeros instead of inf - inf = NaN
_MASK_FLOOR = -1e30


def _col_ids(bq, bk):
    return jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)


# -- forward kernel ----------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                l_ref, *, n_k, scale, t_real, bk, precision_level):
    """One (b, i, kk) grid step of the online-softmax forward.

    ``acc_ref`` (bq, dh) f32 carries the running unnormalized output;
    ``m_ref``/``l_ref`` (bq, 128) carry the running row max and row
    sum, lane-broadcast so the scratch tiles stay MXU-shaped.
    """
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _MASK_FLOOR)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0]
    s = mxu_partial_dot(q, k_ref[0].T, precision_level) * scale
    # mask padded key columns to the finite floor, never -inf
    col = kk * bk + _col_ids(*s.shape)
    s = jnp.where(col < t_real, s, _MASK_FLOOR)

    m_prev = m_ref[:, :1]                      # (bq, 1)
    s_max = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
    m_new = jnp.maximum(m_prev, s_max)
    p = jnp.exp(s - m_new)                     # (bq, bk) f32
    alpha = jnp.exp(m_prev - m_new)            # (bq, 1)
    l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + mxu_partial_dot(
        p, v_ref[0], precision_level)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kk == n_k - 1)
    def _store():
        l_fin = l_ref[:, :1]
        # fully-masked (padded) q rows have l == 0; divide by 1 so the
        # garbage rows stay finite for the unpad slice
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


@functools.partial(
    jax.jit, static_argnames=("scale", "precision_level", "blocks",
                              "interpret"))
def _flash_fwd_jit(q, k, v, scale, precision_level, blocks, interpret):
    """(out, lse): the tiled forward.  q/k/v are (B, T, dh); lse comes
    back (B, Tq_padded, 128) f32, lane-broadcast (the backward kernels
    read the same layout)."""
    b, t, dh = q.shape
    bq, bk = _clamped_blocks(blocks, t)
    qp = pad_to(q, (None, bq, 128))
    kp = pad_to(k, (None, bk, 128))
    vp = pad_to(v, (None, bk, 128))
    _, tq, dhp = qp.shape
    tk = kp.shape[1]
    n_k = tk // bk
    grid = (b, tq // bq, n_k)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, n_k=n_k, scale=scale,
                          t_real=t, bk=bk,
                          precision_level=precision_level),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dhp), lambda bb, i, kk: (bb, i, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, i, kk: (bb, kk, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, i, kk: (bb, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dhp), lambda bb, i, kk: (bb, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda bb, i, kk: (bb, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, dhp), q.dtype),
            jax.ShapeDtypeStruct((b, tq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dhp), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return unpad(out, (b, t, dh)), lse


# -- backward kernels --------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, n_k, scale, t_real, bk,
                   precision_level):
    """dq for one q-tile, accumulated over k-tiles: the probability
    tile is recomputed from the saved logsumexp (recompute-over-store),
    then ds = p * (dp - delta) and dq += ds @ k * scale."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = mxu_partial_dot(q_ref[0], k_ref[0].T, precision_level) * scale
    col = kk * bk + _col_ids(*s.shape)
    s = jnp.where(col < t_real, s, _MASK_FLOOR)
    p = jnp.exp(s - lse_ref[0][:, :1])
    dp = mxu_partial_dot(do_ref[0].astype(jnp.float32), v_ref[0].T,
                         precision_level)
    ds = p * (dp - delta_ref[0][:, :1]) * scale
    acc_ref[:] += mxu_partial_dot(ds, k_ref[0], precision_level)

    @pl.when(kk == n_k - 1)
    def _store():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, n_q,
                    scale, t_real, bk, precision_level):
    """dk/dv for one k-tile, accumulated over q-tiles.  Padded key
    columns are masked to exact-zero probabilities, so their dk/dv
    rows come out 0 and the unpad slices them away."""
    qq = pl.program_id(2)

    @pl.when(qq == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    kk = pl.program_id(1)
    s = mxu_partial_dot(q_ref[0], k_ref[0].T, precision_level) * scale
    col = kk * bk + _col_ids(*s.shape)
    s = jnp.where(col < t_real, s, _MASK_FLOOR)
    p = jnp.exp(s - lse_ref[0][:, :1])
    do = do_ref[0].astype(jnp.float32)
    dv_acc_ref[:] += mxu_partial_dot(p.T, do, precision_level)
    dp = mxu_partial_dot(do, v_ref[0].T, precision_level)
    ds = p * (dp - delta_ref[0][:, :1]) * scale
    dk_acc_ref[:] += mxu_partial_dot(ds.T, q_ref[0], precision_level)

    @pl.when(qq == n_q - 1)
    def _store():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "precision_level", "blocks",
                              "interpret"))
def _flash_bwd_jit(q, k, v, out, lse, do, scale, precision_level,
                   blocks, interpret):
    """(dq, dk, dv) via the two tiled backward kernels.  ``delta`` =
    rowsum(do * out) is the standard flash-backward precompute — one
    elementwise pass, kept outside the kernels like conv-VJP keeps its
    dgrad as a lax conv."""
    b, t, dh = q.shape
    bq, bk = _clamped_blocks(blocks, t)
    qp = pad_to(q, (None, bq, 128))
    kp = pad_to(k, (None, bk, 128))
    vp = pad_to(v, (None, bk, 128))
    dop = pad_to(do, (None, bq, 128))
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                # (B, T, 1)
    delta = pad_to(jnp.broadcast_to(delta, (b, t, 128)), (None, bq,
                                                          None))
    _, tq, dhp = qp.shape
    tk = kp.shape[1]
    n_q, n_k = tq // bq, tk // bk

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, n_k=n_k, scale=scale,
                          t_real=t, bk=bk,
                          precision_level=precision_level),
        grid=(b, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, dhp), lambda bb, i, kk: (bb, i, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, i, kk: (bb, kk, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, i, kk: (bb, kk, 0)),
            pl.BlockSpec((1, bq, dhp), lambda bb, i, kk: (bb, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda bb, i, kk: (bb, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda bb, i, kk: (bb, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dhp),
                               lambda bb, i, kk: (bb, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, tq, dhp), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dhp), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, n_q=n_q, scale=scale,
                          t_real=t, bk=bk,
                          precision_level=precision_level),
        grid=(b, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, dhp), lambda bb, kk, i: (bb, i, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, kk, i: (bb, kk, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, kk, i: (bb, kk, 0)),
            pl.BlockSpec((1, bq, dhp), lambda bb, kk, i: (bb, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda bb, kk, i: (bb, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda bb, kk, i: (bb, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dhp), lambda bb, kk, i: (bb, kk, 0)),
            pl.BlockSpec((1, bk, dhp), lambda bb, kk, i: (bb, kk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, tk, dhp), q.dtype),
            jax.ShapeDtypeStruct((b, tk, dhp), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dhp), jnp.float32),
            pltpu.VMEM((bk, dhp), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    return (unpad(dq, (b, t, dh)), unpad(dk, (b, t, dh)),
            unpad(dv, (b, t, dh)))


# -- the custom_vjp entry ----------------------------------------------------


@functools.lru_cache(maxsize=None)
def _flash_fn(scale, precision_level, blocks):
    """Per-static-config custom_vjp, cached so jit tracing sees one
    stable callable per (scale, level, schedule) — the conv_act
    pattern."""

    @jax.custom_vjp
    def f(q, k, v):
        out, _ = _flash_fwd_jit(q, k, v, scale, precision_level,
                                blocks, interpret_for(q, k, v))
        return out

    def fwd(q, k, v):
        out, lse = _flash_fwd_jit(q, k, v, scale, precision_level,
                                  blocks, interpret_for(q, k, v))
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        return _flash_bwd_jit(q, k, v, out, lse, do, scale,
                              precision_level, blocks,
                              interpret_for(q, k, v))

    f.defvjp(fwd, bwd)
    return f


def flash_attention(q, k, v, scale=None, precision_level=0,
                    blocks=None):
    """Tiled online-softmax attention with the Pallas backward
    attached: ``softmax(q @ k^T * scale) @ v`` over (B, T, dh)
    operands (B = batch x heads; the model layer folds heads in).

    ``precision_level`` follows the matmul ladder for every product
    step (docs/kernels.md); ``blocks=None`` consults the ``attention``
    schedule-cache family before the static ``_DEFAULT_BLOCKS``.
    """
    if q.ndim != 3 or k.shape != q.shape or v.shape != q.shape:
        raise ValueError("flash_attention expects matching (B, T, dh) "
                         "operands, got %s %s %s" %
                         (q.shape, k.shape, v.shape))
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if blocks is None:
        blocks = _tuned_blocks(q, precision_level) or _DEFAULT_BLOCKS
    out = _flash_fn(float(scale), int(precision_level),
                    tuple(blocks))(q, k, v)
    if _common.DEBUG_NONFINITE and not isinstance(out, jax.core.Tracer):
        _debug_check(q, k, v, out, precision_level)
    return out


def attention_reference(q, k, v, scale=None, precision_level=1):
    """Stock softmax attention in the kernel's exact op order — the
    ``VELES_PALLAS_BWD=0`` fallback (plain jnp, stock autodiff) AND
    the parity oracle: on shapes that fit one (bq, bk) tile the flash
    kernel executes this sequence verbatim AT THE SAME LEVEL, so the
    two are bit-exact there (for padding-stable lengths — module
    docstring); multi-tile shapes differ only by the online rescale's
    accumulation order (ULP-bounded, tests/test_transformer.py).

    The DEFAULT level is 1 (true-f32 HIGHEST products): stock model-
    layer math is full f32 everywhere else in the zoo (the gd units'
    jnp.dot with preferred_element_type), and autodiff THROUGH the
    level-0 bf16x3 decomposition computes the gradient of the
    approximation with bf16-ROUNDED operand jacobians — ~1e-2 relative
    off the true gradient, where the flash kernel's hand-written
    level-0 backward stays within ~1e-5 (it applies the exact-gradient
    FORMULA with bf16x3 products).  Pass ``precision_level=0``
    explicitly only to parity-test the kernel's level-0 op sequence."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def one(qb, kb, vb):
        s = mxu_partial_dot(qb, kb.T, precision_level) * scale
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        return (mxu_partial_dot(p, vb, precision_level) / l).astype(
            qb.dtype)

    return jax.vmap(one)(q, k, v)


def _clamped_blocks(blocks, t):
    bq, bk = blocks or _DEFAULT_BLOCKS
    return min(bq, ceil_mult(t, 8)), min(bk, ceil_mult(t, 128))


def _tuned_blocks(q, precision_level):
    """Schedule-cache consult for a ``blocks=None`` call (tracer-safe:
    shapes/dtypes only, so the consult fires at trace time inside the
    fused step — which is how ``tune/walk.py`` records it)."""
    b, t, dh = q.shape
    if not (b and t and dh):
        return None
    from veles_tpu.tune.cache import schedule_for
    from veles_tpu.tune.spec import attention_spec, valid_schedule
    spec = attention_spec(b, t, dh, jnp.dtype(q.dtype).name,
                          precision_level)
    schedule = schedule_for(spec["op"], spec["shape"], spec["dtype"],
                            spec["precision_level"], spec["extra"],
                            raw=spec["raw"])
    if schedule is None:
        return None
    normalized = valid_schedule("attention", schedule)
    return tuple(normalized["blocks"]) if normalized else None


def _debug_check(q, k, v, out, precision_level):
    """VELES_DEBUG_NONFINITE guard, matmul's contract: eager calls
    only, raise with operand stats on a non-finite output."""
    if not bool(jnp.isfinite(out).all()):
        from veles_tpu.ops.matmul import _operand_stats
        raise FloatingPointError(
            "flash_attention produced non-finite output (%s; "
            "precision_level=%d — level 0's bf16x3 domain excludes "
            "|x| >= bf16-max)" % (
                "; ".join((_operand_stats("q", q),
                           _operand_stats("k", k),
                           _operand_stats("v", v))), precision_level))
