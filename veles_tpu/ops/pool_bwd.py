"""Max-pool select-and-scatter backward Pallas kernel
(docs/kernels.md).

scripts/pool_bwd_experiment.py measured XLA's select-and-scatter max
pool gradient beating the patches/argmax formulation 6x AND being the
only value-exact routing — so select-and-scatter is the scheduled
primitive here, fused with the incoming err cascade: the kernel
multiplies the routing mask by the incoming cotangent in the same tile
pass that computes it, instead of materializing a one-hot and a
separate multiply.

Formulation (one image x one channel tile per grid step): for each tap
(kh, kw) of the window, in row-major window order, a tap element is
SELECTED iff it equals the window max (the forward output ``y``, which
the unit already holds — no recompute) and no earlier tap matched
(first-match tie-break, the same scan order XLA's SelectAndScatter
folds ge-select in).  The selected cotangent is then scattered back to
input coordinates through a stride-dilated shift — all on values
resident in scoped VMEM, one pass over the window.

Ceil-mode partial windows (models/pooling.py pads bottom/right) are
covered by padding the input block with -inf: padded cells never equal
a real window max, exactly reduce_window's -inf init semantics.

Parity (tests/test_pallas_bwd.py): routing is bit-exact vs the
``jax.vjp(lax.reduce_window)`` reference on exactly-representable
cotangents (including ties and ceil-mode tails); random cotangents
agree within ~1 ULP where >= 2 overlapping windows sum in a different
order.  Windows larger than the VMEM budget (big-image VGG-style
inputs with OVERLAPPING windows) fall back to autodiff;
non-overlapping windows (kx == sx, ky == sy — the VGG 2x2/2 case)
tile the W axis and stay on the kernel.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.common import (ceil_mult, interpret_for, pad_to,
                                   tpu_compiler_params, unpad)

__all__ = ["max_pool_bwd", "max_pool", "POOL_VMEM_BUDGET_BYTES",
           "POOL_BWD_KERNEL_VERSION", "pool_block_footprint"]

#: bump when the select-and-scatter kernel's algorithm changes: tuned
#: W-tilings in the schedule cache are keyed to the algorithm they
#: were measured on (stale versions miss, never serve)
POOL_BWD_KERNEL_VERSION = 1

#: per-grid-step VMEM budget for the pool blocks (x + y + dy + out +
#: f32 accumulator); overlapping-window shapes that exceed it keep the
#: autodiff backward rather than risk a Mosaic VMEM overflow
POOL_VMEM_BUDGET_BYTES = 12 * 2 ** 20


def _pool_bwd_kernel(x_ref, y_ref, dy_ref, out_ref, *, window, sliding,
                     out_h, out_w, in_h, in_w):
    """One (n, w-tile, c-tile) grid step of the routed scatter."""
    ky, kx = window
    sx, sy = sliding
    xv = x_ref[0]                       # (Hp, Wp, cb), -inf padded
    yv = y_ref[0]                       # (OH, OWb, cb)
    dyv = dy_ref[0].astype(jnp.float32)
    span_h = (out_h - 1) * sy + 1
    span_w = (out_w - 1) * sx + 1
    matched = jnp.zeros(yv.shape, jnp.bool_)
    acc = jnp.zeros(xv.shape, jnp.float32)
    for kh in range(ky):
        for kw in range(kx):
            x_tap = jax.lax.slice(
                xv, (kh, kw, 0),
                (kh + span_h, kw + span_w, xv.shape[2]),
                (sy, sx, 1))
            sel = (x_tap == yv) & ~matched
            matched = matched | sel
            contrib = jnp.where(sel, dyv, 0.0)
            if sx == 1 and sy == 1:
                dilated = contrib
            else:
                z = jnp.zeros((out_h, sy, out_w, sx, contrib.shape[2]),
                              jnp.float32)
                z = z.at[:, 0, :, 0, :].set(contrib)
                dilated = z.reshape(out_h * sy, out_w * sx,
                                    contrib.shape[2])
                dilated = dilated[:span_h, :span_w, :]
            acc = acc.at[kh:kh + span_h, kw:kw + span_w, :].add(dilated)
    out_ref[0] = acc[:in_h, :in_w, :].astype(out_ref.dtype)


def pool_block_footprint(h, c, oh, owb, window, sliding, itemsize):
    """VMEM bytes of one (image, W-tile) grid step: padded x block +
    y/dy blocks + out block + the f32 accumulator.  The ONE footprint
    formula — the kernel's planner below and the autotuner's
    feasibility gate (tune/spec.py) both call it, so they cannot
    drift when the block layout changes."""
    ky, kx = window
    sx, _sy = sliding
    cb = ceil_mult(c, 128)
    wb = (owb - 1) * sx + kx
    elems = ((h + ky) * wb            # padded x block
             + 2 * oh * owb           # y + dy
             + h * wb)                # out
    return elems * cb * itemsize + (h + ky) * wb * cb * 4  # f32 acc


def _plan_blocks(h, w_sp, c, oh, ow, window, sliding, itemsize,
                 owb_override=None):
    """(w-tiles, ow-block) fitting POOL_VMEM_BUDGET_BYTES, or None when
    the shape cannot tile (overlapping windows need the full W span).

    ``owb_override`` is a TUNED W block (docs/kernels.md
    "Autotuning"): honored only where halo-free tiling exists
    (kx == sx, ky == sy) and the footprint fits the budget; an
    infeasible/stale override logs a warning and falls back to the
    static plan — it can never overflow VMEM or crash the call."""
    ky, kx = window
    sx, sy = sliding

    def footprint(owb):
        return pool_block_footprint(h, c, oh, owb, window, sliding,
                                    itemsize)

    if (owb_override and 0 < owb_override < ow
            and kx == sx and ky == sy):
        if footprint(owb_override) <= POOL_VMEM_BUDGET_BYTES:
            return -(-ow // owb_override), owb_override
        import logging
        logging.getLogger("veles_tpu.tune").warning(
            "tuned pool W block owb=%d exceeds the VMEM budget for "
            "this shape; using the static plan", owb_override)
    if footprint(ow) <= POOL_VMEM_BUDGET_BYTES:
        return 1, ow
    if kx != sx or ky != sy:
        return None  # overlapping windows: no halo-free W tiling
    owb = ow
    while owb > 1 and footprint(owb) > POOL_VMEM_BUDGET_BYTES:
        owb = -(-owb // 2)
    if footprint(owb) > POOL_VMEM_BUDGET_BYTES:
        return None
    return -(-ow // owb), owb


@functools.partial(
    jax.jit, static_argnames=("window", "sliding", "interpret", "owb"))
def _max_pool_bwd_jit(x, y, dy, window, sliding, interpret, owb=None):
    from jax import lax
    ky, kx = window
    sx, sy = sliding
    n, h, w_sp, c = x.shape
    oh, ow = y.shape[1], y.shape[2]

    plan = _plan_blocks(h, w_sp, c, oh, ow, window, sliding,
                        jnp.dtype(x.dtype).itemsize, owb_override=owb)
    if plan is None:
        # VMEM-infeasible overlapping shape: stock autodiff routing
        from veles_tpu.models.pooling import MaxPooling

        def pool(x_):
            return MaxPooling.apply({}, x_, window=window,
                                    sliding=sliding, pallas_bwd=False)

        _, vjp = jax.vjp(pool, x)
        (err_input,) = vjp(dy.astype(x.dtype))
        return err_input
    n_wtiles, owb = plan

    need_h = (oh - 1) * sy + ky
    # W coverage: full need_w when untiled; owb*sx per tile when tiled
    # (tiling only happens for kx == sx, where need_w == ow*sx exactly,
    # so block offsets are exact multiples of the block width)
    bwx = need_w = (ow - 1) * sx + kx
    if n_wtiles > 1:
        bwx = owb * sx
    xw_total = n_wtiles * bwx
    neg_inf = jnp.asarray(-jnp.inf, x.dtype)
    cb = ceil_mult(c, 128)
    # -inf padding everywhere a real (ceil-mode) window can peek past
    # the input — reduce_window's init semantics, so a padded cell can
    # never be selected over a real window max.  Channel padding is
    # plain zeros: a zero can only "match" a zero-padded y cell, whose
    # cotangent is the zero pad_to wrote (contributes nothing).
    xp = lax.pad(x, neg_inf,
                 [(0, 0, 0), (0, need_h - h, 0),
                  (0, xw_total - w_sp, 0), (0, 0, 0)])
    xp = pad_to(xp, (None, None, None, cb))
    y_p = pad_to(y, (None, None, owb, cb))
    dy_p = pad_to(dy, (None, None, owb, cb))

    out = pl.pallas_call(
        functools.partial(
            _pool_bwd_kernel, window=window, sliding=sliding,
            out_h=oh, out_w=owb, in_h=h,
            in_w=w_sp if n_wtiles == 1 else bwx),
        grid=(n, n_wtiles),
        in_specs=[
            pl.BlockSpec((1, need_h, bwx, cb),
                         lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((1, oh, owb, cb), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((1, oh, owb, cb), lambda i, j: (i, 0, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, w_sp if n_wtiles == 1 else bwx,
                                cb),
                               lambda i, j: (i, 0, j, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (n, h, w_sp if n_wtiles == 1 else xw_total, cb), x.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(xp, y_p, dy_p)
    return unpad(out, (n, h, w_sp, c))


def max_pool_bwd(x, y, err_output, *, window, sliding, owb=None):
    """err_input for max pooling via the scheduled select-and-scatter
    kernel: ``x`` the forward input, ``y`` the forward output (the
    window maxima — no recompute), ``err_output`` the incoming
    cotangent.  Returns err_input in ``x.dtype``.

    ``owb=None`` consults the tuned schedule cache for a W-tiling
    override (docs/kernels.md "Autotuning"); an explicit ``owb``
    bypasses the consult (the tuner's own candidate measurements)."""
    window = (int(window[0]), int(window[1]))
    sliding = (int(sliding[0]), int(sliding[1]))
    if owb is None:
        owb = _tuned_owb(x, y, window, sliding)
    return _max_pool_bwd_jit(x, y, err_output.astype(x.dtype),
                             window, sliding,
                             interpret_for(x, err_output), owb)


def _tuned_owb(x, y, window, sliding):
    """Schedule-cache consult: the tuned output-width block for this
    pool shape or None (-> the static ``_plan_blocks`` plan).
    Tracer-safe — shapes only — so it fires at trace time inside the
    fused step (``tune/walk.py`` records it there)."""
    from veles_tpu.tune.cache import schedule_for
    from veles_tpu.tune.spec import pool_bwd_spec, valid_schedule
    spec = pool_bwd_spec(x.shape, (y.shape[1], y.shape[2]), window,
                         sliding, jnp.dtype(x.dtype).name)
    schedule = schedule_for(spec["op"], spec["shape"], spec["dtype"],
                            spec["precision_level"], spec["extra"],
                            raw=spec["raw"])
    if schedule is None:
        return None
    normalized = valid_schedule("pool_bwd", schedule)
    return normalized["owb"] if normalized else None


# -- custom_vjp forward wrapper ---------------------------------------------


@functools.lru_cache(maxsize=None)
def _max_pool_fn(window, sliding):
    """Per-config custom_vjp of the max-pool forward: forward is
    EXACTLY models/pooling.py's reduce_window composition, backward is
    the kernel above."""
    from veles_tpu.models.pooling import MaxPooling

    def raw(x):
        return MaxPooling.apply({}, x, window=window, sliding=sliding,
                                pallas_bwd=False)

    @jax.custom_vjp
    def f(x):
        return raw(x)

    def fwd(x):
        y = raw(x)
        return y, (x, y)

    def bwd(res, dy):
        x, y = res
        return (max_pool_bwd(x, y, dy, window=window,
                             sliding=sliding),)

    f.defvjp(fwd, bwd)
    return f


def max_pool(x, *, window, sliding):
    """Max pooling with the select-and-scatter Pallas backward attached
    (models/pooling.py routes here when VELES_PALLAS_BWD is on)."""
    return _max_pool_fn((int(window[0]), int(window[1])),
                        (int(sliding[0]), int(sliding[1])))(x)
