"""Fused conv-VJP Pallas kernel family — the hand-scheduled backward
for the conv layers (docs/kernels.md).

MFU.json's round-5 attribution showed the backward-vs-forward MFU gap
(42% vs 71%) is COMPOSITION slack, not any single op: isolated conv
gradients already run near peak under autodiff, but a congested step
interleaves every layer's dgrad/wgrad/epilogue/bias ops freely and the
MXU piles up.  This module replaces the autodiff conv backward with a
scheduled composition:

- **wgrad** as a batch-contraction matmul over per-tap strided slices
  of the (padded) input — ONE Pallas kernel whose grid walks
  (Cout-tiles, taps, Cin-tiles, P-tiles) with an f32 scoped-VMEM
  accumulator, following the ``ops/matmul.py`` kernel/interpret/
  precision-level pattern (the PRODUCT step is the shared
  ``common.mxu_partial_dot``, so level 0 runs the bf16x3 decomposition
  for f32 operands and bf16 operands take single-pass MXU products).
- the **elementwise epilogue fused into the matmul tiles**: the
  activation backward (in terms of the forward OUTPUT y, exactly like
  the gd units) and the bias-grad reduction both happen on the (P, Cout)
  tiles the wgrad contraction already streams through VMEM — no
  separate elementwise pass over the cotangent, no extra HBM round
  trip for ``err``.  The kernel emits ``err`` as a third output for the
  dgrad to consume.
- **dgrad** as the explicit lhs-dilated conv (transposed conv: dilate
  ``err`` by the forward stride, convolve with the spatially-flipped
  I/O-swapped kernel) — the formulation XLA's own transpose rule uses,
  kept as a lax conv because the round-5 receipts measured it near
  peak already; the win is consuming the fused ``err`` instead of
  recomputing the epilogue.

Traffic note: the per-tap slices materialize ~taps x input bytes, like
im2col — but the layers whose backward time dominates (AlexNet convs
2/4/5/6, MFU.json) are MXU-bound by 3-7x over their HBM time, so the
extra activation reads stay under the MXU roofline.  Kernels with more
than ``MAX_FUSED_TAPS`` taps (AlexNet's 11x11 layer 0 — HBM-bound
anyway) fall back to the stock autodiff VJP.

Parity contract (tests/test_pallas_bwd.py, ``pallas`` marker): dgrad
is bit-exact vs autodiff; wgrad/bias-grad are bit-exact on
exactly-representable cotangents and within a documented ULP bound
(~1e-6 rel for f32 level>=1, ~5e-7 products + tile-order accumulation
for level 0 bf16x3) on random ones — tile-parallel f32 accumulation
cannot reproduce XLA's reduction order bit-for-bit.  The
``VELES_PALLAS_BWD=0`` fallback restores the autodiff backward
bit-exactly (it IS the stock code path).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops import common as _common
from veles_tpu.ops.common import (ceil_mult, interpret_for,
                                   mxu_partial_dot, pad_to,
                                   tpu_compiler_params, unpad)

__all__ = ["fused_conv_vjp", "conv_act", "activation_grad",
           "ACTIVATIONS", "MAX_FUSED_TAPS",
           "CONV_VJP_KERNEL_VERSION"]

#: bump when the wgrad kernel's algorithm changes: tuned schedules in
#: the cache are only valid for the algorithm they were measured on
#: (the version rides the schedule-cache digest, so old entries become
#: misses, never silently-served stale tiles)
CONV_VJP_KERNEL_VERSION = 1

#: kernels with more taps than this keep the autodiff VJP: the per-tap
#: slice stack would multiply activation traffic past any MXU cover
#: (AlexNet layer 0's 11x11 = 121 taps is the motivating case — and
#: it is HBM-bound, so the fused schedule has nothing to win there)
MAX_FUSED_TAPS = 32

_DEFAULT_BLOCKS = (256, 256, 512)  # (bi=Cin, bj=Cout, bk=P) tile sizes


# -- activation epilogues ----------------------------------------------------
# Derivatives in terms of the forward OUTPUT y (no pre-activation state
# stored) — the same closed forms the gd units use (models/gd.py), kept
# here as (name -> grad(y, err)) so the kernel can fuse them by name.

def _grad_linear(y, err):
    return err


def _grad_strict_relu(y, err):
    return err * (y > 0)


def _grad_relu_log(y, err):
    # y = log(1+exp(x))  =>  dy/dx = 1 - exp(-y)
    return err * (1.0 - jnp.exp(-y))


def _grad_tanh(y, err):
    # y = A*tanh(B x)  =>  dy/dx = (B/A)*(A^2 - y^2); A/B come from the
    # forward's own class so the closed form can never desynchronize
    from veles_tpu.models.all2all import All2AllTanh
    a, b = All2AllTanh.A, All2AllTanh.B
    return err * ((b / a) * (a * a - y * y))


def _grad_sigmoid(y, err):
    return err * (y * (1.0 - y))


ACTIVATIONS = {
    "linear": _grad_linear,
    "strict_relu": _grad_strict_relu,
    "relu_log": _grad_relu_log,
    "tanh": _grad_tanh,
    "sigmoid": _grad_sigmoid,
}


def activation_grad(activation, y, err):
    """err * d(activation)/dz expressed via the forward output y."""
    return ACTIVATIONS[activation](y, err)


# -- the fused epilogue + wgrad + bias kernel --------------------------------


def _wgrad_kernel(xt_ref, y_ref, dy_ref, gw_ref, gb_ref, err_ref,
                  acc_ref, comp_ref, bias_ref, *, n_k,
                  precision_level, activation, err_dtype):
    """One (j, t, i, k) grid step of the batch-contraction wgrad.

    Grid order is (Cout-tile j, tap t, Cin-tile i, P-tile k) with k
    innermost, so ``acc_ref`` (f32 scoped VMEM) accumulates one
    (bi, bj) weight-gradient tile over the full P sweep.  The epilogue
    — activation backward + bias reduction — runs on the (bk, bj)
    err tile the contraction streams anyway; ``err`` is stored for the
    dgrad, and the bias sum accumulates once (on the t==0, i==0
    sweep), landing in ``gb_ref`` whose block index is constant per j
    so the window stays VMEM-resident until j advances.
    """
    t = pl.program_id(1)
    i = pl.program_id(2)
    k = pl.program_id(3)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        if precision_level > 0:
            comp_ref[:] = jnp.zeros_like(comp_ref)

    first_sweep = (t == 0) & (i == 0)

    @pl.when(first_sweep & (k == 0))
    def _init_bias():
        bias_ref[:] = jnp.zeros_like(bias_ref)

    # fused elementwise epilogue: activation backward on the forward
    # OUTPUT tile + the incoming cotangent tile, in f32 on the VPU
    err_f32 = activation_grad(activation, y_ref[:].astype(jnp.float32),
                              dy_ref[:].astype(jnp.float32))
    err = err_f32.astype(err_dtype)
    # written every visit (recomputed per (t, i) anyway — idempotent),
    # so output-window revisits never flush stale data
    err_ref[:] = err

    @pl.when(first_sweep)
    def _bias():
        bias_ref[0:1, :] += jnp.sum(err_f32, axis=0, keepdims=True)

    partial = mxu_partial_dot(xt_ref[0].T, err, precision_level)
    if precision_level == 0:
        acc_ref[:] += partial
    elif precision_level == 1:
        # Kahan across P-tile partial sums (matmul.py's ladder)
        y_c = partial - comp_ref[:]
        t_c = acc_ref[:] + y_c
        comp_ref[:] = (t_c - acc_ref[:]) - y_c
        acc_ref[:] = t_c
    else:
        acc = acc_ref[:]
        t_c = acc + partial
        big = jnp.abs(acc) >= jnp.abs(partial)
        comp_ref[:] += jnp.where(big, (acc - t_c) + partial,
                                 (partial - t_c) + acc)
        acc_ref[:] = t_c

    @pl.when(k == n_k - 1)
    def _store():
        total = acc_ref[:]
        if precision_level == 2:
            total = total + comp_ref[:]
        gw_ref[0] = total

    @pl.when(first_sweep & (k == n_k - 1))
    def _store_bias():
        gb_ref[:] = bias_ref[0:1, :]


def _build_tap_stack(x, ky, kx, out_hw, padding, sliding):
    """(taps, N*OH*OW, Ci) strided-slice stack of the padded input:
    tap (kh, kw)'s matrix row p = (n, oh, ow) is
    x_pad[n, oh*sy + kh, ow*sx + kw, ci].  ``lax.pad`` handles the
    possibly-negative high padding (stride may leave the bottom/right
    input rows uncovered by any window)."""
    from jax import lax
    left, top, _right, _bottom = padding
    sx, sy = sliding
    oh, ow = out_hw
    n, h, w_sp, ci = x.shape
    need_h = (oh - 1) * sy + ky
    need_w = (ow - 1) * sx + kx
    zero = jnp.zeros((), x.dtype)
    xp = lax.pad(x, zero,
                 [(0, 0, 0), (top, need_h - h - top, 0),
                  (left, need_w - w_sp - left, 0), (0, 0, 0)])
    taps = []
    for kh in range(ky):
        for kw in range(kx):
            sl = lax.slice(
                xp, (0, kh, kw, 0),
                (n, kh + (oh - 1) * sy + 1, kw + (ow - 1) * sx + 1, ci),
                (1, sy, sx, 1))
            taps.append(sl.reshape(n * oh * ow, ci))
    return jnp.stack(taps)


@functools.partial(
    jax.jit, static_argnames=("activation", "ky", "kx", "out_hw",
                              "padding", "sliding", "precision_level",
                              "blocks", "interpret"))
def _fused_wgrad_jit(x, y, dy, activation, ky, kx, out_hw, padding,
                     sliding, precision_level, blocks, interpret):
    """(grad_w f32 (ky,kx,Ci,Cout), grad_b f32 (Cout,), err x.dtype) —
    the Pallas-scheduled half of the conv VJP."""
    n, _h, _w, ci = x.shape
    oh, ow = out_hw
    cout = y.shape[-1]
    p = n * oh * ow

    xt = _build_tap_stack(x, ky, kx, out_hw, padding, sliding)
    ym = y.reshape(p, cout)
    dym = dy.reshape(p, cout)

    bi, bj, bk = blocks or _DEFAULT_BLOCKS
    # Cin rides the LANE axis of the tap stack and the sublane axis of
    # the weight tile, so it pads to 128; Cout is lanes everywhere
    bi = min(bi, ceil_mult(ci, 128))
    bj = min(bj, ceil_mult(cout, 128))
    bk = min(bk, ceil_mult(p, 8))
    xt = pad_to(xt, (None, bk, bi))
    ym = pad_to(ym, (bk, bj))
    dym = pad_to(dym, (bk, bj))
    n_taps, pp, cip = xt.shape
    cop = ym.shape[1]
    n_k = pp // bk
    grid = (cop // bj, n_taps, cip // bi, n_k)

    gw, gb, err = pl.pallas_call(
        functools.partial(_wgrad_kernel, n_k=n_k,
                          precision_level=precision_level,
                          activation=activation, err_dtype=x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bk, bi), lambda j, t, i, k: (t, k, i)),
            pl.BlockSpec((bk, bj), lambda j, t, i, k: (k, j)),
            pl.BlockSpec((bk, bj), lambda j, t, i, k: (k, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bi, bj), lambda j, t, i, k: (t, i, j)),
            pl.BlockSpec((1, bj), lambda j, t, i, k: (0, j)),
            pl.BlockSpec((bk, bj), lambda j, t, i, k: (k, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_taps, cip, cop), jnp.float32),
            jax.ShapeDtypeStruct((1, cop), jnp.float32),
            jax.ShapeDtypeStruct((pp, cop), x.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bi, bj), jnp.float32),
            pltpu.VMEM((bi, bj), jnp.float32),
            pltpu.VMEM((8, bj), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(xt, ym, dym)

    grad_w = unpad(gw, (n_taps, ci, cout)).reshape(ky, kx, ci, cout)
    grad_b = unpad(gb, (1, cout))[0]
    err = unpad(err, (p, cout)).reshape(n, oh, ow, cout)
    return grad_w, grad_b, err


def _dgrad_lhs_dilated(err, w, x_shape, padding, sliding):
    """dX via the transposed conv: dilate err by the forward stride and
    convolve with the spatially-flipped, I/O-swapped kernel — the same
    lhs-dilated formulation XLA's own conv transpose rule emits, so it
    is bit-identical to the autodiff dgrad (tests prove it)."""
    from jax import lax
    ky, kx = w.shape[0], w.shape[1]
    left, top, _right, _bottom = padding
    sx, sy = sliding
    h, w_sp = x_shape[1], x_shape[2]
    oh, ow = err.shape[1], err.shape[2]
    lo_h, hi_h = ky - 1 - top, h + top - (oh - 1) * sy - 1
    lo_w, hi_w = kx - 1 - left, w_sp + left - (ow - 1) * sx - 1
    w_t = w[::-1, ::-1].swapaxes(2, 3)
    pet = jnp.float32 if err.dtype == jnp.float32 else None
    return lax.conv_general_dilated(
        err, w_t, window_strides=(1, 1),
        padding=((lo_h, hi_h), (lo_w, hi_w)),
        lhs_dilation=(sy, sx),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=pet).astype(err.dtype)


def fused_conv_vjp(x, w, y, err_output, *, activation="linear",
                   padding=(0, 0, 0, 0), sliding=(1, 1),
                   include_bias=True, need_err_input=True,
                   precision_level=0, blocks=None):
    """The hand-scheduled conv backward: (err_input, grad_w, grad_b).

    ``x``/``w``/``y`` are the forward operands and OUTPUT (activation
    included), ``err_output`` the incoming cotangent.  grad_w/grad_b
    come back f32 (callers cast); err_input in ``x.dtype`` or None.

    ``precision_level`` follows the matmul ladder for the wgrad
    contraction: 0 = bf16x3 products for f32 operands (fastest; safe
    under the PR 3 step-level finite guard, which skips a poisoned
    update bit-exactly), 1/2 = true-f32 products + Kahan/Neumaier.
    Falls back to the stock autodiff VJP when the tap count exceeds
    ``MAX_FUSED_TAPS`` (see module docstring).
    """
    ky, kx = int(w.shape[0]), int(w.shape[1])
    oh, ow = int(err_output.shape[1]), int(err_output.shape[2])
    if ky * kx > MAX_FUSED_TAPS:
        return _autodiff_conv_vjp(
            x, w, y, err_output, activation=activation, padding=padding,
            sliding=sliding, include_bias=include_bias,
            need_err_input=need_err_input)
    if blocks is None:
        blocks = _tuned_blocks(x, ky, kx, oh, ow, err_output,
                               precision_level, activation, padding,
                               sliding)
    grad_w, grad_b, err = _fused_wgrad_jit(
        x, y, err_output, activation, ky, kx, (oh, ow),
        tuple(padding), tuple(sliding), precision_level, blocks,
        interpret_for(x, err_output))
    err_input = (_dgrad_lhs_dilated(err, w, x.shape, padding, sliding)
                 if need_err_input else None)
    if not include_bias:
        grad_b = None
    if _common.DEBUG_NONFINITE and not isinstance(grad_w, jax.core.Tracer):
        # eager calls only, like matmul's guard: the check concretizes
        # values, which would crash a jit trace (the fused train step
        # reaches here as tracers — its finite_guard owns that path)
        _debug_check(x, w, err_output, grad_w, grad_b, err_input,
                     precision_level)
    return err_input, grad_w, grad_b


def _tuned_blocks(x, ky, kx, oh, ow, err_output, precision_level,
                  activation, padding, sliding):
    """Schedule-cache consult for a ``blocks=None`` call: the tuned
    (bi, bj, bk) wgrad tiles for this (taps, padded P/Cin/Cout, dtype,
    precision, device) or None (-> ``_DEFAULT_BLOCKS``).  Padding/
    sliding/activation ride the recorded raw context only — the wgrad
    contraction's grid depends on the padded shape alone.  Tracer-safe
    (shapes/dtypes only), so the consult fires at trace time inside
    the fused step — which is how ``tune/walk.py`` records it."""
    from veles_tpu.tune.cache import schedule_for
    from veles_tpu.tune.spec import conv_vjp_spec, valid_schedule
    spec = conv_vjp_spec(
        x.shape, ky, kx, err_output.shape[-1], (oh, ow),
        jnp.dtype(x.dtype).name, precision_level, padding, sliding,
        activation)
    schedule = schedule_for(spec["op"], spec["shape"], spec["dtype"],
                            spec["precision_level"], spec["extra"],
                            raw=spec["raw"])
    if schedule is None:
        return None
    normalized = valid_schedule("conv_vjp", schedule)
    return tuple(normalized["blocks"]) if normalized else None


def _autodiff_conv_vjp(x, w, y, err_output, *, activation, padding,
                       sliding, include_bias, need_err_input):
    """The stock formulation (what gd_conv runs with the knob off),
    used as the many-tap fallback so the call-site contract is one
    function either way."""
    from veles_tpu.models.conv import Conv
    err = activation_grad(activation, y, err_output).astype(x.dtype)

    def lin(w_, x_):
        return Conv.apply({"weights": w_, "bias": None}, x_,
                          padding=padding, sliding=sliding,
                          pallas_bwd=False)

    _, vjp = jax.vjp(lin, w, x)
    grad_w, err_input = vjp(err)
    grad_b = (err.astype(jnp.float32).sum(axis=(0, 1, 2))
              if include_bias else None)
    return (err_input if need_err_input else None,
            grad_w.astype(jnp.float32), grad_b)


def _debug_check(x, w, dy, grad_w, grad_b, err_input, precision_level):
    """VELES_DEBUG_NONFINITE guard, same contract as matmul's: raise
    with operand stats when a finite input produced a non-finite
    gradient (the level-0 bf16x3 domain limit being the usual cause)."""
    outs = [("grad_w", grad_w)]
    if grad_b is not None:
        outs.append(("grad_b", grad_b))
    if err_input is not None:
        outs.append(("err_input", err_input))
    for name, out in outs:
        if not bool(jnp.isfinite(out).all()):
            from veles_tpu.ops.matmul import _operand_stats
            raise FloatingPointError(
                "fused_conv_vjp produced non-finite %s (%s; "
                "precision_level=%d — level 0's bf16x3 domain excludes "
                "|x| >= bf16-max)" % (
                    name, "; ".join((_operand_stats("x", x),
                                     _operand_stats("w", w),
                                     _operand_stats("dy", dy))),
                    precision_level))


# -- custom_vjp forward wrapper ---------------------------------------------


@functools.lru_cache(maxsize=None)
def _conv_act_fn(activation, padding, sliding, include_bias,
                 precision_level):
    """Per-static-config custom_vjp of act(conv(x, w) + b): the
    forward is EXACTLY models/conv.py's composition (bit-identical
    HLO), the backward is the fused family above.  Cached per config so
    jit tracing sees one stable callable per layer."""
    from veles_tpu.models.conv import conv2d

    left, top, right, bottom = padding
    sx, sy = sliding
    act = _forward_act(activation)

    def raw(x, w, *b):
        pet = jnp.float32 if x.dtype == jnp.float32 else None
        z = conv2d(x, w, (sy, sx), ((top, bottom), (left, right)), pet)
        if include_bias:
            z = z + b[0]
        return act(z).astype(x.dtype)

    @jax.custom_vjp
    def f(x, w, *b):
        return raw(x, w, *b)

    def fwd(x, w, *b):
        y = raw(x, w, *b)
        return y, (x, w, y) + b

    def bwd(res, dy):
        x, w, y = res[:3]
        err_input, grad_w, grad_b = fused_conv_vjp(
            x, w, y, dy, activation=activation, padding=padding,
            sliding=sliding, include_bias=include_bias,
            need_err_input=True, precision_level=precision_level)
        grads = (err_input, grad_w.astype(w.dtype))
        if include_bias:
            grads += (grad_b.astype(res[3].dtype),)
        return grads

    f.defvjp(fwd, bwd)
    return f


def conv_act(x, w, b, *, activation, padding, sliding,
             precision_level=0):
    """act(conv(x, w) + b) with the hand-scheduled backward attached
    (the entry models/conv.py routes through when VELES_PALLAS_BWD is
    on).  ``b`` may be None."""
    fn = _conv_act_fn(activation, tuple(padding), tuple(sliding),
                      b is not None, precision_level)
    return fn(x, w, b) if b is not None else fn(x, w)


def _forward_act(activation):
    """The forward activation by epilogue name — resolved to THE
    models/all2all.py staticmethod (the conv classes' _activate), not a
    local copy, so the knob-on forward is bit-identical to the knob-off
    forward by construction (lazy import: models import this module)."""
    from veles_tpu.models import all2all
    cls = {
        "linear": all2all.All2All,
        "strict_relu": all2all.All2AllStrictRELU,
        "relu_log": all2all.All2AllRELU,
        "tanh": all2all.All2AllTanh,
        "sigmoid": all2all.All2AllSigmoid,
    }[activation]
    return cls._activate
