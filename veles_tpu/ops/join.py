"""Concatenate tensors along the feature axis.

TPU-native counterpart of reference ocl/join.jcl / cuda/join.jcu (a
Jinja2-templated concat of N device buffers, used by InputJoiner).  The
kernel writes each input into its column window of the output; the N-way
structure is unrolled at trace time, replacing the reference's template
expansion with Python-level metaprogramming over the kernel body.
"""

import jax
from jax.experimental import pallas as pl

from veles_tpu.ops.common import interpret_for, kernel_cast

__all__ = ["join"]


def _make_join_kernel(widths):
    offsets = []
    total = 0
    for width in widths:
        offsets.append(total)
        total += width

    def kernel(*refs):
        in_refs, out_ref = refs[:-1], refs[-1]
        for ref, offset, width in zip(in_refs, offsets, widths):
            out_ref[:, offset:offset + width] = \
                kernel_cast(ref[:], out_ref.dtype)
    return kernel


def join(*arrays, out_dtype=None):
    """Concatenate (B, Fi) arrays -> (B, sum Fi) along axis 1."""
    if not arrays:
        raise ValueError("join needs at least one input")
    batch = arrays[0].shape[0]
    for i, a in enumerate(arrays):
        if a.shape[0] != batch:
            raise ValueError(
                "join: input %d has batch %d, expected %d" %
                (i, a.shape[0], batch))
    flats = [a.reshape(batch, -1) for a in arrays]
    widths = tuple(f.shape[1] for f in flats)
    out_dtype = out_dtype or flats[0].dtype
    total = sum(widths)
    out = pl.pallas_call(
        _make_join_kernel(widths),
        out_shape=jax.ShapeDtypeStruct((batch, total), out_dtype),
        interpret=interpret_for(*flats),
    )(*flats)
    return out
