/* Live session view: polls the web-status JSON endpoints and renders
 * an auto-updating multi-series metric chart plus the post/event
 * tables.  Plain ES5-ish DOM code, no dependencies — the TPU-build
 * equivalent of the reference's web/ status frontend (ref web/,
 * ~2.9k LoC JS; this client covers its live-status role against the
 * /session/<sid>.json and /events/<sid>.json API).
 *
 * Chart rules (dataviz method): line form for change-over-time; at
 * most 4 categorical series in fixed order (validated palette, CSS
 * vars --series-1..4); legend + last-value direct labels; recessive
 * grid; crosshair + tooltip on hover; the posts table below is the
 * table view of the same data.
 */
(function () {
  "use strict";
  var sid = document.body.getAttribute("data-sid");
  if (!sid) return;
  var POLL_MS = 3000;
  var MAX_SERIES = 4;
  var chartBox = document.getElementById("chart");
  var lastStamp = null;

  function seriesColor(i) {
    return "var(--series-" + (i + 1) + ")";
  }

  // decision.epoch_metrics posts are [test, validation, train]
  var LIST_NAMES = ["test", "validation", "train"];

  function numeric(v) {
    return typeof v === "number" && isFinite(v);
  }

  function extractSeries(history) {
    // {key -> {name, points: [{x, y, t}]}} in first-seen order
    var order = [], byKey = {};
    history.forEach(function (post, idx) {
      var m = post.metrics;
      var entries = [];
      if (Array.isArray(m)) {
        m.forEach(function (v, i) {
          entries.push(["#" + i, LIST_NAMES[i] || "series " + i, v]);
        });
      } else if (m && typeof m === "object") {
        Object.keys(m).forEach(function (k) {
          entries.push([k, k, m[k]]);
        });
      }
      entries.forEach(function (e) {
        if (!numeric(e[2])) return;
        if (!byKey[e[0]]) {
          byKey[e[0]] = { name: e[1], points: [] };
          order.push(e[0]);
        }
        byKey[e[0]].points.push(
          { x: idx, y: e[2], t: post.updated || "" });
      });
    });
    return order.slice(0, MAX_SERIES).map(function (k) {
      return byKey[k];
    });
  }

  function fmt(v) {
    return Math.abs(v) >= 1000 ? v.toFixed(0) : v.toPrecision(4);
  }

  function esc(v) {
    var d = document.createElement("div");
    d.textContent = v == null ? "" : String(v);
    return d.innerHTML;
  }

  function el(tag, attrs) {
    var node = document.createElementNS(
      "http://www.w3.org/2000/svg", tag);
    Object.keys(attrs || {}).forEach(function (k) {
      node.setAttribute(k, attrs[k]);
    });
    return node;
  }

  function drawChart(series, nPosts) {
    var W = 560, H = 200, padL = 8, padR = 60, padY = 14;
    var svg = el("svg", { width: W, height: H, "class": "chart",
                          role: "img" });
    var lo = Infinity, hi = -Infinity;
    series.forEach(function (s) {
      s.points.forEach(function (p) {
        if (p.y < lo) lo = p.y;
        if (p.y > hi) hi = p.y;
      });
    });
    if (!isFinite(lo)) return svg;
    if (hi === lo) { hi += 1; lo -= 1; }
    var plotW = W - padL - padR, plotH = H - 2 * padY;
    var X = function (x) {
      return padL + plotW * (nPosts > 1 ? x / (nPosts - 1) : 0.5);
    };
    var Y = function (y) {
      return padY + plotH * (1 - (y - lo) / (hi - lo));
    };
    // recessive grid: 3 horizontal lines + min/max text labels
    [lo, (lo + hi) / 2, hi].forEach(function (gy) {
      svg.appendChild(el("line", { x1: padL, x2: padL + plotW,
                                   y1: Y(gy), y2: Y(gy),
                                   "class": "grid" }));
      var t = el("text", { x: padL + plotW + 4, y: Y(gy) + 4,
                           "class": "axis" });
      t.textContent = fmt(gy);
      svg.appendChild(t);
    });
    series.forEach(function (s, i) {
      var d = s.points.map(function (p, j) {
        return (j ? "L" : "M") + X(p.x).toFixed(1) + " " +
               Y(p.y).toFixed(1);
      }).join(" ");
      var path = el("path", { d: d, fill: "none",
                              stroke: seriesColor(i),
                              "stroke-width": 2 });
      svg.appendChild(path);
      var last = s.points[s.points.length - 1];
      if (last) {
        var lbl = el("text", { x: X(last.x) + 4,
                               y: Y(last.y) - 4, "class": "axis" });
        lbl.textContent = fmt(last.y);
        svg.appendChild(lbl);
      }
    });
    // crosshair + shared tooltip (nearest post index)
    var cross = el("line", { y1: padY, y2: padY + plotH,
                             "class": "cross", visibility: "hidden" });
    svg.appendChild(cross);
    var tipBox = document.getElementById("tip");
    svg.addEventListener("mousemove", function (ev) {
      var rect = svg.getBoundingClientRect();
      var frac = (ev.clientX - rect.left - padL) / plotW;
      var idx = Math.max(0, Math.min(nPosts - 1,
        Math.round(frac * (nPosts - 1))));
      cross.setAttribute("x1", X(idx));
      cross.setAttribute("x2", X(idx));
      cross.setAttribute("visibility", "visible");
      var lines = [];
      series.forEach(function (s, i) {
        s.points.forEach(function (p) {
          // esc(): metric keys / timestamps come from unauthenticated
          // POST /update — never raw into innerHTML
          if (p.x === idx) {
            lines.push("<span class='swatch' style='background:" +
                       seriesColor(i) + "'></span>" + esc(s.name) +
                       ": " + fmt(p.y) +
                       (p.t ? " <small>(" + esc(p.t) + ")</small>"
                            : ""));
          }
        });
      });
      tipBox.innerHTML = lines.join("<br>");
      tipBox.style.visibility = lines.length ? "visible" : "hidden";
    });
    svg.addEventListener("mouseleave", function () {
      cross.setAttribute("visibility", "hidden");
      tipBox.style.visibility = "hidden";
    });
    return svg;
  }

  function legend(series) {
    if (series.length < 2) return null;  // one series: title names it
    var box = document.createElement("div");
    box.className = "legend";
    series.forEach(function (s, i) {
      var item = document.createElement("span");
      item.innerHTML = "<span class='swatch' style='background:" +
        seriesColor(i) + "'></span>" + esc(s.name);
      box.appendChild(item);
    });
    return box;
  }

  function renderTables(history, events) {
    var rows = history.slice(-100).map(function (p) {
      return "<tr><td>" + esc(p.updated) + "</td><td class='num'>" +
        esc(p.epoch) + "</td><td>" + esc(JSON.stringify(p.metrics)) +
        "</td><td class='num'>" + esc(p.slaves) + "</td></tr>";
    }).join("");
    document.getElementById("posts").innerHTML =
      "<tr><th>time</th><th>epoch</th><th>metrics</th><th>slaves</th>" +
      "</tr>" + rows;
    var evRows = events.slice(-100).map(function (e) {
      return "<tr><td>" + esc(e[0]) + "</td><td>" + esc(e[1]) +
        "</td></tr>";
    }).join("");
    document.getElementById("events").innerHTML =
      "<tr><th>time</th><th>event</th></tr>" + evRows;
  }

  function refresh() {
    if (document.hidden) return;
    Promise.all([
      fetch("/session/" + encodeURIComponent(sid) + ".json")
        .then(function (r) { return r.json(); }),
      fetch("/events/" + encodeURIComponent(sid) + ".json")
        .then(function (r) { return r.json(); })
    ]).then(function (res) {
      var history = res[0], events = res[1];
      var stamp = history.length && JSON.stringify(
        history[history.length - 1]);
      if (stamp === lastStamp) return;
      lastStamp = stamp;
      var series = extractSeries(history);
      chartBox.innerHTML = "";
      var lg = legend(series);
      if (lg) chartBox.appendChild(lg);
      chartBox.appendChild(drawChart(series, history.length));
      renderTables(history, events);
    }).catch(function () { /* server gone; keep last view */ });
  }

  refresh();
  setInterval(refresh, POLL_MS);
})();
