"""GeneticsOptimizer: evolve a population by evaluating candidate
configurations as jobs.

Reference: genetics/optimization_workflow.py:70-260 farmed chromosome
evaluations to slaves as master-slave jobs (each spawning a child veles
process).  Here evaluations run through a pluggable evaluator:

- in-process (default): ``fitness_fn(candidate_spec) -> float``;
- process pool: ``workers=N`` evaluates candidates concurrently in
  subprocesses (the task-parallelism strategy the reference used);
- the control plane (veles_tpu.server) can farm the same callable as
  jobs across hosts — see tests/test_genetics.py for the wiring.

Fitness is MAXIMIZED (use -validation_error).
"""

import concurrent.futures

from veles_tpu.genetics.config import apply_values, extract_tunes
from veles_tpu.genetics.core import Population
from veles_tpu.logger import Logger

__all__ = ["GeneticsOptimizer"]


class GeneticsOptimizer(Logger):
    def __init__(self, spec, fitness_fn, generations=5, population=12,
                 workers=0, rng=None, **population_kwargs):
        super(GeneticsOptimizer, self).__init__()
        self.spec = spec
        self.fitness_fn = fitness_fn
        self.generations = generations
        self.workers = workers
        self.tunes = extract_tunes(spec)
        if not self.tunes:
            raise ValueError("spec contains no Tune markers")
        mins = [t.min for _, t in self.tunes]
        maxs = [t.max for _, t in self.tunes]
        self.population = Population(
            mins, maxs, size=population, rng=rng, **population_kwargs)
        self.history = []  # (generation, best_fitness, best_spec)

    def candidate_spec(self, chromosome):
        return apply_values(self.spec, self.tunes, chromosome.values)

    def _evaluate_all(self):
        pending = self.population.unevaluated()
        specs = [self.candidate_spec(c) for c in pending]
        if self.workers and len(pending) > 1:
            with concurrent.futures.ProcessPoolExecutor(
                    max_workers=self.workers) as pool:
                fits = list(pool.map(self.fitness_fn, specs))
        else:
            fits = [self.fitness_fn(spec) for spec in specs]
        for chromo, fitness in zip(pending, fits):
            chromo.fitness = float(fitness)

    def run(self):
        """Returns (best_spec, best_fitness)."""
        for gen in range(self.generations):
            self._evaluate_all()
            best = self.population.best
            self.history.append(
                (gen, best.fitness, self.candidate_spec(best)))
            self.info("generation %d best fitness %.4f", gen,
                      best.fitness)
            if gen < self.generations - 1:
                self.population.evolve()
        best = self.population.best
        return self.candidate_spec(best), best.fitness
