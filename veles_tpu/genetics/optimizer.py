"""GeneticsOptimizer: evolve a population by evaluating candidate
configurations as jobs.

Reference: genetics/optimization_workflow.py:70-260 farmed chromosome
evaluations to slaves as master-slave jobs (each spawning a child veles
process).  Here evaluations run through a pluggable evaluator:

- in-process (default): ``fitness_fn(candidate_spec) -> float``;
- process pool: ``workers=N`` evaluates candidates concurrently in
  subprocesses;
- control plane: ``farm_slaves=N`` farms each generation's candidate
  specs as jobs through the Server/Client stack
  (veles_tpu.jobfarm.JobFarm) — the reference's strategy — with
  remote hosts joining via :meth:`GeneticsOptimizer.worker`; see
  tests/test_genetics.py::test_optimizer_farms_over_control_plane.

Fitness is MAXIMIZED (use -validation_error).
"""

import concurrent.futures

from veles_tpu.genetics.config import apply_values, extract_tunes
from veles_tpu.genetics.core import Population
from veles_tpu.logger import Logger

__all__ = ["GeneticsOptimizer"]


class GeneticsOptimizer(Logger):

    FARM_TAG = "genetics"

    def __init__(self, spec, fitness_fn, generations=5, population=12,
                 workers=0, farm_slaves=0, farm_address="127.0.0.1:0",
                 rng=None, batch_fitness_fn=None, memoize_fitness=True,
                 **population_kwargs):
        super(GeneticsOptimizer, self).__init__()
        self.spec = spec
        self.fitness_fn = fitness_fn
        self.generations = generations
        self.workers = workers
        self.farm_slaves = farm_slaves
        self.farm_address = farm_address
        #: optional whole-generation evaluator ``fn(specs) -> [fitness]``
        #: for fitness functions that must see a generation's candidates
        #: TOGETHER (the schedule autotuner's interleaved round-robin
        #: timing: one sample of every candidate per pass, so a
        #: congestion window cannot crown the wrong candidate).  Ignored
        #: on the farm/process-pool paths, which are per-candidate by
        #: construction.
        self.batch_fitness_fn = batch_fitness_fn
        #: evolve() produces duplicates of already-scored genomes
        #: (elitism copies keep their fitness, but crossover routinely
        #: recreates a parent when both picks agree on a segment) — the
        #: values-keyed memo serves those for free, so a duplicate
        #: genome never pays a second evaluation (for the autotuner:
        #: never a second kernel compile)
        self.memoize_fitness = memoize_fitness
        self._fitness_memo = {}
        self.tunes = extract_tunes(spec)
        if not self.tunes:
            raise ValueError("spec contains no Tune markers")
        mins = [t.min for _, t in self.tunes]
        maxs = [t.max for _, t in self.tunes]
        self.population = Population(
            mins, maxs, size=population, rng=rng, **population_kwargs)
        self.history = []  # (generation, best_fitness, best_spec)
        self._farm = None

    def candidate_spec(self, chromosome):
        return apply_values(self.spec, self.tunes, chromosome.values)

    def worker(self, address):
        """Blocking remote-worker loop: evaluate candidate specs the
        optimizing master at ``address`` hands out (the worker quotes
        the same fitness_fn)."""
        from veles_tpu.jobfarm import JobFarm
        return JobFarm(self.FARM_TAG).worker(address, self.fitness_fn)

    @property
    def farm_enabled(self):
        from veles_tpu.jobfarm import farm_enabled
        return farm_enabled(self.farm_slaves, self.farm_address)

    @staticmethod
    def _genome_key(chromosome):
        return tuple(float(v) for v in chromosome.values)

    def _evaluate_all(self):
        pending = self.population.unevaluated()
        if self.memoize_fitness:
            # serve memo hits, then collapse the remainder onto one
            # representative per DISTINCT genome (within-batch
            # duplicates are also free)
            groups = {}
            for chromo in pending:
                key = self._genome_key(chromo)
                memoized = self._fitness_memo.get(key)
                if memoized is not None:
                    chromo.fitness = memoized
                else:
                    groups.setdefault(key, []).append(chromo)
            reps = [chromos[0] for chromos in groups.values()]
        else:
            groups = None
            reps = pending
        specs = [self.candidate_spec(c) for c in reps]
        if self.farm_enabled and specs:
            # ONE farm for the whole optimization: remote workers stay
            # connected between generations (a fresh server per batch
            # would disconnect them after generation 0)
            if self._farm is None:
                from veles_tpu.jobfarm import JobFarm
                self._farm = JobFarm(self.FARM_TAG).start(
                    runner=self.fitness_fn,
                    address=self.farm_address,
                    local_slaves=self.farm_slaves)
            fits = self._farm.submit(specs)
        elif self.workers and len(reps) > 1:
            with concurrent.futures.ProcessPoolExecutor(
                    max_workers=self.workers) as pool:
                fits = list(pool.map(self.fitness_fn, specs))
        elif self.batch_fitness_fn is not None:
            fits = list(self.batch_fitness_fn(specs)) if specs else []
        else:
            fits = [self.fitness_fn(spec) for spec in specs]
        for chromo, fitness in zip(reps, fits):
            fitness = float(fitness)
            if groups is None:
                chromo.fitness = fitness
                continue
            key = self._genome_key(chromo)
            self._fitness_memo[key] = fitness
            for duplicate in groups[key]:
                duplicate.fitness = fitness

    def run(self):
        """Returns (best_spec, best_fitness)."""
        try:
            for gen in range(self.generations):
                self._evaluate_all()
                best = self.population.best
                self.history.append(
                    (gen, best.fitness, self.candidate_spec(best)))
                self.info("generation %d best fitness %.4f", gen,
                          best.fitness)
                if gen < self.generations - 1:
                    self.population.evolve()
        finally:
            if self._farm is not None:
                self._farm.shutdown()
                self._farm = None
        best = self.population.best
        return self.candidate_spec(best), best.fitness
