"""GA core: chromosomes, population, selection and mutation operators.

Reference behaviors covered (genetics/core.py:122-370): numeric and
gray-coded binary chromosome representations, roulette-wheel selection,
single/two-point crossover, several mutation operators (uniform reset,
gaussian jitter, binary bit-flip), elitism, reproducibility through the
keyed PRNG.
"""

import numpy

from veles_tpu import prng as prng_module

__all__ = ["Chromosome", "Population", "gray_encode", "gray_decode"]


def gray_encode(value, vmin, vmax, bits):
    """Quantize value in [vmin, vmax] to a gray-coded integer."""
    span = (1 << bits) - 1
    frac = 0.0 if vmax == vmin else (value - vmin) / (vmax - vmin)
    n = int(round(numpy.clip(frac, 0.0, 1.0) * span))
    return n ^ (n >> 1)

def gray_decode(code, vmin, vmax, bits):
    n = code
    shift = 1
    while shift < bits:
        n ^= n >> shift
        shift <<= 1
    span = (1 << bits) - 1
    return vmin + (vmax - vmin) * (n / span if span else 0.0)


class Chromosome(object):
    """One candidate: numeric genome over [mins, maxs] boxes.

    ``binary_bits``: when set, genes live as gray-coded integers of that
    many bits (the reference's binary representation); mutation flips
    bits instead of jittering floats.
    """

    def __init__(self, mins, maxs, rng, values=None, binary_bits=None):
        self.mins = numpy.asarray(mins, numpy.float64)
        self.maxs = numpy.asarray(maxs, numpy.float64)
        self.binary_bits = binary_bits
        self.fitness = None
        if values is not None:
            self.values = numpy.asarray(values, numpy.float64)
        else:
            self.values = self.mins + rng.random_sample(
                len(self.mins)) * (self.maxs - self.mins)

    def copy(self):
        c = Chromosome(self.mins, self.maxs, None, values=self.values,
                       binary_bits=self.binary_bits)
        c.fitness = self.fitness
        return c

    # -- mutation operators --------------------------------------------------

    def mutate_uniform(self, rng, rate):
        for i in range(len(self.values)):
            if rng.random_sample() < rate:
                self.values[i] = self.mins[i] + rng.random_sample() * (
                    self.maxs[i] - self.mins[i])
        self.fitness = None

    def mutate_gaussian(self, rng, rate, scale=0.1):
        for i in range(len(self.values)):
            if rng.random_sample() < rate:
                span = self.maxs[i] - self.mins[i]
                self.values[i] = float(numpy.clip(
                    self.values[i] + rng.normal(0, scale * span),
                    self.mins[i], self.maxs[i]))
        self.fitness = None

    def mutate_binary(self, rng, rate):
        bits = self.binary_bits or 16
        for i in range(len(self.values)):
            code = gray_encode(self.values[i], self.mins[i], self.maxs[i],
                               bits)
            for b in range(bits):
                if rng.random_sample() < rate:
                    code ^= 1 << b
            self.values[i] = gray_decode(code, self.mins[i], self.maxs[i],
                                         bits)
        self.fitness = None


class Population(object):
    """Roulette GA loop (reference genetics/core.py:371-).

    fitness is MAXIMIZED; use -metric for minimization.
    """

    def __init__(self, mins, maxs, size=20, rng=None, binary_bits=None,
                 crossover="two_point", mutation="gaussian",
                 mutation_rate=0.2, elite=2):
        self.rng = rng or prng_module.get("genetics")
        self.mins = list(mins)
        self.maxs = list(maxs)
        self.binary_bits = binary_bits
        self.crossover = crossover
        self.mutation = mutation
        self.mutation_rate = mutation_rate
        self.elite = elite
        self.generation = 0
        self.chromosomes = [
            Chromosome(mins, maxs, self.rng, binary_bits=binary_bits)
            for _ in range(size)]

    @property
    def best(self):
        evaluated = [c for c in self.chromosomes if c.fitness is not None]
        return max(evaluated, key=lambda c: c.fitness) if evaluated \
            else None

    def unevaluated(self):
        return [c for c in self.chromosomes if c.fitness is None]

    # -- selection -----------------------------------------------------------

    def _roulette_pick(self):
        fits = numpy.array([c.fitness for c in self.chromosomes],
                           numpy.float64)
        shifted = fits - fits.min() + 1e-12
        probs = shifted / shifted.sum()
        r = self.rng.random_sample()
        return self.chromosomes[int(numpy.searchsorted(
            numpy.cumsum(probs), r))]

    def _crossover(self, a, b):
        n = len(a.values)
        values = numpy.array(a.values)
        if self.crossover == "single_point":
            point = int(self.rng.random_sample() * n)
            values[point:] = b.values[point:]
        elif self.crossover == "two_point":
            p1 = int(self.rng.random_sample() * n)
            p2 = int(self.rng.random_sample() * n)
            p1, p2 = min(p1, p2), max(p1, p2)
            values[p1:p2] = b.values[p1:p2]
        else:  # uniform
            for i in range(n):
                if self.rng.random_sample() < 0.5:
                    values[i] = b.values[i]
        return Chromosome(self.mins, self.maxs, self.rng, values=values,
                          binary_bits=self.binary_bits)

    def _mutate(self, chromo):
        if self.mutation == "uniform":
            chromo.mutate_uniform(self.rng, self.mutation_rate)
        elif self.mutation == "binary":
            chromo.mutate_binary(self.rng, self.mutation_rate)
        else:
            chromo.mutate_gaussian(self.rng, self.mutation_rate)

    def evolve(self):
        """All chromosomes must be evaluated; produce the next
        generation (elitism + roulette crossover + mutation)."""
        if self.unevaluated():
            raise RuntimeError("evolve() with unevaluated chromosomes")
        ranked = sorted(self.chromosomes, key=lambda c: -c.fitness)
        next_gen = [c.copy() for c in ranked[:self.elite]]
        while len(next_gen) < len(self.chromosomes):
            child = self._crossover(self._roulette_pick(),
                                    self._roulette_pick())
            self._mutate(child)
            next_gen.append(child)
        self.chromosomes = next_gen
        self.generation += 1
