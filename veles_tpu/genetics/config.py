"""Tune markers: declare which config/hyper values the GA may vary.

Reference: genetics/config.py:45-110 wrapped config leaves in
``Tune(value, min, max)``; the optimizer collected them into a
chromosome and wrote candidate values back before each evaluation.
Here Tune works on any nested dict/list structure (including the layer
specs fed to StandardWorkflow) as well as the global Config tree.
"""

__all__ = ["Tune", "extract_tunes", "apply_values"]


class Tune(object):
    """A tunable leaf: default value + allowed [min, max] box."""

    def __init__(self, value, minimum, maximum):
        self.value = value
        self.min = minimum
        self.max = maximum

    def __repr__(self):
        return "Tune(%s, %s, %s)" % (self.value, self.min, self.max)


def _walk(obj, path, found):
    if isinstance(obj, Tune):
        found.append((path, obj))
    elif isinstance(obj, dict):
        for key, value in obj.items():
            _walk(value, path + (key,), found)
    elif isinstance(obj, (list, tuple)):
        for i, value in enumerate(obj):
            _walk(value, path + (i,), found)


def extract_tunes(spec):
    """Return [(path, Tune), ...] in deterministic order."""
    found = []
    _walk(spec, (), found)
    found.sort(key=lambda pair: str(pair[0]))
    return found


def apply_values(spec, tunes, values):
    """Deep-copy ``spec`` with each Tune leaf replaced by its candidate
    value (int-preserving when the Tune default was an int)."""
    import copy
    result = copy.deepcopy(spec)
    for (path, tune), value in zip(tunes, values):
        if isinstance(tune.value, int) and not isinstance(tune.value, bool):
            value = int(round(value))
        node = result
        for key in path[:-1]:
            node = node[key]
        node[path[-1]] = value
    return result
