"""Hyper-parameter optimization by genetic algorithm.

TPU-native counterpart of reference veles/genetics/ (core.py:122-370
Chromosome/Population, config.py:45-223 Tune markers,
optimization_workflow.py:70-260 job-farming optimizer).
"""

from veles_tpu.genetics.core import (  # noqa: F401
    Chromosome, Population, gray_encode, gray_decode)
from veles_tpu.genetics.config import Tune, extract_tunes, apply_values  # noqa
from veles_tpu.genetics.optimizer import GeneticsOptimizer  # noqa: F401
