"""Numerics health: shared finiteness checks and watchdog failures.

One bad minibatch (or one sick worker) must never silently poison a
training run: the fused step and the per-unit gd chain *skip* non-finite
updates (docs/health.md), the decision unit detects divergence and
triggers :meth:`veles_tpu.snapshotter.Snapshotter.rollback`, and the
master validates slave updates with :func:`all_finite` before applying
them.  This module holds the pieces every plane shares so the guards
cannot drift apart.
"""

import math

import numpy

__all__ = ["all_finite", "DivergenceError", "EmaSpikeWatch",
           "RollbackExhausted", "is_finite_metric", "PoisonedUpdate"]


class DivergenceError(RuntimeError):
    """Training diverged and no recovery path exists (no snapshotter
    attached, or nothing good to roll back to).  Raised loudly instead
    of letting the run converge to garbage."""


class RollbackExhausted(DivergenceError):
    """The bounded rollback retry budget is spent and the run still
    diverges; continuing would loop rollback -> divergence forever."""


class PoisonedUpdate(RuntimeError):
    """A slave update failed the inline finiteness validation
    (``Workflow.apply_update_validated``).  Raised BEFORE the poisoned
    part touched any state; the server's quarantine path treats it
    exactly like a failed pre-walk (drop + TTL blacklist + requeue)."""

    def __init__(self, unit=None):
        name = type(unit).__name__ if unit is not None else "?"
        super(PoisonedUpdate, self).__init__(
            "non-finite update part for unit %s" % name)
        self.unit_name = name


def is_finite_metric(metric):
    """True only for a real, finite scalar metric.  ``None`` and NaN
    both fail: ``NaN < best`` is silently False, so a NaN metric could
    otherwise be *recorded as best* when no best exists yet."""
    if metric is None:
        return False
    try:
        return math.isfinite(float(metric))
    except (TypeError, ValueError):
        return False


def all_finite(obj):
    """Recursively check a payload tree (the master-slave update wire
    format: nested dicts/lists of numpy arrays and scalars) for
    non-finite floats.  Non-numeric leaves (str, bytes, bool, None) and
    integer arrays are vacuously finite.  Used by the master to
    validate a slave's update BEFORE ``apply_data_from_slave`` — a NaN
    delta merged into global weights poisons every other slave's next
    job."""
    if obj is None or isinstance(obj, (bool, int, str, bytes)):
        return True
    if isinstance(obj, float):
        return math.isfinite(obj)
    if isinstance(obj, numpy.ndarray):
        if obj.dtype.kind not in "fc":
            return True
        return bool(numpy.isfinite(obj).all())
    if isinstance(obj, numpy.generic):
        if obj.dtype.kind not in "fc":
            return True
        return bool(numpy.isfinite(obj))
    if isinstance(obj, dict):
        return all(all_finite(v) for v in obj.values())
    if isinstance(obj, (list, tuple, set, frozenset)):
        return all(all_finite(v) for v in obj)
    # jax arrays (and anything array-like) reach here via __array__
    try:
        arr = numpy.asarray(obj)
    except Exception:
        return True  # opaque object: nothing numeric to validate
    if arr.dtype.kind not in "fc":
        return True
    return bool(numpy.isfinite(arr).all())


class EmaSpikeWatch(object):
    """The EMA spike discipline the divergence watchdog trips on
    (docs/health.md), extracted so every plane that needs "has this
    series suddenly gone bad?" shares ONE definition: the decision
    unit's train-metric watchdog, and the serve fleet's canary
    comparator (docs/serving.md "Freshness loop").

    Semantics (bit-for-bit the pre-extraction decision logic): a value
    spikes when ``value > spike_factor * max(EMA, spike_floor)`` and an
    EMA exists; a spiking value is reported and NOT folded into the EMA
    (one outlier must not drag the baseline up to meet the next one),
    while a healthy value updates ``EMA = beta * EMA + (1-beta) *
    value``.  The floor keeps near-zero converged baselines from
    turning ordinary noise into "spikes".  Callers gate non-finite
    values themselves (:func:`is_finite_metric`) — NaN comparisons are
    silently False and would sail through."""

    def __init__(self, spike_factor=10.0, spike_floor=1.0, beta=0.5,
                 label="value"):
        self.spike_factor = float(spike_factor)
        self.spike_floor = float(spike_floor)
        self.beta = float(beta)
        self.label = label
        self.ema = None

    def reset(self):
        """Start a fresh observation window (post-rollback)."""
        self.ema = None

    def observe(self, value):
        """Fold a trusted baseline value into the EMA WITHOUT a spike
        check — how the canary comparator primes its latency baseline
        from the live fleet before judging the candidate against it."""
        value = float(value)
        self.ema = value if self.ema is None else \
            self.beta * self.ema + (1.0 - self.beta) * value

    def update(self, value):
        """Check ``value`` against the spike threshold, then fold it in
        when healthy.  Returns the human-readable spike reason, or
        None."""
        value = float(value)
        threshold = self.spike_factor * max(
            self.ema if self.ema is not None else value,
            self.spike_floor)
        if self.ema is not None and value > threshold:
            return "%s spiked to %.4g (EMA %.4g, threshold %.4g)" % (
                self.label, value, self.ema, threshold)
        self.observe(value)
        return None
