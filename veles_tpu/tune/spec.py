"""Per-kernel-family search spaces for the schedule autotuner.

One family per parameterized Pallas kernel (docs/kernels.md):

- ``matmul`` — ``ops/matmul.py``'s (bm, bn, bk) tiles;
- ``conv_vjp`` — ``ops/conv_vjp.py``'s (bi, bj, bk) wgrad tiles;
- ``pool_bwd`` — ``ops/pool_bwd.py``'s output-width block (W tiling);
- ``attention`` — ``ops/attention.py``'s (bq, bk) flash tiles.

Each family owns four things the GA needs: the **search space** as
:class:`veles_tpu.genetics.config.Tune` markers (so the stock
GeneticsOptimizer drives it unchanged), **quantization** of raw genes
to MXU-legal multiples (sublane 8 on the second-minor axis, lane 128
on the minor axis — Mosaic tiles below the hardware quanta just pad
back up, so off-grid genes are pure duplicate schedules), a **VMEM
feasibility** check that rejects overflowing candidates BEFORE any
compile is paid, and a **runner builder** that turns (spec, schedule)
into the timed callable the shared measurement discipline
(``tune/measure.py``) ranks.

The ``*_spec`` builders at the bottom are the ONE definition of each
family's cache-key coordinates — the kernels' consult sites and the
MFU-attribution provenance lookups both call them, so the key a tuner
writes is byte-identical to the key a kernel later reads.

Schedules change tile/grid SCHEDULING only, never math: the precision
level and dtype are key coordinates, not genes, and the parity tests
(tests/test_tune.py) hold tuned-vs-static results bit-equal on
representable operands.
"""

import functools
import logging

from veles_tpu.genetics.config import Tune

__all__ = ["FAMILIES", "family_for", "matmul_spec", "matmul_int8_spec",
           "conv_vjp_spec", "pool_bwd_spec", "attention_spec",
           "valid_schedule", "matmul_seed_candidates",
           "current_kernel_version", "TUNE_VMEM_BUDGET_BYTES"]

logger = logging.getLogger("veles_tpu.tune")

#: per-grid-step VMEM ceiling for candidate REJECTION before compile —
#: aligned with ops/pool_bwd.POOL_VMEM_BUDGET_BYTES; the compile-time
#: Mosaic check stays the backstop for shapes that squeak past
TUNE_VMEM_BUDGET_BYTES = 12 * 2 ** 20

_warned = set()


def _warn_once(key, message, *args):
    if key not in _warned:
        _warned.add(key)
        logger.warning(message, *args)


def _ceil_mult(value, mult):
    rem = value % mult
    return value if rem == 0 else value + mult - rem


def _quant(value, mult, lo, hi):
    """Round a raw gene to the nearest legal multiple inside
    [lo, hi] — clamped duplicates collapse onto one schedule, which the
    tuner's fitness memo then serves for free."""
    q = int(round(float(value) / mult)) * mult
    if q < mult:
        q = mult
    return max(lo, min(hi, q))


def _itemsize(dtype):
    import numpy
    if str(dtype) == "bfloat16":
        return 2
    return numpy.dtype(str(dtype)).itemsize


def matmul_seed_candidates(dtype, precision_level):
    """ops/matmul.py's curated tile list — measured winners on real
    chips, kept as the GA's seed population AND the plain candidate
    sweep ``autotune_matmul`` still runs."""
    candidates = [(256, 256, 256), (512, 512, 512), (512, 512, 1024),
                  (512, 512, 2048), (256, 256, 1024), (512, 1024, 512),
                  (1024, 512, 512), (256, 512, 1024)]
    if str(dtype) == "float32" and precision_level in (0, 1):
        # taller-M / wider-N tiles for the f32 paths (level 0's three
        # bf16 dots per K-step and level 1's six-pass HIGHEST products
        # + Kahan both shift the VMEM/compute balance away from the
        # square default): a (768, 512, 512) tile measured ~1.25x over
        # (512, 512, 512) at 3001^2 on v5e for level 0
        candidates += [(768, 512, 512), (640, 512, 512),
                       (512, 640, 512), (512, 640, 640)]
    return candidates


class MatmulFamily(object):
    """(bm, bn, bk) tiles of the tiled Pallas matmul."""

    name = "matmul"

    def space(self, spec):
        mp, kp, np_ = spec["shape"]
        return {
            "bm": Tune(min(512, mp), 8, min(1024, mp)),
            "bn": Tune(min(512, np_), 128, min(2048, np_)),
            "bk": Tune(min(512, kp), 128, min(2048, kp)),
        }

    def quantize(self, spec, genes):
        mp, kp, np_ = spec["shape"]
        return {"blocks": [
            _quant(genes["bm"], 8, 8, min(1024, mp)),
            _quant(genes["bn"], 128, 128, min(2048, np_)),
            _quant(genes["bk"], 128, 128, min(2048, kp)),
        ]}

    def footprint(self, spec, schedule):
        bm, bn, bk = schedule["blocks"]
        isz = _itemsize(spec["dtype"])
        return (bm * bk * isz + bk * bn * isz   # a + b blocks
                + 2 * bm * bn * 4               # f32 acc + comp
                + bm * bn * isz)                # out block

    def feasible(self, spec, schedule):
        return self.footprint(spec, schedule) <= TUNE_VMEM_BUDGET_BYTES

    def seeds(self, spec):
        # the GA seeds at most `population` chromosomes, so the
        # dtype-specific measured winners (appended LAST in the sweep's
        # curated order) go FIRST here — a population of 8 must not
        # silently drop the known f32 best tiles
        curated = matmul_seed_candidates(spec["dtype"],
                                         spec["precision_level"])
        generic = matmul_seed_candidates("bfloat16", 2)
        specific = [c for c in curated if c not in generic]
        return [{"blocks": list(c)} for c in specific + generic]

    def default(self, spec):
        from veles_tpu.ops import matmul as _m
        return {"blocks": list(_m._DEFAULT_BLOCKS)}

    def genes_of(self, schedule):
        bm, bn, bk = schedule["blocks"]
        return {"bm": bm, "bn": bn, "bk": bk}

    def validate(self, schedule):
        blocks = schedule.get("blocks")
        if (isinstance(blocks, (list, tuple)) and len(blocks) == 3
                and all(isinstance(b, int) and b > 0 for b in blocks)
                and blocks[0] % 8 == 0 and blocks[1] % 128 == 0
                and blocks[2] % 128 == 0):
            return {"blocks": [int(b) for b in blocks]}
        return None

    def build_runner(self, spec, schedule):
        """(warm, run): ``warm()`` compiles (VMEM-overflow candidates
        raise here, before any timed chain); ``run(n)`` executes an
        n-long chain ended by a completion fetch.  Square self-multiply
        shapes chain DEPENDENTLY (matmul_benchmark's methodology);
        rectangular shapes queue n dispatches and block once."""
        import jax
        import jax.numpy as jnp
        import numpy

        from veles_tpu.ops.matmul import matmul

        m, k, n = spec.get("raw", {}).get("mkn", spec["shape"])
        rng = numpy.random.RandomState(13)
        dtype = jnp.dtype(spec["dtype"]) if spec["dtype"] != "bfloat16" \
            else jnp.bfloat16
        a = jnp.asarray((rng.rand(m, k) - 0.5) * 0.01, dtype)
        b = jnp.asarray((rng.rand(k, n) - 0.5) * 0.01, dtype)
        blocks = tuple(schedule["blocks"])
        level = spec["precision_level"]

        if k == n:
            def mm(x):
                return matmul(x, b, precision_level=level,
                              blocks=blocks)

            def run(count):
                acc = a
                for _ in range(count):
                    acc = mm(acc)
                float(acc[0, 0].astype(jnp.float32))
        else:
            def run(count):
                out = None
                for _ in range(count):
                    out = matmul(a, b, precision_level=level,
                                 blocks=blocks)
                jax.block_until_ready(out)

        def warm():
            run(1)

        return warm, run


class MatmulInt8Family(object):
    """(bm, bn, bk) tiles of the int8 quantized matmul
    (``ops/matmul_int8.py``) — its OWN family: int8 shifts the
    MXU-legal quanta (sublane 32 on M vs f32's 8, lanes still 128) and
    the VMEM balance (1-byte operand tiles vs a 4-byte int32
    accumulator), so f32-tuned tiles are off-grid here and the digest
    carries ``MATMUL_INT8_KERNEL_VERSION`` so neither family can ever
    serve the other."""

    name = "matmul_int8"

    def space(self, spec):
        mp, kp, np_ = spec["shape"]
        return {
            "bm": Tune(min(256, mp), 32, min(1024, mp)),
            "bn": Tune(min(512, np_), 128, min(2048, np_)),
            "bk": Tune(min(512, kp), 128, min(2048, kp)),
        }

    def quantize(self, spec, genes):
        mp, kp, np_ = spec["shape"]
        return {"blocks": [
            _quant(genes["bm"], 32, 32, min(1024, mp)),
            _quant(genes["bn"], 128, 128, min(2048, np_)),
            _quant(genes["bk"], 128, 128, min(2048, kp)),
        ]}

    def footprint(self, spec, schedule):
        bm, bn, bk = schedule["blocks"]
        return (bm * bk + bk * bn     # int8 a + b blocks (1 B)
                + bm * bn * 4         # int32 accumulator
                + bm * bn * 4         # f32 out block
                + 2 * bn * 4)         # scale + bias rows

    def feasible(self, spec, schedule):
        return self.footprint(spec, schedule) <= TUNE_VMEM_BUDGET_BYTES

    def seeds(self, spec):
        return [{"blocks": list(c)} for c in
                [(256, 512, 512), (512, 512, 512), (256, 256, 512),
                 (512, 512, 1024), (256, 512, 1024), (128, 512, 512)]]

    def default(self, spec):
        from veles_tpu.ops import matmul_int8 as _m
        return {"blocks": list(_m._DEFAULT_BLOCKS)}

    def genes_of(self, schedule):
        bm, bn, bk = schedule["blocks"]
        return {"bm": bm, "bn": bn, "bk": bk}

    def validate(self, schedule):
        blocks = schedule.get("blocks")
        if (isinstance(blocks, (list, tuple)) and len(blocks) == 3
                and all(isinstance(b, int) and b > 0 for b in blocks)
                and blocks[0] % 32 == 0 and blocks[1] % 128 == 0
                and blocks[2] % 128 == 0):
            return {"blocks": [int(b) for b in blocks]}
        return None

    def build_runner(self, spec, schedule):
        """Queued-dispatch runner: the int8 matmul's output is f32, so
        there is no dependent int8 chain to thread — ``run(n)`` queues
        n dispatches and blocks once, like the rectangular f32 path."""
        import jax
        import jax.numpy as jnp
        import numpy

        from veles_tpu.ops.matmul_int8 import matmul_int8

        m, k, n = spec.get("raw", {}).get("mkn", spec["shape"])
        rng = numpy.random.RandomState(17)
        a = jnp.asarray(rng.randint(-127, 128, (m, k)), jnp.int8)
        b = jnp.asarray(rng.randint(-127, 128, (k, n)), jnp.int8)
        scale = jnp.asarray(rng.rand(n) * 1e-3 + 1e-4, jnp.float32)
        blocks = tuple(schedule["blocks"])

        def run(count):
            out = None
            for _ in range(count):
                out = matmul_int8(a, b, scale, blocks=blocks)
            jax.block_until_ready(out)

        def warm():
            run(1)

        return warm, run


class ConvVjpFamily(object):
    """(bi, bj, bk) = (Cin, Cout, P) tiles of the fused conv-VJP
    wgrad contraction."""

    name = "conv_vjp"

    def space(self, spec):
        _taps, pp, cip, cop = spec["shape"]
        return {
            "bi": Tune(min(256, cip), 128, min(1024, cip)),
            "bj": Tune(min(256, cop), 128, min(1024, cop)),
            "bk": Tune(min(512, pp), 8, min(2048, pp)),
        }

    def quantize(self, spec, genes):
        _taps, pp, cip, cop = spec["shape"]
        return {"blocks": [
            _quant(genes["bi"], 128, 128, min(1024, cip)),
            _quant(genes["bj"], 128, 128, min(1024, cop)),
            _quant(genes["bk"], 8, 8, min(2048, pp)),
        ]}

    def footprint(self, spec, schedule):
        bi, bj, bk = schedule["blocks"]
        isz = _itemsize(spec["dtype"])
        return (bk * bi * isz          # tap-stack block
                + 2 * bk * bj * isz    # y + dy blocks
                + bk * bj * isz        # err out block
                + bi * bj * 4          # gw out block (f32)
                + 2 * bi * bj * 4      # acc + comp scratch
                + 8 * bj * 4)          # bias scratch

    def feasible(self, spec, schedule):
        return self.footprint(spec, schedule) <= TUNE_VMEM_BUDGET_BYTES

    def seeds(self, spec):
        return [{"blocks": list(c)} for c in
                [(256, 256, 512), (128, 256, 512), (256, 128, 512),
                 (256, 256, 1024), (128, 128, 256), (512, 256, 512)]]

    def default(self, spec):
        from veles_tpu.ops import conv_vjp as _c
        return {"blocks": list(_c._DEFAULT_BLOCKS)}

    def genes_of(self, schedule):
        bi, bj, bk = schedule["blocks"]
        return {"bi": bi, "bj": bj, "bk": bk}

    def validate(self, schedule):
        blocks = schedule.get("blocks")
        if (isinstance(blocks, (list, tuple)) and len(blocks) == 3
                and all(isinstance(b, int) and b > 0 for b in blocks)
                and blocks[0] % 128 == 0 and blocks[1] % 128 == 0
                and blocks[2] % 8 == 0):
            return {"blocks": [int(b) for b in blocks]}
        return None

    def build_runner(self, spec, schedule):
        import jax
        import jax.numpy as jnp
        import numpy

        from veles_tpu.ops.conv_vjp import fused_conv_vjp

        raw = spec["raw"]
        n, h, w_sp, ci = raw["x_shape"]
        oh, ow = raw["y_hw"]
        ky, kx, cout = raw["ky"], raw["kx"], raw["cout"]
        rng = numpy.random.RandomState(7)
        dtype = jnp.bfloat16 if spec["dtype"] == "bfloat16" \
            else jnp.dtype(spec["dtype"])
        x = jnp.asarray(rng.randn(n, h, w_sp, ci) * 0.1, dtype)
        w = jnp.asarray(rng.randn(ky, kx, ci, cout) * 0.1, dtype)
        y = jnp.asarray(rng.randn(n, oh, ow, cout) * 0.1, dtype)
        dy = jnp.asarray(rng.randn(n, oh, ow, cout) * 0.1, dtype)
        blocks = tuple(schedule["blocks"])

        def run(count):
            gw = None
            for _ in range(count):
                _, gw, _ = fused_conv_vjp(
                    x, w, y, dy, activation=raw["activation"],
                    padding=tuple(raw["padding"]),
                    sliding=tuple(raw["sliding"]),
                    need_err_input=False,
                    precision_level=spec["precision_level"],
                    blocks=blocks)
            jax.block_until_ready(gw)

        def warm():
            run(1)

        return warm, run


class AttentionFamily(object):
    """(bq, bk) q/k tiles of the flash-attention kernels
    (``ops/attention.py``).  bq rides sublanes of the score tile
    (quantum 8); bk rides its lanes (quantum 128).  The head dim is
    lane-padded to 128 and is a key coordinate, not a gene — the
    kernel holds a whole (padded) head row per tile."""

    name = "attention"

    def space(self, spec):
        _b, tq, tk, _dhp = spec["shape"]
        return {
            "bq": Tune(min(256, tq), 8, min(1024, tq)),
            "bk": Tune(min(256, tk), 128, min(2048, tk)),
        }

    def quantize(self, spec, genes):
        _b, tq, tk, _dhp = spec["shape"]
        return {"blocks": [
            _quant(genes["bq"], 8, 8, min(1024, tq)),
            _quant(genes["bk"], 128, 128, min(2048, tk)),
        ]}

    def footprint(self, spec, schedule):
        bq, bk = schedule["blocks"]
        dhp = spec["shape"][3]
        isz = _itemsize(spec["dtype"])
        return (bq * dhp * isz          # q block
                + 2 * bk * dhp * isz    # k + v blocks
                + bq * dhp * isz        # out block
                + bq * dhp * 4          # f32 acc scratch
                + 2 * bq * 128 * 4      # m + l scratch
                + bq * 128 * 4          # lse block
                + 2 * bq * bk * 4)      # score + prob tiles

    def feasible(self, spec, schedule):
        return self.footprint(spec, schedule) <= TUNE_VMEM_BUDGET_BYTES

    def seeds(self, spec):
        return [{"blocks": list(c)} for c in
                [(256, 256), (128, 256), (256, 512), (512, 256),
                 (128, 128), (512, 512)]]

    def default(self, spec):
        from veles_tpu.ops import attention as _a
        return {"blocks": list(_a._DEFAULT_BLOCKS)}

    def genes_of(self, schedule):
        bq, bk = schedule["blocks"]
        return {"bq": bq, "bk": bk}

    def validate(self, schedule):
        blocks = schedule.get("blocks")
        if (isinstance(blocks, (list, tuple)) and len(blocks) == 2
                and all(isinstance(b, int) and b > 0 for b in blocks)
                and blocks[0] % 8 == 0 and blocks[1] % 128 == 0):
            return {"blocks": [int(b) for b in blocks]}
        return None

    def build_runner(self, spec, schedule):
        """Queued-dispatch runner over the full custom_vjp step
        (forward + both backward kernels via jax.grad — the composition
        a train step actually pays for)."""
        import jax
        import jax.numpy as jnp
        import numpy

        from veles_tpu.ops.attention import flash_attention

        b, t, dh = spec["raw"]["btd"]
        rng = numpy.random.RandomState(23)
        dtype = jnp.bfloat16 if spec["dtype"] == "bfloat16" \
            else jnp.dtype(spec["dtype"])
        q = jnp.asarray(rng.randn(b, t, dh) * 0.1, dtype)
        k = jnp.asarray(rng.randn(b, t, dh) * 0.1, dtype)
        v = jnp.asarray(rng.randn(b, t, dh) * 0.1, dtype)
        blocks = tuple(schedule["blocks"])
        level = spec["precision_level"]

        grad = jax.grad(lambda q_, k_, v_: jnp.sum(
            flash_attention(q_, k_, v_, precision_level=level,
                            blocks=blocks).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))

        def run(count):
            out = None
            for _ in range(count):
                out = grad(q, k, v)
            jax.block_until_ready(out)

        def warm():
            run(1)

        return warm, run


class PoolBwdFamily(object):
    """Output-width block (W tiling) of the pool select-and-scatter
    backward.  Only non-overlapping windows (kx == sx, ky == sy) admit
    halo-free W tiling, so overlapping shapes are untunable."""

    name = "pool_bwd"

    def space(self, spec):
        _n, _h, _w, _c, _oh, ow, ky, kx, sy, sx = spec["shape"]
        if kx != sx or ky != sy or ow < 2:
            return None  # untunable: no halo-free W tiling exists
        return {"owb": Tune(ow, 1, ow)}

    def quantize(self, spec, genes):
        ow = spec["shape"][5]
        owb = int(round(float(genes["owb"])))
        return {"owb": max(1, min(ow, owb))}

    def footprint(self, spec, schedule):
        # the kernel planner's OWN footprint formula — shared, so the
        # feasibility gate can never drift from what Mosaic gets
        from veles_tpu.ops.pool_bwd import pool_block_footprint
        n, h, w_sp, c, oh, ow, ky, kx, sy, sx = spec["shape"]
        return pool_block_footprint(
            h, c, oh, schedule["owb"], (ky, kx), (sx, sy),
            _itemsize(spec["dtype"]))

    def feasible(self, spec, schedule):
        from veles_tpu.ops.pool_bwd import POOL_VMEM_BUDGET_BYTES
        return (self.footprint(spec, schedule)
                <= POOL_VMEM_BUDGET_BYTES)

    def seeds(self, spec):
        ow = spec["shape"][5]
        owbs = sorted({ow, -(-ow // 2), -(-ow // 4), 1}, reverse=True)
        return [{"owb": owb} for owb in owbs if owb >= 1]

    def default(self, spec):
        ow = spec["shape"][5]
        return {"owb": ow}

    def genes_of(self, schedule):
        return {"owb": schedule["owb"]}

    def validate(self, schedule):
        owb = schedule.get("owb")
        if isinstance(owb, int) and owb > 0:
            return {"owb": owb}
        return None

    def build_runner(self, spec, schedule):
        import jax
        import jax.numpy as jnp
        import numpy

        from veles_tpu.models.pooling import MaxPooling
        from veles_tpu.ops.pool_bwd import max_pool_bwd

        raw = spec["raw"]
        n, h, w_sp, c = raw["x_shape"]
        window = tuple(raw["window"])
        sliding = tuple(raw["sliding"])
        rng = numpy.random.RandomState(5)
        dtype = jnp.bfloat16 if spec["dtype"] == "bfloat16" \
            else jnp.dtype(spec["dtype"])
        x = jnp.asarray(rng.randn(n, h, w_sp, c), dtype)
        y = MaxPooling.apply({}, x, window=window, sliding=sliding,
                             pallas_bwd=False)
        dy = jnp.asarray(rng.randn(*y.shape), dtype)
        owb = int(schedule["owb"])

        def run(count):
            out = None
            for _ in range(count):
                out = max_pool_bwd(x, y, dy, window=window,
                                   sliding=sliding, owb=owb)
            jax.block_until_ready(out)

        def warm():
            run(1)

        return warm, run


FAMILIES = {
    "matmul": MatmulFamily(),
    "matmul_int8": MatmulInt8Family(),
    "conv_vjp": ConvVjpFamily(),
    "pool_bwd": PoolBwdFamily(),
    "attention": AttentionFamily(),
}


def family_for(op):
    family = FAMILIES.get(op)
    if family is None:
        raise KeyError("unknown kernel family %r (have %s)" %
                       (op, sorted(FAMILIES)))
    return family


def current_kernel_version(op):
    """The family's CURRENT kernel algorithm version (the value its
    ``*_spec`` builder rides in ``extra``) or None for families without
    one — the measurement log's staleness coordinate: triples measured
    on an old algorithm must not train the cost model for a new one."""
    if op in ("matmul",):
        from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
        return MATMUL_KERNEL_VERSION
    if op == "matmul_int8":
        from veles_tpu.ops.matmul_int8 import MATMUL_INT8_KERNEL_VERSION
        return MATMUL_INT8_KERNEL_VERSION
    if op == "conv_vjp":
        from veles_tpu.ops.conv_vjp import CONV_VJP_KERNEL_VERSION
        return CONV_VJP_KERNEL_VERSION
    if op == "attention":
        from veles_tpu.ops.attention import ATTENTION_KERNEL_VERSION
        return ATTENTION_KERNEL_VERSION
    if op == "pool_bwd":
        from veles_tpu.ops.pool_bwd import POOL_BWD_KERNEL_VERSION
        return POOL_BWD_KERNEL_VERSION
    return None


def valid_schedule(op, schedule):
    """Structural validation of a cache-served schedule: the family's
    normalized dict, or None (with ONE warning) for anything malformed
    — a stale/corrupt entry must degrade to the static tables, never
    crash a kernel call."""
    family = FAMILIES.get(op)
    if family is None or not isinstance(schedule, dict):
        return None
    normalized = family.validate(schedule)
    if normalized is None:
        _warn_once(
            ("invalid", op, str(schedule)),
            "ignoring malformed tuned schedule for %s: %r (static "
            "tables serve this shape)", op, schedule)
    return normalized


# -- cache-key spec builders (ONE definition per family) ---------------------


def matmul_spec(m, k, n, dtype, precision_level):
    """The matmul consult/tune spec: shape is PADDED to the MXU quanta
    (sublane 8 on M, lane 128 on K/N) so raw shapes that run the same
    grid share one cache entry; the kernel version rides ``extra``."""
    from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
    return {
        "op": "matmul",
        "shape": [_ceil_mult(int(m), 8), _ceil_mult(int(k), 128),
                  _ceil_mult(int(n), 128)],
        "dtype": str(dtype),
        "precision_level": int(precision_level),
        "extra": {"kernel_version": MATMUL_KERNEL_VERSION},
        "raw": {"mkn": [int(m), int(k), int(n)]},
    }


def matmul_int8_spec(m, k, n):
    """The int8 matmul consult/tune spec: shape PADDED to the int8 MXU
    quanta (sublane 32 on M, lane 128 on K/N); dtype is pinned
    ``int8`` and the precision level 0 — the int8 level has no
    sub-ladder (integer accumulation is already exact)."""
    from veles_tpu.ops.matmul_int8 import MATMUL_INT8_KERNEL_VERSION
    return {
        "op": "matmul_int8",
        "shape": [_ceil_mult(int(m), 32), _ceil_mult(int(k), 128),
                  _ceil_mult(int(n), 128)],
        "dtype": "int8",
        "precision_level": 0,
        "extra": {"kernel_version": MATMUL_INT8_KERNEL_VERSION},
        "raw": {"mkn": [int(m), int(k), int(n)]},
    }


def conv_vjp_spec(x_shape, ky, kx, cout, y_hw, dtype, precision_level,
                  padding=(0, 0, 0, 0), sliding=(1, 1),
                  activation="linear"):
    """The fused conv-VJP consult/tune spec: shape is (taps, padded P,
    padded Cin, padded Cout) — the wgrad contraction's grid coordinates."""
    from veles_tpu.ops.conv_vjp import CONV_VJP_KERNEL_VERSION
    n, _h, _w, ci = [int(s) for s in x_shape]
    oh, ow = [int(s) for s in y_hw]
    p = n * oh * ow
    return {
        "op": "conv_vjp",
        "shape": [int(ky) * int(kx), _ceil_mult(p, 8),
                  _ceil_mult(ci, 128), _ceil_mult(int(cout), 128)],
        "dtype": str(dtype),
        "precision_level": int(precision_level),
        "extra": {"kernel_version": CONV_VJP_KERNEL_VERSION},
        "raw": {"x_shape": [int(s) for s in x_shape],
                "y_hw": [oh, ow], "ky": int(ky), "kx": int(kx),
                "cout": int(cout),
                "padding": [int(p_) for p_ in padding],
                "sliding": [int(s) for s in sliding],
                "activation": str(activation)},
    }


def attention_spec(b, t, dh, dtype, precision_level):
    """The flash-attention consult/tune spec: shape is (batch-heads,
    T padded to the q sublane quantum, T padded to the k lane quantum,
    lane-padded head dim) — the kernel grid's coordinates; the raw
    (B, T, dh) rides ``raw`` for the runner."""
    from veles_tpu.ops.attention import ATTENTION_KERNEL_VERSION
    return {
        "op": "attention",
        "shape": [int(b), _ceil_mult(int(t), 8),
                  _ceil_mult(int(t), 128), _ceil_mult(int(dh), 128)],
        "dtype": str(dtype),
        "precision_level": int(precision_level),
        "extra": {"kernel_version": ATTENTION_KERNEL_VERSION},
        "raw": {"btd": [int(b), int(t), int(dh)]},
    }


def pool_bwd_spec(x_shape, out_hw, window, sliding, dtype):
    """The pool-backward consult/tune spec: raw dims ride the key (the
    kernel's W plan depends on every one of them)."""
    from veles_tpu.ops.pool_bwd import POOL_BWD_KERNEL_VERSION
    n, h, w_sp, c = [int(s) for s in x_shape]
    oh, ow = [int(s) for s in out_hw]
    ky, kx = [int(s) for s in window]
    sx, sy = [int(s) for s in sliding]
    return {
        "op": "pool_bwd",
        "shape": [n, h, w_sp, c, oh, ow, ky, kx, sy, sx],
        "dtype": str(dtype),
        "precision_level": 0,  # pooling has no precision ladder
        "extra": {"kernel_version": POOL_BWD_KERNEL_VERSION},
        "raw": {"x_shape": [n, h, w_sp, c], "window": [ky, kx],
                "sliding": [sx, sy]},
    }
