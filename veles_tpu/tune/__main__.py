"""``python -m veles_tpu.tune`` — tune the kernel schedules a model
actually uses and commit a TUNE.json receipt.

Walks the fused train step's lowering for the model's kernel specs
(tune/walk.py), tunes each through the GA (cache hits skip straight
through — a second run over the same model is ~all hits), and writes
the receipt.  A fleet tunes in parallel: start workers with
``--worker host:port`` on other machines/processes, then run the
master with ``--farm-slaves N --farm-address host:port``.

    # tune the MNIST MLP's shapes on this host
    python -m veles_tpu.tune --model mlp --out TUNE.json

    # pre-tune an AlexNet pod: 1 master + remote workers
    python -m veles_tpu.tune --model alexnet --farm-slaves 0 \
        --farm-address 0.0.0.0:8270   # master
    python -m veles_tpu.tune --worker master-host:8270  # each worker

    # CI smoke: compile-only fitness, tiny GA
    python -m veles_tpu.tune --model mlp --fitness compile \
        --generations 1 --population 4 --ops matmul --max-specs 2

    # model-guided search: rank candidates with the learned cost
    # model, compile only the top decile (falls back to --model-base
    # when training data is thin or the model fails its trust gate)
    python -m veles_tpu.tune --model mlp --fitness model

    # fleet schedule bank: fold another host's tuning into this cache
    python -m veles_tpu.tune --merge-bank /nfs/pod/schedule_bank.json
    # audit the training data, model trust and cache provenance
    python -m veles_tpu.tune --report
"""

import argparse
import json
import os
import sys
import time

__all__ = ["main"]

_MODELS = ("mlp", "convnet", "alexnet", "vgg16", "transformer")


def _model(name, hidden):
    from veles_tpu.models import zoo
    if name == "mlp":
        return zoo.mnist_mlp_layers(hidden=hidden), (784,)
    if name == "convnet":
        specs = [
            {"type": "conv_str", "n_kernels": 8, "kx": 3, "ky": 3,
             "padding": 1, "learning_rate": 0.05,
             "gradient_moment": 0.9},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "conv_tanh", "n_kernels": 8, "kx": 3, "ky": 3,
             "padding": 1, "learning_rate": 0.05,
             "gradient_moment": 0.9},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ]
        return specs, (16, 16, 3)
    if name == "alexnet":
        return zoo.alexnet_layers(), (227, 227, 3)
    if name == "vgg16":
        return zoo.vgg_layers(), (224, 224, 3)
    if name == "transformer":
        # the sequence workload: its fused step records attention
        # consults (and the head/MLP matmuls) at trace time
        return zoo.transformer_layers(blocks=2, heads=8,
                                      hidden=2048), (128, 512)
    raise SystemExit("unknown --model %r (have %s)" %
                     (name, ", ".join(_MODELS)))


def _parser():
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.tune",
        description="Genetics-driven Pallas schedule autotuner")
    parser.add_argument("--model", default="mlp",
                        help="zoo model to walk (%s)" %
                        "|".join(_MODELS))
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--hidden", type=int, default=100,
                        help="mlp hidden width")
    parser.add_argument("--generations", type=int, default=4)
    parser.add_argument("--population", type=int, default=8)
    parser.add_argument("--fitness",
                        choices=("measure", "compile", "model"),
                        default="measure",
                        help="measure = interleaved timing; compile = "
                        "compile-only (CI smoke); model = cost-model "
                        "ranked, only the top decile compiles")
    parser.add_argument("--model-base", choices=("measure", "compile"),
                        default="measure",
                        help="measurement mode for --fitness model's "
                        "top slice (and its fallback)")
    parser.add_argument("--repeats", type=int, default=8,
                        help="chain length per timing slope")
    parser.add_argument("--rounds", type=int, default=3,
                        help="interleaved passes per generation")
    parser.add_argument("--workers", type=int, default=0,
                        help="process-pool evaluators")
    parser.add_argument("--farm-slaves", type=int, default=0,
                        help="local control-plane farm workers")
    parser.add_argument("--farm-address", default="127.0.0.1:0")
    parser.add_argument("--worker", metavar="HOST:PORT",
                        help="run as a remote farm worker for a "
                        "tuning master at HOST:PORT (blocks)")
    # choices derive from the family registry so a new kernel family
    # (matmul_int8, attention, ...) is reachable the day it lands
    from veles_tpu.tune.spec import FAMILIES
    parser.add_argument("--ops", action="append",
                        choices=tuple(sorted(FAMILIES)),
                        help="restrict to these kernel families")
    parser.add_argument("--max-specs", type=int, default=0,
                        help="tune at most N specs (0 = all)")
    parser.add_argument("--precision-level", type=int, default=None)
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--loss", default="softmax")
    parser.add_argument("--cache", default=None,
                        help="schedule cache DIR (default: beside the "
                        "XLA compile cache; $VELES_SCHEDULE_CACHE)")
    parser.add_argument("--out", default="TUNE.json",
                        help="receipt path")
    parser.add_argument("--force", action="store_true",
                        help="retune even on cache hits")
    parser.add_argument("--seed", type=int, default=13)
    parser.add_argument("--merge-bank", metavar="PATH",
                        help="merge a fleet schedule bank into the "
                        "local cache and exit (no tuning)")
    parser.add_argument("--export-bank", metavar="PATH",
                        help="export the local cache as a fleet bank "
                        "and exit (no tuning)")
    parser.add_argument("--report", action="store_true",
                        help="print cost-model validation, per-family "
                        "triple counts and bank provenance; exit")
    return parser


def _merge_bank(path):
    from veles_tpu.tune import cache as tune_cache
    cache = tune_cache.cache_for()
    counts = cache.merge_bank(path)
    print("bank merge: %d adopted, %d kept (local wins), %d stale "
          "digests rejected, %d invalid of %d (cache now %d entries)"
          % (counts["adopted"], counts["kept"], counts["stale"],
             counts["invalid"], counts["total"], len(cache)),
          flush=True)
    return 0


def _export_bank(path):
    from veles_tpu.tune import cache as tune_cache
    cache = tune_cache.cache_for()
    count = cache.export_bank(path)
    print("bank export: %d entries -> %s" % (count, path), flush=True)
    return 0


def _report(mode):
    """The operator audit: what would the cost model train on, how
    much does it trust itself, and who contributed the cache."""
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune import costmodel
    from veles_tpu.tune.spec import FAMILIES
    log = tune_cache.measurement_log()
    print("measurement sidecar: %s" % log.path)
    counts = log.count_by_family(mode=mode)
    stale = len(log.rows(mode=mode, current_only=False)) \
        - sum(counts.values())
    print("  %d current triple(s) (mode=%s), %d stale/foreign"
          % (sum(counts.values()), mode, stale))
    for op in sorted(FAMILIES):
        n = counts.get(op, 0)
        if not n:
            print("  %-12s %5d triples (thin: no model)" % (op, n))
            continue
        model, info = costmodel.train_for(op, mode=mode)
        if info["fallback"] == "thin-data":
            print("  %-12s %5d triples (thin: < %d, no model)"
                  % (op, n, info["min_triples"]))
        elif info["error"] is None:
            print("  %-12s %5d triples (unvalidatable: no spec group "
                  "with %d+ schedules; untrusted)" % (op, n, 3))
        else:
            print("  %-12s %5d triples  val error %.3f (spearman "
                  "%.3f over %d held-out specs) -> %s"
                  % (op, n, info["error"], info["spearman"],
                     info["groups"],
                     "TRUSTED" if info["trusted"] else "untrusted"))
    cache = tune_cache.cache_for()
    entries = cache.entries()
    print("schedule cache: %s (%d entries)" % (cache.path,
                                               len(entries)))
    for digest in sorted(entries):
        entry = entries[digest]
        print("  %s  %-9s %-22s %-8s host=%s fitness=%s"
              % (digest[:12], entry.get("op"),
                 tuple(entry.get("shape", ())), entry.get("source"),
                 entry.get("host", "local"), entry.get("fitness")))
    print("tune counters: %s" % tune_cache.tune_counters(),
          flush=True)
    return 0


def main(argv=None):
    args = _parser().parse_args(argv)

    if args.cache:
        os.environ["VELES_SCHEDULE_CACHE"] = args.cache
    if args.merge_bank:
        return _merge_bank(args.merge_bank)
    if args.export_bank:
        return _export_bank(args.export_bank)
    if args.report:
        return _report(args.model_base if args.fitness == "model"
                       else args.fitness)

    if args.worker:
        from veles_tpu.jobfarm import JobFarm
        from veles_tpu.tune.autotune import evaluate_candidate
        return JobFarm("genetics").worker(args.worker,
                                          evaluate_candidate)

    import jax

    from veles_tpu.models.zoo import build_plans_and_state
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.walk import collect_specs

    if args.precision_level is None:
        from veles_tpu.config import root
        args.precision_level = int(root.common.engine.get(
            "precision_level", 0))

    start = time.monotonic()
    layer_specs, input_shape = _model(args.model, args.hidden)
    plans, state, _ = build_plans_and_state(layer_specs, input_shape,
                                            seed=args.seed)
    specs = collect_specs(plans, state, args.batch, input_shape,
                          loss=args.loss, dtype=args.dtype,
                          precision_level=args.precision_level,
                          ops=args.ops)
    if args.max_specs:
        specs = specs[:args.max_specs]
    print("tune: %s walked %d kernel spec(s) from the fused step's "
          "lowering" % (args.model, len(specs)), flush=True)

    cache = tune_cache.cache_for()
    rows, counts, evals = [], {}, 0
    for spec in specs:
        tuner = ScheduleTuner(
            spec, cache=cache, generations=args.generations,
            population=args.population, workers=args.workers,
            farm_slaves=args.farm_slaves,
            farm_address=args.farm_address, fitness=args.fitness,
            repeats=args.repeats, rounds=args.rounds,
            model_base=args.model_base,
            rng=RandomGenerator("tune", seed=args.seed))
        row = tuner.tune(force=args.force)
        rows.append(row)
        counts[row["source"]] = counts.get(row["source"], 0) + 1
        evals += row["evals"]
        print("  %-9s %-24s %s  (%s, %d evals)" % (
            row["op"], tuple(row["shape"]),
            row.get("schedule"), row["source"], row["evals"]),
            flush=True)

    receipt = {
        "schema": 1,
        "model": args.model,
        "batch": args.batch,
        "dtype": args.dtype,
        "precision_level": args.precision_level,
        "loss": args.loss,
        "device_kind": tune_cache.device_kind(),
        "jax": jax.__version__,
        "fitness": args.fitness,
        "generations": args.generations,
        "population": args.population,
        "cache_path": cache.path,
        "specs": rows,
        "counts": counts,
        "evals": evals,
        "tune_counters": tune_cache.tune_counters(),
        "wall_s": round(time.monotonic() - start, 2),
    }
    with open(args.out, "w") as fout:
        json.dump(receipt, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print("tune: %s -> %s (%s; %d evals, %.1fs)" % (
        args.model, args.out,
        ", ".join("%d %s" % (n, src)
                  for src, n in sorted(counts.items())),
        evals, receipt["wall_s"]), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
