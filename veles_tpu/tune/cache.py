"""Digest-keyed persistent schedule cache (docs/kernels.md,
"Autotuning").

The tuner's winners live beside the XLA compile cache: one JSON table
under ``<cache-dir>/schedule_cache/`` (``VELES_SCHEDULE_CACHE``
overrides the directory) mapping a sha256 digest of

    (op name, padded shape tuple, dtype, precision level,
     device kind, jax version, kernel version)

to the winning schedule — tile/grid parameters ONLY, never anything
that changes math (the precision level is part of the KEY: a schedule
tuned at level 0 can never serve a level-1 call).  The kernel version
rides the digest so optima measured on an old algorithm are a MISS for
a new one, exactly like ``MATMUL_KERNEL_VERSION`` gated the old
DeviceInfo table.

``schedule_for`` is the kernels' consult hook (``ops/matmul.py``,
``ops/conv_vjp.py``, ``ops/pool_bwd.py``): an in-memory table lookup
after one lazy disk load, counted as ``tune.cache_hits`` /
``tune.cache_misses``.  A corrupt or stale entry is a logged WARNING
and a miss — the static ``_DEFAULT_BLOCKS`` tables stay the fallback,
a bad cache can never crash a kernel call.  Under a
:func:`record_specs` context every consult also records its full spec,
which is how ``tune/walk.py`` harvests the shapes a fused step's
lowering actually uses.
"""

import functools
import hashlib
import json
import logging
import os
import threading

__all__ = ["ScheduleCache", "schedule_key", "schedule_for",
           "provenance", "cache_for", "default_cache_dir",
           "record_specs", "tune_counters", "SCHEDULE_CACHE_SCHEMA",
           "MeasurementLog", "measurement_log", "record_measurement",
           "load_bank", "BANK_FILE_NAME"]

logger = logging.getLogger("veles_tpu.tune")

#: bump when the cache FILE layout changes (entry payloads carry their
#: own per-kernel versions inside the digest)
SCHEDULE_CACHE_SCHEMA = 1

_FILE_NAME = "schedules.json"

#: the measured-triple sidecar beside ``schedules.json`` — the cost
#: model's training data (docs/kernels.md, "Autotuning")
_MEASUREMENTS_NAME = "measurements.jsonl"

#: rewrite threshold: when the sidecar exceeds this byte size an append
#: compacts it to the newest ``_MEASUREMENTS_KEEP`` rows (append-only
#: in the common case, bounded in the limit)
_MEASUREMENTS_MAX_BYTES = 8 * 2 ** 20
_MEASUREMENTS_KEEP = 10000

#: the portable fleet-bank file name used by the publish channel
BANK_FILE_NAME = "schedule_bank.json"


def default_cache_dir():
    """``$VELES_SCHEDULE_CACHE`` or ``<root cache dir>/schedule_cache``
    — resolved per call so tests can redirect via the environment."""
    env = os.environ.get("VELES_SCHEDULE_CACHE", "")
    if env:
        return env
    from veles_tpu.config import root
    return os.path.join(root.common.dirs.get("cache", "/tmp"),
                        "schedule_cache")


@functools.lru_cache(maxsize=4096)
def _digest(payload_json):
    return hashlib.sha256(payload_json.encode("utf-8")).hexdigest()


def schedule_key(op, shape, dtype, precision_level, device_kind,
                 extra=None):
    """(digest, payload) for one schedule-cache entry.

    ``shape`` is the PADDED shape tuple (MXU sublane/lane multiples):
    two raw shapes that pad identically run the identical kernel grid,
    so they share one entry.  ``extra`` carries per-family versioning
    (e.g. the kernel algorithm version)."""
    payload = {
        "op": str(op),
        "shape": [int(s) for s in shape],
        "dtype": str(dtype),
        "precision_level": int(precision_level),
        "device_kind": str(device_kind),
        "jax": _jax_version(),
    }
    if extra:
        payload.update({str(k): extra[k] for k in sorted(extra)})
    return _digest(json.dumps(payload, sort_keys=True)), payload


@functools.lru_cache(maxsize=1)
def _jax_version():
    import jax
    return jax.__version__


@functools.lru_cache(maxsize=1)
def _device_kind_cached():
    import jax
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def device_kind():
    """The default device's kind string — the cache-key coordinate that
    keeps a v5e's tiles from serving a v4 (or a CPU test host)."""
    return _device_kind_cached()


class ScheduleCache(object):
    """One on-disk schedule table: lazy load, atomic save, tolerant of
    corruption (a broken file logs a warning and reads as empty — it
    is a CACHE; the static tables are the source of truth)."""

    def __init__(self, path=None):
        self.path = path or os.path.join(default_cache_dir(),
                                         _FILE_NAME)
        self._lock = threading.Lock()
        self._entries = None
        self._warned = set()

    # -- load/save -----------------------------------------------------------

    def _read_disk(self):
        """The on-disk table, or {} (with ONE warning when corrupt)."""
        try:
            with open(self.path) as fin:
                data = json.load(fin)
            if (not isinstance(data, dict)
                    or data.get("schema") != SCHEDULE_CACHE_SCHEMA
                    or not isinstance(data.get("entries"), dict)):
                raise ValueError("unrecognized schedule cache layout")
            return data["entries"]
        except FileNotFoundError:
            return {}
        except (OSError, ValueError) as exc:
            self._warn_once(
                "corrupt", "schedule cache %s unreadable (%s); "
                "falling back to static tables" % (self.path, exc))
            return {}

    def _load(self):
        if self._entries is None:
            self._entries = self._read_disk()
        return self._entries

    def _save(self):
        data = {"schema": SCHEDULE_CACHE_SCHEMA,
                "entries": self._entries or {}}
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(data, fout, indent=1, sort_keys=True)
            fout.flush()
        os.replace(tmp, self.path)

    def _warn_once(self, key, message):
        if key not in self._warned:
            self._warned.add(key)
            logger.warning(message)

    # -- table API -----------------------------------------------------------

    def get(self, digest):
        """The full entry dict for ``digest`` or None.  A structurally
        invalid entry (no ``schedule`` dict) warns and misses."""
        with self._lock:
            entry = self._load().get(digest)
        if entry is None:
            return None
        if (not isinstance(entry, dict)
                or not isinstance(entry.get("schedule"), dict)):
            self._warn_once(
                digest, "schedule cache entry %s malformed; ignoring "
                "(static tables serve this shape)" % digest[:12])
            return None
        return entry

    def put(self, digest, payload, schedule, fitness=None,
            source="ga", evals=None):
        """Persist one winner.  ``schedule`` is the family's
        tile/grid dict; ``fitness`` the GA's (negative seconds)."""
        entry = dict(payload)
        entry["schedule"] = dict(schedule)
        entry["source"] = source
        if fitness is not None:
            entry["fitness"] = float(fitness)
        if evals is not None:
            entry["evals"] = int(evals)
        with self._lock:
            # re-read the file before the read-modify-write: another
            # process (a fleet pre-tune, a concurrent sweep) may have
            # added OR re-tuned entries since our lazy load — the
            # fresher disk state wins for every digest except the one
            # we are writing right now (a stale in-memory snapshot
            # must neither wipe nor revert them)
            merged = self._read_disk()
            merged[digest] = entry
            self._entries = merged
            self._save()
        return entry

    def entries(self):
        with self._lock:
            return dict(self._load())

    def __len__(self):
        with self._lock:
            return len(self._load())

    # -- fleet bank ----------------------------------------------------------

    def export_bank(self, path):
        """Write the whole table as one portable bank file (atomic
        write): entries verbatim plus per-entry ``host`` provenance so
        a merged fleet bank can still say which host tuned what.
        Returns the entry count."""
        import socket
        host = socket.gethostname()
        with self._lock:
            entries = self._read_disk()
            self._entries = entries
        exported = {}
        for digest, entry in sorted(entries.items()):
            entry = dict(entry)
            entry.setdefault("host", host)
            exported[digest] = entry
        bank = {"schema": SCHEDULE_CACHE_SCHEMA,
                "kind": "schedule_bank", "host": host,
                "jax": _jax_version(), "entries": exported}
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(bank, fout, indent=1, sort_keys=True)
            fout.flush()
        os.replace(tmp, path)
        return len(exported)

    def merge_bank(self, bank):
        """Merge a fleet bank (dict or path) into the table under the
        same re-read-before-write discipline as :meth:`put`.

        Per-digest policy is **disk wins except newer fitness**: a bank
        entry is adopted only when the digest is absent locally or the
        bank's measured fitness is strictly better (fitness = negative
        seconds, so higher wins).  Entries whose digest does not match
        a recompute over their own key coordinates are STALE (tampered,
        or written by a different schedule_key discipline) and
        rejected; structurally invalid schedules are rejected the same
        way the kernels' consult would reject them.  Returns the count
        dict ``{"adopted", "kept", "stale", "invalid", "total"}``."""
        from veles_tpu.tune.spec import valid_schedule
        if not isinstance(bank, dict):
            bank = load_bank(bank)
        entries = bank.get("entries") or {}
        counts = {"adopted": 0, "kept": 0, "stale": 0, "invalid": 0,
                  "total": len(entries)}
        adoptable = {}
        for digest, entry in entries.items():
            if not isinstance(entry, dict):
                counts["invalid"] += 1
                continue
            payload = {k: v for k, v in entry.items()
                       if k not in _NON_KEY_FIELDS}
            if _digest(json.dumps(payload, sort_keys=True)) != digest:
                counts["stale"] += 1
                continue
            if valid_schedule(entry.get("op"),
                              entry.get("schedule")) is None:
                counts["invalid"] += 1
                continue
            adoptable[digest] = entry
        with self._lock:
            merged = self._read_disk()
            for digest, entry in adoptable.items():
                local = merged.get(digest)
                if local is not None and not _fitter(entry, local):
                    counts["kept"] += 1
                    continue
                merged[digest] = dict(entry)
                counts["adopted"] += 1
            self._entries = merged
            if counts["adopted"]:
                self._save()
        reg = _counters()
        reg.counter("tune.bank_merged").inc()
        if counts["adopted"]:
            reg.counter("tune.bank_entries").inc(counts["adopted"])
        return counts


#: entry fields that ride ALONGSIDE the key payload (everything else
#: in an entry is a schedule_key coordinate, so a digest recompute over
#: the remainder must reproduce the entry's own digest)
_NON_KEY_FIELDS = frozenset(
    ("schedule", "source", "fitness", "evals", "host"))


def _fitter(challenger, incumbent):
    """True when the challenger's measured fitness strictly beats the
    incumbent's (an unmeasured challenger never displaces anything; an
    unmeasured incumbent yields to any measured challenger)."""
    cf = challenger.get("fitness")
    if cf is None:
        return False
    inf = incumbent.get("fitness")
    return inf is None or float(cf) > float(inf)


def load_bank(path):
    """Read + structurally verify one bank file; raises ValueError on
    anything that is not a schedule bank of the current schema."""
    with open(path) as fin:
        bank = json.load(fin)
    if (not isinstance(bank, dict)
            or bank.get("kind") != "schedule_bank"
            or bank.get("schema") != SCHEDULE_CACHE_SCHEMA
            or not isinstance(bank.get("entries"), dict)):
        raise ValueError("%s is not a schedule bank (schema %s)"
                         % (path, SCHEDULE_CACHE_SCHEMA))
    return bank


class MeasurementLog(object):
    """The ``measurements.jsonl`` sidecar: every measured
    (spec, schedule, slope) triple the tuner ever ranks, one JSON row
    per line — the cost model's training set.

    Append-only in the common case; an append that finds the file past
    ``_MEASUREMENTS_MAX_BYTES`` compacts it to the newest
    ``_MEASUREMENTS_KEEP`` rows (atomic replace).  Rows carry the full
    digest payload, so loads can filter to the CURRENT jax version /
    device kind / kernel version — a version bump strands old rows
    exactly like it strands old cache entries."""

    def __init__(self, path=None):
        self.path = path or os.path.join(default_cache_dir(),
                                         _MEASUREMENTS_NAME)
        self._lock = threading.Lock()
        self._warned = False

    def append(self, digest, payload, schedule, slope, mode="measure"):
        row = {"digest": str(digest), "payload": dict(payload),
               "schedule": dict(schedule), "slope": float(slope),
               "mode": str(mode)}
        line = json.dumps(row, sort_keys=True) + "\n"
        with self._lock:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            with open(self.path, "a") as fout:
                fout.write(line)
            try:
                oversized = (os.path.getsize(self.path)
                             > _MEASUREMENTS_MAX_BYTES)
            except OSError:
                oversized = False
            if oversized:
                self._compact()

    def _compact(self):
        with open(self.path) as fin:
            lines = fin.readlines()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fout:
            fout.writelines(lines[-_MEASUREMENTS_KEEP:])
            fout.flush()
        os.replace(tmp, self.path)

    def rows(self, op=None, mode=None, current_only=True):
        """The parsed rows, newest last.  ``current_only`` keeps only
        rows whose payload matches the CURRENT jax version and device
        kind AND whose digest recompute matches (a jax/kernel-version
        bump invalidates training data like it invalidates cache
        entries).  Unparseable lines are skipped (one warning)."""
        try:
            with open(self.path) as fin:
                lines = fin.readlines()
        except FileNotFoundError:
            return []
        except OSError as exc:
            self._warn("measurement log %s unreadable (%s)"
                       % (self.path, exc))
            return []
        jax_now = _jax_version() if current_only else None
        kind_now = device_kind() if current_only else None
        kernel_now = {}
        if current_only:
            from veles_tpu.tune.spec import current_kernel_version
        out = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
                payload = row["payload"]
                digest = row["digest"]
                float(row["slope"])
                row["schedule"], row["mode"]
            except (ValueError, KeyError, TypeError):
                self._warn("measurement log %s has unparseable rows; "
                           "skipping them" % self.path)
                continue
            if op is not None and payload.get("op") != op:
                continue
            if mode is not None and row.get("mode") != mode:
                continue
            if current_only:
                if (payload.get("jax") != jax_now
                        or payload.get("device_kind") != kind_now):
                    continue
                row_op = payload.get("op")
                if row_op not in kernel_now:
                    kernel_now[row_op] = current_kernel_version(row_op)
                if (kernel_now[row_op] is not None
                        and payload.get("kernel_version")
                        != kernel_now[row_op]):
                    continue
                recomputed = _digest(json.dumps(payload,
                                                sort_keys=True))
                if recomputed != digest:
                    continue
            out.append(row)
        return out

    def count_by_family(self, mode=None, current_only=True):
        counts = {}
        for row in self.rows(mode=mode, current_only=current_only):
            op = row["payload"].get("op", "?")
            counts[op] = counts.get(op, 0) + 1
        return counts

    def _warn(self, message):
        if not self._warned:
            self._warned = True
            logger.warning(message)


# -- process-wide consult hook ----------------------------------------------

_instances_lock = threading.Lock()
_instances = {}


def cache_for(path=None):
    """The ScheduleCache singleton for ``path`` (default: the resolved
    cache dir).  Keyed by resolved path so tests that redirect
    ``VELES_SCHEDULE_CACHE`` get a fresh table, not a stale singleton."""
    resolved = path or os.path.join(default_cache_dir(), _FILE_NAME)
    with _instances_lock:
        inst = _instances.get(resolved)
        if inst is None:
            inst = _instances[resolved] = ScheduleCache(resolved)
        return inst


_log_instances = {}


def measurement_log(path=None):
    """The MeasurementLog singleton for ``path`` — same resolved-path
    keying as :func:`cache_for`, so the conftest tmp-redirect that
    isolates ``schedules.json`` isolates the sidecar too."""
    resolved = path or os.path.join(default_cache_dir(),
                                    _MEASUREMENTS_NAME)
    with _instances_lock:
        inst = _log_instances.get(resolved)
        if inst is None:
            inst = _log_instances[resolved] = MeasurementLog(resolved)
        return inst


def record_measurement(digest, payload, schedule, slope,
                       mode="measure"):
    """Append one measured triple to the sidecar; never raises (a
    read-only cache dir must not break a tune run)."""
    try:
        measurement_log().append(digest, payload, schedule, slope,
                                 mode=mode)
    except Exception as exc:
        logger.warning("measurement log append failed (%s); triple "
                       "dropped", exc)


#: active recording sink (tune/walk.py) — a plain list; consults append
#: their spec dicts.  Guarded by the GIL like every other module flag.
_recording = None


class record_specs(object):
    """Context manager: while active, every ``schedule_for`` consult
    appends ``{"op", "shape", "dtype", "precision_level", "extra",
    "raw", "digest"}`` to the returned list (dedup by digest) — the
    walk's harvest of what a lowering actually consulted."""

    def __enter__(self):
        global _recording
        self._saved = _recording
        self._sink = []
        self._seen = set()
        _recording = self
        return self._sink

    def __exit__(self, *exc):
        global _recording
        _recording = self._saved
        return False

    def add(self, spec):
        if spec["digest"] not in self._seen:
            self._seen.add(spec["digest"])
            self._sink.append(spec)


def _counters():
    from veles_tpu.observe.metrics import registry
    return registry


def schedule_for(op, shape, dtype, precision_level, extra=None,
                 raw=None):
    """The kernels' consult: the cached ``schedule`` dict for this
    (op, padded shape, dtype, precision level, device kind) or None.

    Counts ``tune.cache_hits`` / ``tune.cache_misses``; under an
    active :class:`record_specs` context also records the spec.  Never
    raises — a broken cache is a warning plus the static fallback."""
    try:
        kind = device_kind()
        digest, payload = schedule_key(op, shape, dtype,
                                       precision_level, kind, extra)
        if _recording is not None:
            _recording.add({
                "op": str(op), "shape": [int(s) for s in shape],
                "dtype": str(dtype),
                "precision_level": int(precision_level),
                "device_kind": kind, "extra": dict(extra or {}),
                "raw": dict(raw or {}), "digest": digest})
        entry = cache_for().get(digest)
        reg = _counters()
        if entry is None:
            reg.counter("tune.cache_misses").inc()
            return None
        reg.counter("tune.cache_hits").inc()
        return entry["schedule"]
    except Exception as exc:  # never let the cache break a kernel call
        logger.warning("schedule cache consult failed (%s); using "
                       "static tables", exc)
        return None


def provenance(op, shape, dtype, precision_level, extra=None):
    """"tuned" when a cache entry would ACTUALLY serve this spec —
    same structural validation as the kernels' consult, so an entry
    the consult rejects (and serves statically) is never attributed as
    tuned — else "static".  The MFU-attribution annotation (scripts/
    mfu_breakdown.py); no counters, no recording."""
    try:
        digest, _ = schedule_key(op, shape, dtype, precision_level,
                                 device_kind(), extra)
        entry = cache_for().get(digest)
        if entry is None:
            return "static"
        from veles_tpu.tune.spec import valid_schedule
        return ("tuned" if valid_schedule(op, entry["schedule"])
                else "static")
    except Exception:
        return "static"


def tune_counters():
    """Snapshot of the tune metric set + cache population for receipts
    (the serve engine's compile receipt, the CLI's TUNE.json)."""
    reg = _counters()
    out = {}
    for name in ("tune.cache_hits", "tune.cache_misses", "tune.evals",
                 "tune.bank_published", "tune.bank_merged",
                 "tune.bank_entries"):
        metric = reg.peek(name)
        if metric is not None:
            out[name.split(".", 1)[1]] = metric.value
    try:
        out["entries"] = len(cache_for())
    except Exception:
        pass
    return out
