"""Digest-keyed persistent schedule cache (docs/kernels.md,
"Autotuning").

The tuner's winners live beside the XLA compile cache: one JSON table
under ``<cache-dir>/schedule_cache/`` (``VELES_SCHEDULE_CACHE``
overrides the directory) mapping a sha256 digest of

    (op name, padded shape tuple, dtype, precision level,
     device kind, jax version, kernel version)

to the winning schedule — tile/grid parameters ONLY, never anything
that changes math (the precision level is part of the KEY: a schedule
tuned at level 0 can never serve a level-1 call).  The kernel version
rides the digest so optima measured on an old algorithm are a MISS for
a new one, exactly like ``MATMUL_KERNEL_VERSION`` gated the old
DeviceInfo table.

``schedule_for`` is the kernels' consult hook (``ops/matmul.py``,
``ops/conv_vjp.py``, ``ops/pool_bwd.py``): an in-memory table lookup
after one lazy disk load, counted as ``tune.cache_hits`` /
``tune.cache_misses``.  A corrupt or stale entry is a logged WARNING
and a miss — the static ``_DEFAULT_BLOCKS`` tables stay the fallback,
a bad cache can never crash a kernel call.  Under a
:func:`record_specs` context every consult also records its full spec,
which is how ``tune/walk.py`` harvests the shapes a fused step's
lowering actually uses.
"""

import functools
import hashlib
import json
import logging
import os
import threading

__all__ = ["ScheduleCache", "schedule_key", "schedule_for",
           "provenance", "cache_for", "default_cache_dir",
           "record_specs", "tune_counters", "SCHEDULE_CACHE_SCHEMA"]

logger = logging.getLogger("veles_tpu.tune")

#: bump when the cache FILE layout changes (entry payloads carry their
#: own per-kernel versions inside the digest)
SCHEDULE_CACHE_SCHEMA = 1

_FILE_NAME = "schedules.json"


def default_cache_dir():
    """``$VELES_SCHEDULE_CACHE`` or ``<root cache dir>/schedule_cache``
    — resolved per call so tests can redirect via the environment."""
    env = os.environ.get("VELES_SCHEDULE_CACHE", "")
    if env:
        return env
    from veles_tpu.config import root
    return os.path.join(root.common.dirs.get("cache", "/tmp"),
                        "schedule_cache")


@functools.lru_cache(maxsize=4096)
def _digest(payload_json):
    return hashlib.sha256(payload_json.encode("utf-8")).hexdigest()


def schedule_key(op, shape, dtype, precision_level, device_kind,
                 extra=None):
    """(digest, payload) for one schedule-cache entry.

    ``shape`` is the PADDED shape tuple (MXU sublane/lane multiples):
    two raw shapes that pad identically run the identical kernel grid,
    so they share one entry.  ``extra`` carries per-family versioning
    (e.g. the kernel algorithm version)."""
    payload = {
        "op": str(op),
        "shape": [int(s) for s in shape],
        "dtype": str(dtype),
        "precision_level": int(precision_level),
        "device_kind": str(device_kind),
        "jax": _jax_version(),
    }
    if extra:
        payload.update({str(k): extra[k] for k in sorted(extra)})
    return _digest(json.dumps(payload, sort_keys=True)), payload


@functools.lru_cache(maxsize=1)
def _jax_version():
    import jax
    return jax.__version__


@functools.lru_cache(maxsize=1)
def _device_kind_cached():
    import jax
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def device_kind():
    """The default device's kind string — the cache-key coordinate that
    keeps a v5e's tiles from serving a v4 (or a CPU test host)."""
    return _device_kind_cached()


class ScheduleCache(object):
    """One on-disk schedule table: lazy load, atomic save, tolerant of
    corruption (a broken file logs a warning and reads as empty — it
    is a CACHE; the static tables are the source of truth)."""

    def __init__(self, path=None):
        self.path = path or os.path.join(default_cache_dir(),
                                         _FILE_NAME)
        self._lock = threading.Lock()
        self._entries = None
        self._warned = set()

    # -- load/save -----------------------------------------------------------

    def _read_disk(self):
        """The on-disk table, or {} (with ONE warning when corrupt)."""
        try:
            with open(self.path) as fin:
                data = json.load(fin)
            if (not isinstance(data, dict)
                    or data.get("schema") != SCHEDULE_CACHE_SCHEMA
                    or not isinstance(data.get("entries"), dict)):
                raise ValueError("unrecognized schedule cache layout")
            return data["entries"]
        except FileNotFoundError:
            return {}
        except (OSError, ValueError) as exc:
            self._warn_once(
                "corrupt", "schedule cache %s unreadable (%s); "
                "falling back to static tables" % (self.path, exc))
            return {}

    def _load(self):
        if self._entries is None:
            self._entries = self._read_disk()
        return self._entries

    def _save(self):
        data = {"schema": SCHEDULE_CACHE_SCHEMA,
                "entries": self._entries or {}}
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(data, fout, indent=1, sort_keys=True)
            fout.flush()
        os.replace(tmp, self.path)

    def _warn_once(self, key, message):
        if key not in self._warned:
            self._warned.add(key)
            logger.warning(message)

    # -- table API -----------------------------------------------------------

    def get(self, digest):
        """The full entry dict for ``digest`` or None.  A structurally
        invalid entry (no ``schedule`` dict) warns and misses."""
        with self._lock:
            entry = self._load().get(digest)
        if entry is None:
            return None
        if (not isinstance(entry, dict)
                or not isinstance(entry.get("schedule"), dict)):
            self._warn_once(
                digest, "schedule cache entry %s malformed; ignoring "
                "(static tables serve this shape)" % digest[:12])
            return None
        return entry

    def put(self, digest, payload, schedule, fitness=None,
            source="ga", evals=None):
        """Persist one winner.  ``schedule`` is the family's
        tile/grid dict; ``fitness`` the GA's (negative seconds)."""
        entry = dict(payload)
        entry["schedule"] = dict(schedule)
        entry["source"] = source
        if fitness is not None:
            entry["fitness"] = float(fitness)
        if evals is not None:
            entry["evals"] = int(evals)
        with self._lock:
            # re-read the file before the read-modify-write: another
            # process (a fleet pre-tune, a concurrent sweep) may have
            # added OR re-tuned entries since our lazy load — the
            # fresher disk state wins for every digest except the one
            # we are writing right now (a stale in-memory snapshot
            # must neither wipe nor revert them)
            merged = self._read_disk()
            merged[digest] = entry
            self._entries = merged
            self._save()
        return entry

    def entries(self):
        with self._lock:
            return dict(self._load())

    def __len__(self):
        with self._lock:
            return len(self._load())


# -- process-wide consult hook ----------------------------------------------

_instances_lock = threading.Lock()
_instances = {}


def cache_for(path=None):
    """The ScheduleCache singleton for ``path`` (default: the resolved
    cache dir).  Keyed by resolved path so tests that redirect
    ``VELES_SCHEDULE_CACHE`` get a fresh table, not a stale singleton."""
    resolved = path or os.path.join(default_cache_dir(), _FILE_NAME)
    with _instances_lock:
        inst = _instances.get(resolved)
        if inst is None:
            inst = _instances[resolved] = ScheduleCache(resolved)
        return inst


#: active recording sink (tune/walk.py) — a plain list; consults append
#: their spec dicts.  Guarded by the GIL like every other module flag.
_recording = None


class record_specs(object):
    """Context manager: while active, every ``schedule_for`` consult
    appends ``{"op", "shape", "dtype", "precision_level", "extra",
    "raw", "digest"}`` to the returned list (dedup by digest) — the
    walk's harvest of what a lowering actually consulted."""

    def __enter__(self):
        global _recording
        self._saved = _recording
        self._sink = []
        self._seen = set()
        _recording = self
        return self._sink

    def __exit__(self, *exc):
        global _recording
        _recording = self._saved
        return False

    def add(self, spec):
        if spec["digest"] not in self._seen:
            self._seen.add(spec["digest"])
            self._sink.append(spec)


def _counters():
    from veles_tpu.observe.metrics import registry
    return registry


def schedule_for(op, shape, dtype, precision_level, extra=None,
                 raw=None):
    """The kernels' consult: the cached ``schedule`` dict for this
    (op, padded shape, dtype, precision level, device kind) or None.

    Counts ``tune.cache_hits`` / ``tune.cache_misses``; under an
    active :class:`record_specs` context also records the spec.  Never
    raises — a broken cache is a warning plus the static fallback."""
    try:
        kind = device_kind()
        digest, payload = schedule_key(op, shape, dtype,
                                       precision_level, kind, extra)
        if _recording is not None:
            _recording.add({
                "op": str(op), "shape": [int(s) for s in shape],
                "dtype": str(dtype),
                "precision_level": int(precision_level),
                "device_kind": kind, "extra": dict(extra or {}),
                "raw": dict(raw or {}), "digest": digest})
        entry = cache_for().get(digest)
        reg = _counters()
        if entry is None:
            reg.counter("tune.cache_misses").inc()
            return None
        reg.counter("tune.cache_hits").inc()
        return entry["schedule"]
    except Exception as exc:  # never let the cache break a kernel call
        logger.warning("schedule cache consult failed (%s); using "
                       "static tables", exc)
        return None


def provenance(op, shape, dtype, precision_level, extra=None):
    """"tuned" when a cache entry would ACTUALLY serve this spec —
    same structural validation as the kernels' consult, so an entry
    the consult rejects (and serves statically) is never attributed as
    tuned — else "static".  The MFU-attribution annotation (scripts/
    mfu_breakdown.py); no counters, no recording."""
    try:
        digest, _ = schedule_key(op, shape, dtype, precision_level,
                                 device_kind(), extra)
        entry = cache_for().get(digest)
        if entry is None:
            return "static"
        from veles_tpu.tune.spec import valid_schedule
        return ("tuned" if valid_schedule(op, entry["schedule"])
                else "static")
    except Exception:
        return "static"


def tune_counters():
    """Snapshot of the tune metric set + cache population for receipts
    (the serve engine's compile receipt, the CLI's TUNE.json)."""
    reg = _counters()
    out = {}
    for name in ("tune.cache_hits", "tune.cache_misses", "tune.evals"):
        metric = reg.peek(name)
        if metric is not None:
            out[name.split(".", 1)[1]] = metric.value
    try:
        out["entries"] = len(cache_for())
    except Exception:
        pass
    return out
