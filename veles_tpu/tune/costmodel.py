"""The learned schedule cost model (docs/kernels.md, "Autotuning").

TVM's lesson (PAPERS.md) scaled to this repo: the GA autotuner's
fitness is compile-bound — every candidate pays a full Pallas build
before its first timing pass — so a small regressor trained on the
measurement sidecar (``tune/cache.py``, ``measurements.jsonl``) ranks
a generation's candidates FIRST and only the top slice ever compiles.

Design constraints, in order:

- **Deterministic.**  The model is gradient-boosted depth-1 stumps
  over hand-built features, fit by exhaustive scan over quantile
  thresholds in fixed feature order with first-wins tie-breaking —
  same triples in, same stumps out, same ranking out, on every host.
  No RNG anywhere.
- **Pure numpy.**  No new dependencies; the whole module imports in
  milliseconds and never touches jax, so the fast tier-1 subset
  (``pytest -m costmodel``) runs without a single compile.
- **Honest about its own error.**  ``validate()`` runs
  leave-one-spec-out: every distinct spec digest with enough rows is
  held out in turn, the model refit on the rest, and the held-out
  ranking scored by Spearman correlation against the measured slopes.
  ``train_for`` refuses to hand back a model when training data is
  thin (< ``MIN_TRIPLES`` rows for the family) or the validation
  error exceeds ``TRUST_ERROR`` — the tuner then falls back to
  measured fitness, which is always correct, just slower.

The model predicts ``log(slope seconds)``; only RANK matters to the
tuner (predicted seconds are never persisted, never published — cache
entries stay measured-only).
"""

import json

import numpy

from veles_tpu.tune import cache as _cache
from veles_tpu.tune.spec import family_for

__all__ = ["CostModel", "featurize", "train_for", "spearman",
           "MIN_TRIPLES", "TRUST_ERROR"]

#: below this many current-version triples for a family the model is
#: not trained at all (thin-data fallback to measured fitness)
MIN_TRIPLES = 32

#: trust threshold on the leave-one-spec-out validation ERROR
#: (1 - mean held-out Spearman): above it the tuner ignores the model
TRUST_ERROR = 0.5

#: minimum distinct measured schedules a held-out spec needs for its
#: ranking to be scorable
_MIN_GROUP = 3

#: leave-one-spec-out refits are O(groups * fit); cap the held-out
#: groups (largest first, digest-ordered ties) so validation stays
#: cheap on long measurement histories
_MAX_GROUPS = 8


def _log2(value):
    return float(numpy.log2(max(float(value), 1.0)))


def _ceil_div(a, b):
    return -(-int(a) // max(int(b), 1))


def _grid_flops(op, shape, genes):
    """(grid steps, flops per grid step) for one (family, padded
    shape, schedule) — the two features tile dims alone cannot
    express.  Unknown families get a tile-product proxy."""
    if op in ("matmul", "matmul_int8"):
        m, k, n = shape
        bm, bn, bk = genes["bm"], genes["bn"], genes["bk"]
        grid = _ceil_div(m, bm) * _ceil_div(n, bn) * _ceil_div(k, bk)
        return grid, 2.0 * bm * bn * bk
    if op == "conv_vjp":
        taps, p, ci, co = shape
        bi, bj, bk = genes["bi"], genes["bj"], genes["bk"]
        grid = (taps * _ceil_div(ci, bi) * _ceil_div(co, bj)
                * _ceil_div(p, bk))
        return grid, 2.0 * bi * bj * bk
    if op == "attention":
        b, tq, tk, dhp = shape
        bq, bk = genes["bq"], genes["bk"]
        grid = b * _ceil_div(tq, bq) * _ceil_div(tk, bk)
        return grid, 2.0 * bq * bk * dhp
    if op == "pool_bwd":
        ow = shape[5]
        owb = genes["owb"]
        return _ceil_div(ow, owb), float(max(owb, 1))
    tiles = 1.0
    for value in genes.values():
        tiles *= max(float(value), 1.0)
    return 1, tiles


def featurize(spec, schedule):
    """The hand-built feature vector for one (spec, schedule): log2 of
    every padded dim and tile dim, the family's VMEM footprint, grid
    size, per-step flops, arithmetic intensity (flops per VMEM byte)
    and total-traffic proxy.  Fixed length per family (models are
    per-family, so lengths never mix)."""
    op = spec["op"]
    family = family_for(op)
    shape = [int(s) for s in spec["shape"]]
    genes = family.genes_of(schedule)
    tiles = [int(genes[name]) for name in sorted(genes)]
    foot = float(family.footprint(spec, schedule))
    grid, flops = _grid_flops(op, shape, genes)
    feats = ([_log2(s) for s in shape]
             + [_log2(t) for t in tiles]
             + [_log2(foot), _log2(grid), _log2(flops),
                _log2(max(flops, 1.0) / max(foot, 1.0) + 1.0),
                _log2(foot * max(grid, 1))])
    return numpy.asarray(feats, numpy.float64)


def spearman(a, b):
    """Spearman rank correlation (average ranks for ties); 0.0 when
    either side has no rank variance."""
    ra = _ranks(numpy.asarray(a, numpy.float64))
    rb = _ranks(numpy.asarray(b, numpy.float64))
    sa, sb = ra.std(), rb.std()
    if sa == 0.0 or sb == 0.0:
        return 0.0
    return float(((ra - ra.mean()) * (rb - rb.mean())).mean()
                 / (sa * sb))


def _ranks(values):
    order = numpy.argsort(values, kind="stable")
    ranks = numpy.empty(len(values), numpy.float64)
    ranks[order] = numpy.arange(len(values), dtype=numpy.float64)
    # average ties so duplicate slopes do not fabricate an ordering
    for value in numpy.unique(values):
        mask = values == value
        if mask.sum() > 1:
            ranks[mask] = ranks[mask].mean()
    return ranks


def _spec_of(payload):
    """A featurize()-able spec dict from a measurement row's digest
    payload (the payload IS the key coordinates, flattened)."""
    return {"op": payload["op"], "shape": list(payload["shape"]),
            "dtype": payload.get("dtype", "float32"),
            "precision_level": payload.get("precision_level", 0)}


def _fit_boost(X, y, rounds, learning_rate, max_thresholds):
    """Deterministic least-squares gradient boosting with depth-1
    stumps.  Candidate thresholds are midpoints of each feature's
    unique values, quantile-subsampled to ``max_thresholds``; the best
    split per round is chosen by SSE gain with first-wins ties (lowest
    feature index, then lowest threshold index)."""
    n, d = X.shape
    base = float(y.mean())
    pred = numpy.full(n, base, numpy.float64)
    thresholds = []
    for j in range(d):
        vals = numpy.unique(X[:, j])
        if len(vals) < 2:
            thresholds.append(numpy.empty(0, numpy.float64))
            continue
        mids = (vals[1:] + vals[:-1]) / 2.0
        if len(mids) > max_thresholds:
            idx = numpy.unique(numpy.linspace(
                0, len(mids) - 1, max_thresholds).round().astype(int))
            mids = mids[idx]
        thresholds.append(mids)
    stumps = []
    for _ in range(rounds):
        resid = y - pred
        total = resid.sum()
        best = None   # (gain, j, threshold, left_mean, right_mean)
        for j in range(d):
            ts = thresholds[j]
            if not len(ts):
                continue
            left = X[None, :, j] <= ts[:, None]      # (T, n)
            nl = left.sum(axis=1)
            sl = (left * resid[None, :]).sum(axis=1)
            nr = n - nl
            sr = total - sl
            valid = (nl > 0) & (nr > 0)
            gain = numpy.where(
                valid,
                sl ** 2 / numpy.maximum(nl, 1)
                + sr ** 2 / numpy.maximum(nr, 1),
                -numpy.inf)
            ti = int(numpy.argmax(gain))
            if not numpy.isfinite(gain[ti]):
                continue
            if best is None or gain[ti] > best[0] + 1e-12:
                best = (float(gain[ti]), j, float(ts[ti]),
                        float(sl[ti] / nl[ti]),
                        float(sr[ti] / nr[ti]))
        if best is None:
            break
        _, j, t, lv, rv = best
        lv *= learning_rate
        rv *= learning_rate
        stumps.append((j, t, lv, rv))
        pred += numpy.where(X[:, j] <= t, lv, rv)
    return base, stumps


class CostModel(object):
    """One family's learned slope regressor.

    ``fit(rows)`` takes measurement-log rows (``{"digest", "payload",
    "schedule", "slope"}``); ``predict_seconds``/``predict_rank``
    score candidate schedules for one spec; ``validate()`` is the
    leave-one-spec-out audit the trust gate runs."""

    def __init__(self, op, rounds=120, learning_rate=0.1,
                 max_thresholds=32):
        self.op = op
        self.rounds = int(rounds)
        self.learning_rate = float(learning_rate)
        self.max_thresholds = int(max_thresholds)
        self.base = 0.0
        self.stumps = []
        self._rows = []

    # -- training ------------------------------------------------------------

    def _design(self, rows):
        X = numpy.stack([featurize(_spec_of(row["payload"]),
                                   row["schedule"]) for row in rows])
        y = numpy.log(numpy.maximum(
            numpy.asarray([row["slope"] for row in rows],
                          numpy.float64), 1e-12))
        return X, y

    def fit(self, rows):
        rows = list(rows)
        if not rows:
            raise ValueError("cost model needs at least one triple")
        self._rows = rows
        X, y = self._design(rows)
        self.base, self.stumps = _fit_boost(
            X, y, self.rounds, self.learning_rate,
            self.max_thresholds)
        return self

    # -- prediction ----------------------------------------------------------

    def _predict_matrix(self, X):
        pred = numpy.full(X.shape[0], self.base, numpy.float64)
        for j, t, lv, rv in self.stumps:
            pred += numpy.where(X[:, j] <= t, lv, rv)
        return pred

    def predict_seconds(self, spec, schedules):
        """Predicted slope seconds per candidate schedule (rank is
        what matters; the absolute scale is only as good as the
        training slopes)."""
        X = numpy.stack([featurize(spec, s) for s in schedules])
        return numpy.exp(self._predict_matrix(X))

    def predict_rank(self, spec, schedules):
        """Candidate indices, predicted-fastest first; ties break on
        the lower index (deterministic)."""
        pred = self.predict_seconds(spec, schedules)
        return sorted(range(len(schedules)),
                      key=lambda i: (float(pred[i]), i))

    # -- validation ----------------------------------------------------------

    def validate(self):
        """Leave-one-spec-out: ``{"error", "spearman", "groups"}``
        where error = 1 - mean held-out Spearman over the scorable
        spec groups (None when NO group is scorable — an unvalidatable
        model must read as untrusted, not as perfect)."""
        groups = {}
        for i, row in enumerate(self._rows):
            groups.setdefault(row["digest"], []).append(i)
        scorable = []
        for digest in sorted(groups):
            indices = groups[digest]
            distinct = {json.dumps(self._rows[i]["schedule"],
                                   sort_keys=True) for i in indices}
            if (len(distinct) >= _MIN_GROUP
                    and len(self._rows) - len(indices) >= _MIN_GROUP):
                scorable.append((len(indices), digest))
        scorable = [digest for _, digest in
                    sorted(scorable, key=lambda g: (-g[0], g[1]))]
        scorable = scorable[:_MAX_GROUPS]
        rhos = []
        for digest in scorable:
            held = set(groups[digest])
            train = [row for i, row in enumerate(self._rows)
                     if i not in held]
            probe = CostModel(self.op, self.rounds,
                              self.learning_rate,
                              self.max_thresholds).fit(train)
            # collapse duplicate schedules to their median slope so a
            # re-measured schedule does not flood the rank with ties
            by_schedule = {}
            for i in held:
                row = self._rows[i]
                key = json.dumps(row["schedule"], sort_keys=True)
                by_schedule.setdefault(
                    key, (row["schedule"], []))[1].append(row["slope"])
            schedules = [by_schedule[k][0]
                         for k in sorted(by_schedule)]
            actual = [float(numpy.median(by_schedule[k][1]))
                      for k in sorted(by_schedule)]
            spec = _spec_of(self._rows[next(iter(held))]["payload"])
            pred = probe.predict_seconds(spec, schedules)
            rhos.append(spearman(pred, actual))
        if not rhos:
            return {"error": None, "spearman": None, "groups": 0}
        rho = float(numpy.mean(rhos))
        return {"error": 1.0 - rho, "spearman": rho,
                "groups": len(rhos)}


def train_for(op, mode="measure", log=None, min_triples=MIN_TRIPLES,
              trust_error=TRUST_ERROR):
    """(model, info): the trained-and-trusted CostModel for one
    family, or (None, info) with ``info["fallback"]`` naming why the
    tuner must use measured fitness (``"thin-data"`` below
    ``min_triples`` rows, ``"untrusted"`` above the validation-error
    threshold or unvalidatable)."""
    log = log or _cache.measurement_log()
    rows = log.rows(op=op, mode=mode)
    info = {"family": op, "mode": mode, "triples": len(rows),
            "min_triples": int(min_triples),
            "trust_error": float(trust_error),
            "error": None, "spearman": None, "groups": 0,
            "trusted": False, "fallback": None}
    if len(rows) < min_triples:
        info["fallback"] = "thin-data"
        return None, info
    model = CostModel(op).fit(rows)
    val = model.validate()
    info.update(error=val["error"], spearman=val["spearman"],
                groups=val["groups"])
    if val["error"] is None or val["error"] > trust_error:
        info["fallback"] = "untrusted"
        return None, info
    info["trusted"] = True
    return model, info
