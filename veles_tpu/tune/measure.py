"""The ONE timing-measurement discipline for schedule ranking.

Every published kernel-schedule ranking — the GA autotuner's fitness,
``ops/matmul.py``'s curated candidate sweep, bench.py's A/B medians —
runs through these helpers, so the jitter policy can never drift
between the tuner and the benchmarks:

- **Pass filtering** (``filter_passes``): a non-positive chain slope
  means tunnel/host jitter exceeded the whole chain delta for that
  pass — it measured the weather, not the program.  Such passes are
  DISCARDED, never clamped (a floor-clamped negative slope once
  published an impossible rate and crowned the wrong autotune tile).
- **Positive majority** (``rank``): a candidate's median runs over ALL
  its samples and must be positive with a positive MAJORITY.
  Filtering negatives first would let a jitter-swamped candidate win
  on its two tiny surviving samples.
- **Interleaving** (``interleaved_slopes``): whole-chip congestion
  drifts minute to minute (~1.4x swings measured), so timing each
  candidate's samples back to back lets a congestion window crown the
  wrong schedule.  One sample of EVERY candidate per round spreads the
  drift across all candidates equally; the median over rounds then
  ranks honestly — the same hazard ``ops/matmul.py`` documents.
"""

import time

__all__ = ["filter_passes", "chain_seconds", "slope_sample",
           "interleaved_slopes", "rank", "positive_majority_median"]


def filter_passes(samples):
    """Drop jitter-dominated timing passes: a non-positive slope means
    tunnel/host jitter exceeded the whole chain delta for that pass —
    it measures the weather, not the program (the negative-slope pass
    that contaminated MFU.json's published 48.8% capture is the
    motivating case; same discard-never-clamp policy as the matmul
    autotuner).  Returns the retained passes; when EVERY pass is
    jitter-dominated the raw list comes back unchanged so the caller's
    plausibility floor (not this filter) rejects the measurement."""
    used = [s for s in samples if s > 0]
    return used if used else list(samples)


def positive_majority_median(samples):
    """Median over ALL samples, published only when a positive
    MAJORITY of passes survived and the median itself is positive;
    ``None`` otherwise (the candidate measured only weather)."""
    import numpy
    positive = sum(1 for s in samples if s > 0)
    if not samples or positive < len(samples) // 2 + 1:
        return None
    med = float(numpy.median(samples))
    return med if med > 0 else None


def chain_seconds(run, n):
    """Wall seconds for ``run(n)`` — run ``n`` dependent/queued kernel
    executions ended by a completion fetch.  ``run`` owns the blocking
    discipline (a scalar fetch or block_until_ready)."""
    start = time.perf_counter()
    run(n)
    return time.perf_counter() - start


def slope_sample(run, n1, n2):
    """One (t(n2) - t(n1)) / (n2 - n1) slope sample: dispatch/tunnel
    latency cancels, pure per-execution device time remains.  May be
    zero or negative when jitter swamps the chain delta — callers
    filter (``filter_passes``), never clamp."""
    t1 = chain_seconds(run, n1)
    t2 = chain_seconds(run, n2)
    return (t2 - t1) / (n2 - n1)


def interleaved_slopes(runners, n1, n2, rounds=5):
    """Round-robin slope samples: one sample of EVERY candidate per
    round, ``rounds`` rounds.  ``runners`` maps candidate key ->
    ``run(n)`` callable (already compiled/warmed — a cold compile
    inside a timed chain would be charged as device time).  A runner
    that raises mid-round just misses that round's sample."""
    samples = {key: [] for key in runners}
    for _ in range(rounds):
        for key, run in runners.items():
            try:
                samples[key].append(slope_sample(run, n1, n2))
            except Exception:
                continue
    return samples


def rank(samples_by_key):
    """{key: median seconds or None} under the positive-majority
    discipline; keys whose every sample was jitter come back None and
    must never be crowned."""
    return {key: positive_majority_median(samples)
            for key, samples in samples_by_key.items()}
