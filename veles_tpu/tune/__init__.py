"""veles_tpu.tune — genetics-driven Pallas schedule autotuner.

The pieces (docs/kernels.md, "Autotuning"):

- ``tune.cache`` — the digest-keyed on-disk :class:`ScheduleCache` the
  kernels consult (beside the XLA compile cache), plus the
  ``record_specs`` walk hook and the ``tune.*`` counters;
- ``tune.spec`` — per-kernel-family search spaces (Tune markers),
  MXU-legal quantization, VMEM feasibility, the shared cache-key spec
  builders;
- ``tune.measure`` — the ONE timing discipline (pass filtering,
  positive-majority ranking, interleaved round-robin sampling) shared
  with bench.py and ``autotune_matmul``;
- ``tune.costmodel`` — the deterministic learned cost model (boosted
  stumps over hand-built features, pure numpy) trained on the
  ``measurements.jsonl`` sidecar, with its leave-one-spec-out trust
  gate;
- ``tune.autotune`` — the GA driver (:class:`ScheduleTuner`, incl.
  the model-ranked ``fitness="model"`` mode) and the plain curated
  sweep (:func:`sweep_candidates`);
- ``tune.walk`` — spec harvesting from a fused step's lowering;
- ``python -m veles_tpu.tune`` — tune the shapes a zoo model actually
  uses and commit a ``TUNE.json`` receipt; ``--merge-bank`` folds a
  fleet schedule bank into the local cache, ``--report`` audits the
  training data/bank provenance.
"""

from veles_tpu.tune.cache import (  # noqa: F401
    MeasurementLog, ScheduleCache, cache_for, default_cache_dir,
    load_bank, measurement_log, provenance, record_specs,
    schedule_for, schedule_key, tune_counters)
from veles_tpu.tune.measure import filter_passes  # noqa: F401
from veles_tpu.tune.spec import (  # noqa: F401
    FAMILIES, conv_vjp_spec, family_for, matmul_int8_spec,
    matmul_spec, pool_bwd_spec, valid_schedule)

__all__ = ["ScheduleCache", "MeasurementLog", "cache_for",
           "measurement_log", "load_bank", "default_cache_dir",
           "provenance", "record_specs", "schedule_for",
           "schedule_key", "tune_counters", "filter_passes",
           "FAMILIES", "family_for", "matmul_spec",
           "matmul_int8_spec", "conv_vjp_spec", "pool_bwd_spec",
           "valid_schedule"]
