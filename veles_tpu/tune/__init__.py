"""veles_tpu.tune — genetics-driven Pallas schedule autotuner.

The pieces (docs/kernels.md, "Autotuning"):

- ``tune.cache`` — the digest-keyed on-disk :class:`ScheduleCache` the
  kernels consult (beside the XLA compile cache), plus the
  ``record_specs`` walk hook and the ``tune.*`` counters;
- ``tune.spec`` — per-kernel-family search spaces (Tune markers),
  MXU-legal quantization, VMEM feasibility, the shared cache-key spec
  builders;
- ``tune.measure`` — the ONE timing discipline (pass filtering,
  positive-majority ranking, interleaved round-robin sampling) shared
  with bench.py and ``autotune_matmul``;
- ``tune.autotune`` — the GA driver (:class:`ScheduleTuner`) and the
  plain curated sweep (:func:`sweep_candidates`);
- ``tune.walk`` — spec harvesting from a fused step's lowering;
- ``python -m veles_tpu.tune`` — tune the shapes a zoo model actually
  uses and commit a ``TUNE.json`` receipt.
"""

from veles_tpu.tune.cache import (  # noqa: F401
    ScheduleCache, cache_for, default_cache_dir, provenance,
    record_specs, schedule_for, schedule_key, tune_counters)
from veles_tpu.tune.measure import filter_passes  # noqa: F401
from veles_tpu.tune.spec import (  # noqa: F401
    FAMILIES, conv_vjp_spec, family_for, matmul_int8_spec,
    matmul_spec, pool_bwd_spec, valid_schedule)

__all__ = ["ScheduleCache", "cache_for", "default_cache_dir",
           "provenance", "record_specs", "schedule_for",
           "schedule_key", "tune_counters", "filter_passes",
           "FAMILIES", "family_for", "matmul_spec",
           "matmul_int8_spec", "conv_vjp_spec", "pool_bwd_spec",
           "valid_schedule"]
