"""Harvest the kernel specs a model's fused train step actually uses.

Two complementary sources, both derived from ONE ``.lower()`` of the
fused step (no execution, no device time):

1. **Consult recording** — lowering traces every layer's backward, so
   the Pallas kernel families' schedule-cache consults
   (``ops/conv_vjp.py``, ``ops/pool_bwd.py``, ``ops/matmul.py``) fire
   with the step's real traced shapes.  A :func:`~veles_tpu.tune.
   cache.record_specs` context captures them verbatim — the exact
   (op, padded shape, dtype, precision) coordinates the kernels will
   later look up.  The hand-scheduled backward knob is forced ON for
   the walk (lowering only — nothing runs), so conv/pool specs are
   collected even on a CPU host pre-tuning for a TPU pod.
2. **dot_general harvest** — the model layers' dense matmuls lower to
   ``stablehlo.dot_general`` (XLA's own kernels, not ops/matmul), but
   serving/BLAS paths route the same shapes through the Pallas matmul;
   parsing the lowering's 2-D dots yields those (M, K, N) specs so a
   tune run covers them too.
"""

import re

__all__ = ["collect_specs", "dot_specs_from_text"]

_TENSOR = r"tensor<(\d+)x(\d+)x(f32|bf16|f16)>"
_DOT_RE = re.compile(
    r"dot_general\s[^\n]*?\(%s,\s*%s\)\s*->\s*%s" %
    (_TENSOR, _TENSOR, _TENSOR))


def _mkn(a0, a1, b0, b1, o0, o1):
    """(M, K, N) for a 2-D dot with operand/result dims, tolerant of
    transposed contractions (the backward's dT/xT dots); None when the
    dims don't tell a consistent GEMM story."""
    if a0 == o0 and b1 == o1 and a1 == b0:
        return o0, a1, o1          # (M,K) @ (K,N)
    if a1 == o0 and b1 == o1 and a0 == b0:
        return o0, a0, o1          # (K,M)^T @ (K,N)
    if a0 == o0 and b0 == o1 and a1 == b1:
        return o0, a1, o1          # (M,K) @ (N,K)^T
    if a1 == o0 and b0 == o1 and a0 == b1:
        return o0, a0, o1          # (K,M)^T @ (N,K)^T
    return None


def dot_specs_from_text(text, precision_level=0):
    """matmul tune specs for every distinct 2-D ``dot_general`` in a
    lowering's StableHLO text."""
    from veles_tpu.tune.spec import matmul_spec
    dtypes = {"f32": "float32", "bf16": "bfloat16", "f16": "float16"}
    specs, seen = [], set()
    for match in _DOT_RE.finditer(text):
        a0, a1, dt_a, b0, b1, dt_b, o0, o1, dt_o = match.groups()
        if dt_a != dt_b:
            continue
        dims = _mkn(*[int(v) for v in (a0, a1, b0, b1, o0, o1)])
        if dims is None:
            continue
        m, k, n = dims
        key = (m, k, n, dt_a)
        if key in seen:
            continue
        seen.add(key)
        specs.append(matmul_spec(m, k, n, dtypes[dt_a],
                                 precision_level))
    return specs


def collect_specs(plans, state, batch, sample_shape, loss="softmax",
                  dtype="float32", precision_level=0, ops=None):
    """Lower the fused train step once and return the deduplicated
    tune-spec list it consulted (+ the dot_general matmul harvest).

    ``plans``/``state`` as from ``models.zoo.build_plans_and_state``;
    ``ops`` optionally restricts to a family subset (e.g. the CLI's
    ``--ops matmul``)."""
    import jax
    import numpy

    from veles_tpu import compiler
    from veles_tpu.ops import common
    from veles_tpu.tune.cache import record_specs, schedule_key

    def aval(leaf):
        return (None if leaf is None else
                jax.ShapeDtypeStruct(numpy.shape(leaf),
                                     numpy.asarray(leaf).dtype))

    state_avals = [{key: aval(value) for key, value in entry.items()}
                   for entry in state]
    if dtype == "bfloat16":
        import jax.numpy as jnp
        np_dtype = jnp.bfloat16
    else:
        np_dtype = numpy.dtype(dtype)
    x_aval = jax.ShapeDtypeStruct((batch,) + tuple(sample_shape),
                                  np_dtype)
    if loss == "mse":
        out_shape = numpy.shape(state[-1]["weights"])[-1]
        y_aval = jax.ShapeDtypeStruct((batch, out_shape), np_dtype)
    else:
        y_aval = jax.ShapeDtypeStruct((batch,), numpy.int32)

    saved_knob = common.PALLAS_BWD_ENV
    try:
        # pass 1: hand-scheduled backward ON — the Pallas families'
        # consults fire with the step's traced shapes (recording only;
        # in interpret mode this lowering's text also contains the
        # kernels' INTERNAL tile dots, which must not be harvested as
        # model matmuls)
        common.PALLAS_BWD_ENV = "1"
        step = compiler.build_train_step(plans, loss=loss,
                                         donate=False)
        with record_specs() as recorded:
            step.lower(state_avals, x_aval, y_aval,
                       numpy.float32(batch))
        # pass 2: stock autodiff backward — the lowering's dot_generals
        # are the MODEL's dense contractions, harvested for the Pallas
        # matmul the serving/BLAS paths route those shapes through
        common.PALLAS_BWD_ENV = "0"
        step_stock = compiler.build_train_step(plans, loss=loss,
                                               donate=False)
        text = step_stock.lower(state_avals, x_aval, y_aval,
                                numpy.float32(batch)).as_text()
    finally:
        common.PALLAS_BWD_ENV = saved_knob

    specs = list(recorded)
    seen = {spec["digest"] for spec in specs}
    from veles_tpu.tune.cache import device_kind
    kind = device_kind()
    for spec in dot_specs_from_text(text, precision_level):
        digest, _ = schedule_key(spec["op"], spec["shape"],
                                 spec["dtype"],
                                 spec["precision_level"], kind,
                                 spec.get("extra"))
        if digest in seen:
            continue
        seen.add(digest)
        spec = dict(spec, digest=digest)
        specs.append(spec)
    if ops:
        allowed = set(ops)
        specs = [s for s in specs if s["op"] in allowed]
    return specs
