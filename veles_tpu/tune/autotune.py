"""The genetics-driven schedule tuner (docs/kernels.md, "Autotuning").

TVM's lesson (PAPERS.md) applied with the repo's own GA: a
:class:`ScheduleTuner` searches one kernel family's tile/grid space
per (op, padded shape, dtype, precision level, device kind) spec and
persists the winner in the digest-keyed :class:`~veles_tpu.tune.cache.
ScheduleCache` the kernels consult.

Fitness = **negative measured seconds per kernel execution**, under
the shared measurement discipline (``tune/measure.py``): the
in-process path evaluates a whole GA generation's candidates with
interleaved round-robin slope sampling — one sample of EVERY candidate
per pass, ``filter_passes``/positive-majority ranking — so a
congestion window cannot crown the wrong tile (the hazard
``ops/matmul.py`` documents).  Candidate schedules are quantized to
MXU-legal multiples and VMEM-checked BEFORE any compile; duplicate or
clamped-identical genomes hit the schedule-keyed fitness memo (plus
GeneticsOptimizer's own values-keyed memo) and never pay a second
compile.

Evaluator plumbing mirrors the GA's: ``workers=N`` uses the process
pool, ``farm_slaves``/``farm_address`` the control-plane job farm
(remote hosts join via :func:`GeneticsOptimizer.worker` quoting
:func:`evaluate_candidate`) — a fleet can tune in parallel.  Those
paths score candidates independently (each with its own multi-pass
filtered timing); only the in-process default gets cross-candidate
interleaving.

``fitness="compile"`` replaces timing with one compile+execute pass
(fitness = negative wall seconds of the warm-up) — the CI mode: it
exercises every moving part on CPU interpret kernels in seconds and
still rejects uncompilable candidates.

``fitness="model"`` is the learned-cost-model mode (``tune/
costmodel.py``): every generation's distinct feasible schedules are
ranked by the model and only the top decile (floor: 2) compiles and
measures under the base discipline (``model_base``: "measure", or
"compile" for CI); the rest inherit their PREDICTED fitness for
selection purposes only.  Every measured slope — in every mode — is
appended to the ``measurements.jsonl`` sidecar, which is where the
model's training data comes from in the first place.  The persisted
winner is always the best MEASURED schedule; a predicted fitness can
steer the GA but can never reach the cache.  When the family's
training data is thin or the model fails its leave-one-spec-out trust
gate the tuner silently degrades to the base mode (the receipt row
says why).
"""

import json

from veles_tpu.genetics.config import Tune
from veles_tpu.genetics.optimizer import GeneticsOptimizer
from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.tune import cache as _cache
from veles_tpu.tune import measure as _measure
from veles_tpu.tune.spec import family_for

__all__ = ["ScheduleTuner", "evaluate_candidate", "sweep_candidates",
           "PENALTY"]

#: fitness for infeasible / uncompilable / unmeasurable candidates —
#: large-negative-but-FINITE so roulette selection stays well-defined
PENALTY = -1.0e9


def _schedule_memo_key(schedule):
    return json.dumps(schedule, sort_keys=True)


def _compile_runner(family, spec, schedule):
    """(run, compile_seconds) or (None, None) when the candidate fails
    to build — a VMEM overflow Mosaic rejects at compile is a PENALTY,
    never a crash."""
    import time
    try:
        warm, run = family.build_runner(spec, schedule)
        start = time.perf_counter()
        warm()
        return run, time.perf_counter() - start
    except Exception:
        return None, None


def _timed_fitness(run, repeats, rounds):
    """Multi-pass filtered slope timing of one already-warm runner:
    ``rounds`` passes, positive-majority median, PENALTY when every
    pass measured jitter."""
    samples = [_measure.slope_sample(run, 1, repeats + 1)
               for _ in range(rounds)]
    med = _measure.positive_majority_median(samples)
    return PENALTY if med is None else -med


def _record_triple(spec, schedule, slope, mode):
    """Append one measured (spec, schedule, slope) triple to the
    ``measurements.jsonl`` sidecar — the cost model's training data;
    never raises."""
    try:
        digest, payload = _cache.schedule_key(
            spec["op"], spec["shape"], spec["dtype"],
            spec["precision_level"], _cache.device_kind(),
            spec.get("extra"))
        _cache.record_measurement(digest, payload, schedule, slope,
                                  mode=mode)
    except Exception:
        pass


def evaluate_candidate(candidate):
    """Per-candidate fitness — module-level and self-contained so the
    process-pool and control-plane farm evaluators can pickle/quote it.
    ``candidate`` is the GA's applied spec: ``{"family", "spec",
    "genes", "fitness_mode", "repeats", "rounds"}``."""
    family = family_for(candidate["family"])
    spec = candidate["spec"]
    schedule = family.quantize(spec, candidate["genes"])
    if not family.feasible(spec, schedule):
        return PENALTY
    run, compile_s = _compile_runner(family, spec, schedule)
    if run is None:
        return PENALTY
    _registry.counter("tune.evals").inc()
    if candidate.get("fitness_mode") == "compile":
        _record_triple(spec, schedule, compile_s, "compile")
        return -compile_s
    fitness = _timed_fitness(run, candidate.get("repeats", 8),
                             candidate.get("rounds", 3))
    if fitness > PENALTY:
        _record_triple(spec, schedule, -fitness, "measure")
    return fitness


class _TunerGA(GeneticsOptimizer):
    """GeneticsOptimizer + the observe plane: every generation's
    evaluation runs under a ``tune.generation`` span, and the number
    of genuinely dispatched (non-memoized) evaluations is tracked for
    the receipt.  ``snap_fn`` projects raw genomes onto the quantized
    schedule lattice BEFORE the memo lookup, so genomes that clamp to
    the same schedule are bit-identical values — the values-keyed memo
    then dedupes them on EVERY evaluator path, including the
    process-pool/farm children that cannot share the in-process
    schedule memo."""

    def __init__(self, *args, snap_fn=None, **kwargs):
        super(_TunerGA, self).__init__(*args, **kwargs)
        self.snap_fn = snap_fn
        self.dispatched = 0

    def _evaluate_all(self):
        if self.snap_fn is not None:
            for chromo in self.population.unevaluated():
                chromo.values = self.snap_fn(chromo.values)
        memo_before = len(self._fitness_memo)
        pending = len(self.population.unevaluated())
        with _tracer.span("tune.generation", cat="tune",
                          generation=self.population.generation,
                          pending=pending):
            super(_TunerGA, self)._evaluate_all()
        self.dispatched += (len(self._fitness_memo) - memo_before
                            if self.memoize_fitness else pending)


class ScheduleTuner(Logger):
    """Tune ONE (op, shape, dtype, precision, device) spec.

    ``spec`` comes from the ``tune/spec.py`` builders or a
    ``record_specs`` walk.  :meth:`tune` consults the schedule cache
    first (a hit skips the GA entirely); on a miss it runs the GA —
    population seeded with the family's curated candidates — and
    persists the winner.
    """

    def __init__(self, spec, cache=None, generations=4, population=8,
                 workers=0, farm_slaves=0, farm_address="127.0.0.1:0",
                 fitness="measure", repeats=8, rounds=3, rng=None,
                 device_kind=None, model_base="measure",
                 model_min_triples=None, model_trust=None, **kwargs):
        super(ScheduleTuner, self).__init__(**kwargs)
        self.spec = dict(spec)
        self.family = family_for(self.spec["op"])
        self.cache = cache or _cache.cache_for()
        self.generations = generations
        self.population = population
        self.workers = workers
        self.farm_slaves = farm_slaves
        self.farm_address = farm_address
        self.fitness_mode = fitness
        self.repeats = repeats
        self.rounds = rounds
        self.rng = rng
        self.device_kind = device_kind or _cache.device_kind()
        self.model_base = model_base
        self.model_min_triples = model_min_triples
        self.model_trust = model_trust
        if fitness == "model" and (workers or farm_slaves):
            # model ranking needs the in-process batch evaluator (the
            # pool/farm children score candidates independently);
            # degrade to the base mode rather than mis-rank
            self.warning("tune: fitness='model' is in-process only; "
                         "using fitness=%r for the pool/farm run",
                         model_base)
            self.fitness_mode = model_base
        self._model = None
        self._model_info = None
        self._best_measured = (PENALTY, None)
        self._sched_memo = {}

    @property
    def _measure_mode(self):
        """The mode actual measurements run under: the base mode in
        (and under fallback from) fitness='model'."""
        if self.fitness_mode == "model":
            return self.model_base
        return self.fitness_mode

    # -- cache key -----------------------------------------------------------

    def key(self):
        return _cache.schedule_key(
            self.spec["op"], self.spec["shape"], self.spec["dtype"],
            self.spec["precision_level"], self.device_kind,
            self.spec.get("extra"))

    # -- the in-process batch evaluator (interleaved discipline) -------------

    def _batch_fitness(self, candidates):
        fits = [None] * len(candidates)
        to_measure = {}   # schedule memo key -> (schedule, [indices])
        for i, cand in enumerate(candidates):
            schedule = self.family.quantize(self.spec, cand["genes"])
            key = _schedule_memo_key(schedule)
            if key in self._sched_memo:
                fits[i] = self._sched_memo[key]
            elif not self.family.feasible(self.spec, schedule):
                fits[i] = self._sched_memo[key] = PENALTY
            else:
                entry = to_measure.setdefault(key, (schedule, []))
                entry[1].append(i)

        measure_keys = list(to_measure)
        if self._model is not None and len(measure_keys) > 2:
            # model mode: rank the generation's distinct feasible
            # schedules, compile+measure only the top decile (floor 2);
            # the rest carry their PREDICTED fitness — selection
            # pressure only, never persisted, never a tune.eval
            schedules = [to_measure[key][0] for key in measure_keys]
            predicted = self._model.predict_seconds(self.spec,
                                                    schedules)
            order = sorted(range(len(measure_keys)),
                           key=lambda i: (float(predicted[i]), i))
            top = max(2, -(-len(measure_keys) // 10))
            for rank_i in order[top:]:
                key = measure_keys[rank_i]
                fitness = -float(predicted[rank_i])
                self._sched_memo[key] = fitness
                self._model_info["predicted"] += 1
                for i in to_measure[key][1]:
                    fits[i] = fitness
            measure_keys = [measure_keys[rank_i]
                            for rank_i in order[:top]]

        mode = self._measure_mode
        runners, compile_s = {}, {}
        for key in measure_keys:
            schedule, indices = to_measure[key]
            run, seconds = _compile_runner(self.family, self.spec,
                                           schedule)
            if run is None:
                self._sched_memo[key] = PENALTY
                for i in indices:
                    fits[i] = PENALTY
                continue
            _registry.counter("tune.evals").inc()
            runners[key] = run
            compile_s[key] = seconds

        if mode == "compile":
            ranked = {key: compile_s[key] for key in runners}
        else:
            # ONE sample of every candidate per pass: congestion drift
            # spreads across all candidates equally
            samples = _measure.interleaved_slopes(
                runners, 1, self.repeats + 1, rounds=self.rounds)
            ranked = _measure.rank(samples)

        for key in runners:
            med = ranked.get(key)
            fitness = PENALTY if med is None else -med
            self._sched_memo[key] = fitness
            if med is not None:
                schedule = to_measure[key][0]
                _record_triple(self.spec, schedule, med, mode)
                if fitness > self._best_measured[0]:
                    self._best_measured = (fitness, schedule)
            for i in to_measure[key][1]:
                fits[i] = fitness
        return fits

    # -- the cost model ------------------------------------------------------

    def _setup_model(self):
        """Train-and-trust-gate the family's cost model from the
        measurement sidecar; on thin data or a failed validation gate
        ``self._model`` stays None and the run degrades to the base
        mode (the receipt row's ``model.fallback`` says why)."""
        from veles_tpu.tune import costmodel
        kwargs = {}
        if self.model_min_triples is not None:
            kwargs["min_triples"] = self.model_min_triples
        if self.model_trust is not None:
            kwargs["trust_error"] = self.model_trust
        try:
            model, info = costmodel.train_for(
                self.family.name, mode=self.model_base, **kwargs)
        except Exception as exc:
            model, info = None, {"family": self.family.name,
                                 "fallback": "train-error: %s" % exc}
        self._model = model
        info["predicted"] = 0
        self._model_info = info
        if model is None:
            self.warning(
                "tune: cost model unavailable for %s (%s); measuring "
                "every candidate (fitness=%r)", self.family.name,
                info.get("fallback"), self.model_base)

    # -- the GA run ----------------------------------------------------------

    def _ga_spec(self, space):
        return {
            "family": self.family.name,
            "spec": {k: v for k, v in self.spec.items()},
            "genes": space,
            # the pool/farm children measure every candidate they get
            # (model ranking is in-process only), so they are told the
            # base mode, never "model"
            "fitness_mode": self._measure_mode,
            "repeats": self.repeats,
            "rounds": self.rounds,
        }

    def _snap_genome(self, space):
        """A genome -> genome projection onto the quantized schedule
        lattice: raw genes become the exact quantize()d tile values
        (which live inside the Tune boxes by construction), so two
        genomes that clamp to the same schedule ARE the same genome."""
        import numpy

        from veles_tpu.genetics.config import extract_tunes
        order = [path[-1] for path, _ in extract_tunes(space)]

        def snap(values):
            genes = dict(zip(order, (float(v) for v in values)))
            schedule = self.family.quantize(self.spec, genes)
            snapped = self.family.genes_of(schedule)
            return numpy.asarray([float(snapped[name])
                                  for name in order], numpy.float64)

        return snap

    def _seed_population(self, opt):
        """Overwrite the random initial genomes with the family's
        curated candidates (clamped into the Tune boxes) — the GA
        starts from measured winners, mutation explores around them."""
        import numpy
        tunes = opt.tunes  # [(path, Tune)] in the GA's gene order
        seeds = self.family.seeds(self.spec)
        for chromo, schedule in zip(opt.population.chromosomes, seeds):
            genes = self.family.genes_of(
                self.family.quantize(self.spec,
                                     self.family.genes_of(schedule)))
            chromo.values = numpy.asarray(
                [min(max(float(genes[path[-1]]), tune.min), tune.max)
                 for path, tune in tunes], numpy.float64)
            chromo.fitness = None

    def tune(self, force=False):
        """Returns the receipt row: ``{"digest", "op", "shape",
        "dtype", "schedule", "fitness", "source", "evals",
        "generations"}`` with ``source`` one of ``cache`` / ``ga`` /
        ``untunable`` / ``unranked``."""
        digest, payload = self.key()
        row = {"digest": digest, "op": self.spec["op"],
               "shape": list(self.spec["shape"]),
               "dtype": self.spec["dtype"],
               "precision_level": self.spec["precision_level"],
               "evals": 0, "genomes": 0}
        if not force:
            entry = self.cache.get(digest)
            if entry is not None:
                # same structural validation as the kernels' consult:
                # a malformed/stale entry the kernels would reject
                # must be a MISS here too (and get retuned/overwritten)
                # — otherwise it reports source="cache" forever while
                # static tiles actually serve
                from veles_tpu.tune.spec import valid_schedule
                normalized = valid_schedule(self.spec["op"],
                                            entry["schedule"])
                if normalized is not None:
                    row.update(schedule=normalized,
                               fitness=entry.get("fitness"),
                               source="cache")
                    _registry.counter("tune.cache_hits").inc()
                    return row
        _registry.counter("tune.cache_misses").inc()

        space = self.family.space(self.spec)
        if space is None:
            row.update(schedule=None, source="untunable")
            return row

        if self.fitness_mode == "model":
            self._setup_model()
            row["model"] = self._model_info

        batch = None if (self.workers or self.farm_slaves) \
            else self._batch_fitness
        opt = _TunerGA(
            self._ga_spec(space), evaluate_candidate,
            generations=self.generations, population=self.population,
            workers=self.workers, farm_slaves=self.farm_slaves,
            farm_address=self.farm_address, rng=self.rng,
            batch_fitness_fn=batch,
            snap_fn=self._snap_genome(space))
        self._seed_population(opt)
        evals_before = _registry.counter("tune.evals").value
        with _tracer.span("tune.spec", cat="tune", op=self.spec["op"],
                          digest=digest[:12]):
            best_candidate, best_fitness = opt.run()
        # "evals" = compiles actually PAID (the tune.evals counter
        # delta; infeasible and memo-hit genomes are free and must not
        # inflate the receipt).  "genomes" = distinct genomes the GA
        # dispatched — the memo's denominator.  On subprocess paths
        # (workers/farm) the counter ticks in the children, so fall
        # back to the dispatch count there rather than claim zero.
        evals = _registry.counter("tune.evals").value - evals_before
        if (self.workers or self.farm_slaves) and evals == 0:
            evals = opt.dispatched
        row["evals"] = evals
        row["genomes"] = opt.dispatched

        if self._model is not None:
            # the GA's champion may carry a PREDICTED fitness; only a
            # measured winner may be persisted or reported — swap in
            # the best measured schedule (every generation measured
            # its top slice, so one exists whenever anything ranked)
            best_fitness, best_schedule = self._best_measured
            if best_fitness > PENALTY:
                self.cache.put(digest, payload, best_schedule,
                               fitness=best_fitness, source="ga",
                               evals=evals)
                row.update(schedule=best_schedule,
                           fitness=best_fitness, source="ga")
                self.info(
                    "tune: %s %s -> %s (model-ranked; fitness %.3g, "
                    "%d evals / %d genomes, %d predicted-only)",
                    self.spec["op"], tuple(self.spec["shape"]),
                    best_schedule, best_fitness, evals,
                    opt.dispatched, self._model_info["predicted"])
                return row

        if best_fitness <= PENALTY:
            # every candidate was infeasible or measured only jitter:
            # nothing rankable — do NOT persist (the static tables
            # keep serving; a later, quieter run may succeed)
            self.warning(
                "tune: no candidate for %s %s produced a rankable "
                "measurement; keeping static tables",
                self.spec["op"], tuple(self.spec["shape"]))
            row.update(schedule=None, source="unranked")
            return row

        schedule = self.family.quantize(self.spec,
                                        best_candidate["genes"])
        self.cache.put(digest, payload, schedule,
                       fitness=best_fitness, source="ga", evals=evals)
        row.update(schedule=schedule, fitness=best_fitness,
                   source="ga")
        self.info("tune: %s %s -> %s (fitness %.3g, %d evals / %d "
                  "genomes)", self.spec["op"],
                  tuple(self.spec["shape"]), schedule, best_fitness,
                  evals, opt.dispatched)
        return row


def sweep_candidates(spec, candidates, repeats=24, rounds=5,
                     device_kind=None, cache=None, persist=True,
                     fitness="measure"):
    """The plain curated-candidate sweep (no GA) under the SAME
    measurement discipline and persistence path — what
    ``ops.matmul.autotune_matmul`` runs.  ``candidates`` are schedule
    dicts; clamp-identical ones are measured once.  Returns
    ``(best_schedule_or_None, ranking)`` where ranking maps the memo
    key of each distinct schedule to its median seconds (None =
    jitter-rejected)."""
    family = family_for(spec["op"])
    distinct = {}
    for candidate in candidates:
        schedule = family.quantize(spec, family.genes_of(candidate))
        key = _schedule_memo_key(schedule)
        if key not in distinct and family.feasible(spec, schedule):
            distinct[key] = schedule

    runners, compile_s = {}, {}
    for key, schedule in distinct.items():
        run, seconds = _compile_runner(family, spec, schedule)
        if run is None:
            continue  # VMEM-overflow tiles fail to compile: skipped
        _registry.counter("tune.evals").inc()
        runners[key] = run
        compile_s[key] = seconds

    if fitness == "compile":
        ranking = {key: compile_s[key] for key in runners}
    else:
        samples = _measure.interleaved_slopes(
            runners, 1, repeats + 1, rounds=rounds)
        ranking = _measure.rank(samples)
    for key, med in ranking.items():
        if med is not None:
            _record_triple(spec, distinct[key], med, fitness)
    best_key, best_time = None, float("inf")
    for key, med in ranking.items():
        if med is not None and med < best_time:
            best_key, best_time = key, med
    if best_key is None:
        return None, ranking
    best = distinct[best_key]
    if persist:
        kind = device_kind or _cache.device_kind()
        digest, payload = _cache.schedule_key(
            spec["op"], spec["shape"], spec["dtype"],
            spec["precision_level"], kind, spec.get("extra"))
        (cache or _cache.cache_for()).put(
            digest, payload, best, fitness=-best_time, source="sweep",
            evals=len(runners))
    return best, ranking
