"""Ensemble train/test drivers.

Reference semantics (ensemble/base_workflow.py:104-161): ``--ensemble-
train N:r`` trained N models, each a fresh child process with its own
seed and a random ``r`` fraction of the train set, collecting per-model
results into a JSON file; ``--ensemble-test`` reran stored snapshots
and aggregated outputs.

Here each member is a workflow built by a factory(member_index, seed)
-> StandardWorkflow, trained in-process (or farmed as control-plane
jobs); results carry snapshot paths + metrics in the same JSON spirit
(the reference's wine_ensemble.json artifact).  Test-time aggregation
averages softmax outputs (the reference's evaluation transform).
"""

import json
import os
import pickle

import numpy

from veles_tpu.logger import Logger

__all__ = ["EnsembleTrainer", "EnsembleTester"]


class EnsembleTrainer(Logger):
    """Train ``size`` members; persist snapshots + a results JSON."""

    def __init__(self, workflow_factory, size, directory,
                 train_ratio=1.0, device=None, base_seed=1000):
        super(EnsembleTrainer, self).__init__()
        self.workflow_factory = workflow_factory
        self.size = size
        self.directory = directory
        self.train_ratio = train_ratio
        self.device = device
        self.base_seed = base_seed
        self.results = []

    @property
    def results_path(self):
        return os.path.join(self.directory, "ensemble.json")

    def run(self):
        os.makedirs(self.directory, exist_ok=True)
        for i in range(self.size):
            seed = self.base_seed + i
            sw = self.workflow_factory(i, seed)
            sw.initialize(device=self.device)
            sw.run()
            snapshot = os.path.join(self.directory,
                                    "member_%03d.pickle" % i)
            with open(snapshot, "wb") as fout:
                pickle.dump(sw, fout, protocol=pickle.HIGHEST_PROTOCOL)
            entry = {
                "id": i,
                "seed": seed,
                "snapshot": snapshot,
                "EvaluationFitness": -(
                    sw.decision.best_metric
                    if sw.decision.best_metric is not None else 1e9),
                "metrics": list(sw.decision.epoch_metrics),
            }
            self.results.append(entry)
            self.info("member %d/%d trained: metrics %s", i + 1,
                      self.size, entry["metrics"])
        with open(self.results_path, "w") as fout:
            json.dump({"models": self.results}, fout, indent=1,
                      sort_keys=True)
        return self.results_path


class EnsembleTester(Logger):
    """Load trained members; average their outputs on given data."""

    def __init__(self, results_path, device=None):
        super(EnsembleTester, self).__init__()
        with open(results_path) as fin:
            self.results = json.load(fin)["models"]
        self.device = device
        self._members = None

    @property
    def members(self):
        if self._members is None:
            from veles_tpu.dummy import DummyLauncher
            self._members = []
            for entry in self.results:
                with open(entry["snapshot"], "rb") as fin:
                    sw = pickle.load(fin)
                sw.workflow = DummyLauncher()
                sw.initialize(device=self.device)
                self._members.append(sw)
        return self._members

    def predict(self, x):
        """Average member outputs: (B, classes)."""
        from veles_tpu.compiler import (
            build_forward, extract_state, workflow_plan)
        outputs = []
        for sw in self.members:
            plans = workflow_plan(sw)
            state = extract_state(sw)
            params = [{"weights": s["weights"], "bias": s["bias"]}
                      for s in state]
            outputs.append(numpy.asarray(build_forward(plans)(params, x)))
        return numpy.mean(outputs, axis=0)

    def error_rate(self, x, labels):
        probs = self.predict(x)
        pred = probs.argmax(axis=1)
        return 100.0 * float((pred != labels).sum()) / len(labels)
