"""Ensemble train/test drivers.

Reference semantics (ensemble/base_workflow.py:104-161): ``--ensemble-
train N:r`` trained N models, each a fresh child process with its own
seed and a random ``r`` fraction of the train set, collecting per-model
results into a JSON file; ``--ensemble-test`` reran stored snapshots
and aggregated outputs.

Here each member is a workflow built by a factory(member_index, seed)
-> StandardWorkflow, trained in-process (or farmed as control-plane
jobs); results carry snapshot paths + metrics in the same JSON spirit
(the reference's wine_ensemble.json artifact).  Test-time aggregation
averages softmax outputs (the reference's evaluation transform).
"""

import json
import os
import pickle

import numpy

from veles_tpu.logger import Logger

__all__ = ["EnsembleTrainer", "EnsembleTester"]


class EnsembleTrainer(Logger):
    """Train ``size`` members; persist snapshots + a results JSON.

    ``farm_slaves`` > 0 farms member training as control-plane jobs
    (the reference distributed members as master-slave jobs,
    ensemble/base_workflow.py:135-153): a job farm master serves
    member indices, ``farm_slaves`` in-process workers train them
    concurrently, and remote hosts may join via
    :meth:`worker` against ``farm_address``.  Snapshots land on the
    filesystem of whichever worker trained the member — same-host
    workers (the default) share ``directory``; cross-host setups need
    it on a shared mount, exactly like the reference's child-process
    result files."""

    FARM_TAG = "ensemble"

    def __init__(self, workflow_factory, size, directory,
                 train_ratio=1.0, device=None, base_seed=1000,
                 farm_slaves=0, farm_address="127.0.0.1:0"):
        super(EnsembleTrainer, self).__init__()
        self.workflow_factory = workflow_factory
        self.size = size
        self.directory = directory
        self.train_ratio = train_ratio
        self.device = device
        self.base_seed = base_seed
        self.farm_slaves = farm_slaves
        self.farm_address = farm_address
        self.results = []

    @property
    def results_path(self):
        return os.path.join(self.directory, "ensemble.json")

    def train_member(self, i):
        """Train one member end to end; returns its results entry.
        This is the farmed job body — self-contained so any worker
        (thread here, remote host via :meth:`worker`) can run it.

        The reference's ``--ensemble-train N:r`` trained each member
        on a random r-fraction of the train set; factories that take
        a third argument receive ``train_ratio`` to apply it (the
        two-argument ``factory(index, seed)`` form stays valid)."""
        import inspect
        seed = self.base_seed + i
        takes_ratio = False
        try:
            params = inspect.signature(
                self.workflow_factory).parameters.values()
            positional = sum(
                1 for p in params
                if p.kind in (p.POSITIONAL_ONLY,
                              p.POSITIONAL_OR_KEYWORD))
            var_positional = any(
                p.kind == p.VAR_POSITIONAL for p in params)
            takes_ratio = positional >= 3 or var_positional
        except (TypeError, ValueError):
            pass
        if takes_ratio:
            sw = self.workflow_factory(i, seed, self.train_ratio)
        else:
            sw = self.workflow_factory(i, seed)
        sw.initialize(device=self.device)
        sw.run()
        snapshot = os.path.join(self.directory,
                                "member_%03d.pickle" % i)
        # atomic publish: a speculative backup copy of this job (farm
        # straggler shadowing) may write the same path concurrently
        tmp = "%s.%d.tmp" % (snapshot, os.getpid() ^ id(sw))
        with open(tmp, "wb") as fout:
            pickle.dump(sw, fout, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, snapshot)
        entry = {
            "id": i,
            "seed": seed,
            "snapshot": snapshot,
            "EvaluationFitness": -(
                sw.decision.best_metric
                if sw.decision.best_metric is not None else 1e9),
            "metrics": list(sw.decision.epoch_metrics),
        }
        self.info("member %d/%d trained: metrics %s", i + 1,
                  self.size, entry["metrics"])
        return entry

    @property
    def farm_enabled(self):
        from veles_tpu.jobfarm import farm_enabled
        return farm_enabled(self.farm_slaves, self.farm_address)

    def run(self):
        os.makedirs(self.directory, exist_ok=True)
        if self.farm_enabled:
            from veles_tpu.jobfarm import JobFarm
            self.results = JobFarm(self.FARM_TAG).run(
                range(self.size), runner=self.train_member,
                address=self.farm_address,
                local_slaves=self.farm_slaves)
        else:
            self.results = [self.train_member(i)
                            for i in range(self.size)]
        with open(self.results_path, "w") as fout:
            json.dump({"models": self.results}, fout, indent=1,
                      sort_keys=True)
        return self.results_path

    def worker(self, address):
        """Blocking remote-worker loop: train members the master at
        ``address`` hands out (build this trainer with the SAME
        factory/directory arguments on the worker host)."""
        from veles_tpu.jobfarm import JobFarm
        os.makedirs(self.directory, exist_ok=True)
        return JobFarm(self.FARM_TAG).worker(address, self.train_member)


class EnsembleTester(Logger):
    """Load trained members; average their outputs on given data.

    ``farm_slaves``/``farm_address``: evaluate members as control-plane
    jobs instead of in-process (the reference's ``--ensemble-test``
    reran stored snapshots as jobs the same way,
    ensemble/test_workflow.py); workers need the snapshot files
    visible at the recorded paths (same host or shared mount)."""

    FARM_TAG = "ensemble-test"

    def __init__(self, results_path, device=None, farm_slaves=0,
                 farm_address="127.0.0.1:0"):
        super(EnsembleTester, self).__init__()
        with open(results_path) as fin:
            self.results = json.load(fin)["models"]
        self.device = device
        self.farm_slaves = farm_slaves
        self.farm_address = farm_address
        self._members = None

    @property
    def farm_enabled(self):
        from veles_tpu.jobfarm import farm_enabled
        return farm_enabled(self.farm_slaves, self.farm_address)

    def _device_spec(self):
        """Picklable device identity for job specs (workers rebuild
        their own Device from the backend name)."""
        if self.device is None or isinstance(self.device, str):
            return self.device
        return getattr(self.device, "backend", None)

    @staticmethod
    def _forward_outputs(sw, x):
        """One member's forward pass — the single definition both the
        in-process and farmed paths run, so they cannot diverge."""
        from veles_tpu.compiler import (
            build_forward, extract_state, workflow_plan)
        plans = workflow_plan(sw)
        state = extract_state(sw)
        params = [{"weights": s["weights"], "bias": s["bias"]}
                  for s in state]
        return numpy.asarray(build_forward(plans)(params, x))

    @staticmethod
    def predict_member(spec, x):
        """Farmed job body: load one snapshot, run its forward on the
        context-shipped batch ``x``; returns (B, classes) numpy."""
        from veles_tpu.dummy import DummyLauncher
        snapshot, device_spec = spec
        with open(snapshot, "rb") as fin:
            sw = pickle.load(fin)
        sw.workflow = DummyLauncher()
        sw.initialize(device=device_spec)
        return EnsembleTester._forward_outputs(sw, x)

    def worker(self, address):
        """Blocking remote-worker loop for distributed ensemble
        evaluation."""
        from veles_tpu.jobfarm import JobFarm
        return JobFarm(self.FARM_TAG).worker(address,
                                             self.predict_member)

    @property
    def members(self):
        if self._members is None:
            from veles_tpu.dummy import DummyLauncher
            self._members = []
            for entry in self.results:
                with open(entry["snapshot"], "rb") as fin:
                    sw = pickle.load(fin)
                sw.workflow = DummyLauncher()
                sw.initialize(device=self.device)
                self._members.append(sw)
        return self._members

    def predict(self, x):
        """Average member outputs: (B, classes)."""
        if self.farm_enabled:
            from veles_tpu.jobfarm import JobFarm
            device_spec = self._device_spec()
            # the batch ships ONCE per worker as farm context, not
            # inside every member's job spec
            outputs = JobFarm(self.FARM_TAG,
                              context=numpy.asarray(x)).run(
                [(entry["snapshot"], device_spec)
                 for entry in self.results],
                runner=self.predict_member,
                address=self.farm_address,
                local_slaves=self.farm_slaves)
            return numpy.mean(outputs, axis=0)
        outputs = [self._forward_outputs(sw, x)
                   for sw in self.members]
        return numpy.mean(outputs, axis=0)

    def error_rate(self, x, labels):
        probs = self.predict(x)
        pred = probs.argmax(axis=1)
        return 100.0 * float((pred != labels).sum()) / len(labels)
