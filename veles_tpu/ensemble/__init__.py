"""Ensembles: train N models, test by aggregating their outputs.

TPU-native counterpart of reference veles/ensemble/ (base_workflow.py:59
job farm, model_workflow.py:50 --ensemble-train, test_workflow.py
--ensemble-test).
"""

from veles_tpu.ensemble.workflows import (  # noqa: F401
    EnsembleTrainer, EnsembleTester)
