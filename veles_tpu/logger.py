"""Logging mixin and structured event tracing.

TPU-native counterpart of the reference's Logger mixin
(reference: veles/logger.py:59,187,264).  Differences by design:

- Event tracing writes JSON lines to a local file (or any file-like sink)
  instead of MongoDB; the schema (name, kind=begin|end|single, timestamp,
  session, attrs) is preserved so downstream dashboards can consume either.
- Colored console output is plain ANSI, no termcolor dependency.
"""

import datetime
import json
import logging
import logging.handlers
import os
import sys
import threading
import time
import uuid

__all__ = ["Logger", "set_file_logging", "set_event_file",
           "add_event_hook", "remove_event_hook"]

_COLORS = {
    logging.DEBUG: "\033[36m",     # cyan
    logging.INFO: "\033[32m",      # green
    logging.WARNING: "\033[33m",   # yellow
    logging.ERROR: "\033[31m",     # red
    logging.CRITICAL: "\033[41m",  # red background
}
_RESET = "\033[0m"

#: Session id grouping all events of this process (reference groups runs by
#: a Mongo ``log_id``; we use a uuid4 hex).
session_id = uuid.uuid4().hex

_event_lock = threading.Lock()
_event_file = None


class ColorFormatter(logging.Formatter):
    def format(self, record):
        msg = super(ColorFormatter, self).format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return "%s%s%s" % (color, msg, _RESET) if color else msg
        return msg


def setup_logging(level=logging.INFO):
    """Install the root console handler once."""
    logger = logging.getLogger()
    if getattr(setup_logging, "_done", False):
        logger.setLevel(level)
        return
    setup_logging._done = True
    logger.setLevel(level)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(ColorFormatter(
        "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"))
    logger.addHandler(handler)


def set_file_logging(path, level=logging.DEBUG):
    """Duplicate all log records into ``path`` (reference: -f flag)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    handler.setLevel(level)
    logging.getLogger().addHandler(handler)
    return handler


_event_hooks = []


def add_event_hook(fn):
    """Register an observer called with every event record (the
    reference streamed events to MongoDB, logger.py:264-289; the
    web-status reporter forwards them to the dashboard's event log).
    Hooks must be fast or enqueue — they run on the traced thread."""
    _event_hooks.append(fn)


def remove_event_hook(fn):
    try:
        _event_hooks.remove(fn)
    except ValueError:
        pass


def set_event_file(path):
    """Route ``Logger.event`` records to a JSON-lines file."""
    global _event_file
    with _event_lock:
        if _event_file is not None:
            _event_file.close()
        if path is None:
            _event_file = None
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            _event_file = open(path, "a")


class Logger(object):
    """Mixin giving every object a named logger plus event tracing."""

    def __init__(self, **kwargs):
        logger_name = kwargs.pop("logger_name", type(self).__name__)
        super(Logger, self).__init__()
        self._logger_ = logging.getLogger(logger_name)

    def init_unpickled(self):
        parent = super(Logger, self)
        if hasattr(parent, "init_unpickled"):
            parent.init_unpickled()
        self._logger_ = logging.getLogger(type(self).__name__)

    @property
    def logger(self):
        return self._logger_

    def change_logger_name(self, name):
        self._logger_ = logging.getLogger(name)

    def debug(self, msg, *args):
        self._logger_.debug(msg, *args)

    def info(self, msg, *args):
        self._logger_.info(msg, *args)

    def warning(self, msg, *args):
        self._logger_.warning(msg, *args)

    def error(self, msg, *args):
        self._logger_.error(msg, *args)

    def exception(self, msg="Exception", *args):
        self._logger_.exception(msg, *args)

    def critical(self, msg, *args):
        self._logger_.critical(msg, *args)

    def event(self, name, kind, **attrs):
        """Emit a structured trace record.

        ``kind`` is one of ``"begin"``, ``"end"``, ``"single"``
        (reference: veles/logger.py:264-289).
        """
        if kind not in ("begin", "end", "single"):
            raise ValueError("kind must be begin|end|single, got %r" % kind)
        if _event_file is None and not _event_hooks:
            return
        record = {
            "session": session_id,
            "instance": type(self).__name__,
            "name": name,
            "kind": kind,
            "time": time.time(),
            "iso": datetime.datetime.now().isoformat(),
        }
        record.update(attrs)
        with _event_lock:
            if _event_file is not None:
                _event_file.write(json.dumps(record, default=repr) + "\n")
                _event_file.flush()
        for hook in list(_event_hooks):
            try:
                hook(record)
            except Exception:
                pass  # observers must never break the traced code
