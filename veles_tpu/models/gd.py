"""Gradient-descent units for the fully-connected family.

Znicz-equivalent gd / gd_tanh / gd_relu / gd_sigmoid / gd_sm.  The whole
backward pass of a layer — activation derivative, err_input propagation,
weight/bias gradients with L1/L2 regularization, and the solver update —
is ONE jitted XLA call (the reference ran 3-4 separate kernels:
err_y_update, weights_update, bias_update, err_h_update).

Activation derivatives are expressed in terms of the forward OUTPUT y
(not the pre-activation), exactly as the reference kernels did, so no
extra activation state is stored.
"""

from veles_tpu.models.nn_units import GradientDescentBase

__all__ = ["GradientDescent", "GDTanh", "GDRELU", "GDStrictRELU",
           "GDSigmoid", "GDSoftmax"]


class GradientDescent(GradientDescentBase):
    """Backward for linear All2All."""

    MAPPING = "all2all"

    @staticmethod
    def _activation_grad(y, err):
        return err

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input):
        import jax.numpy as jnp
        W = state["weights"]
        x2 = x.reshape(x.shape[0], -1)
        err = cls._activation_grad(y, err_output)
        err = err.astype(jnp.float32)

        err_input = None
        if need_err_input:
            err_input = jnp.dot(
                err, W.T, preferred_element_type=jnp.float32
            ).astype(x.dtype).reshape(x.shape)

        grad_w = jnp.dot(x2.T.astype(jnp.float32), err,
                         preferred_element_type=jnp.float32)
        grad_w = GradientDescentBase.regularized(
            grad_w, W, hyper["weights_decay"], hyper["l1_vs_l2"])
        new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
            solver, W, grad_w.astype(W.dtype), state["accum_weights"],
            state["accum2_weights"], hyper["learning_rate"],
            hyper["gradient_moment"], hyper["adadelta_rho"],
            hyper["solver_epsilon"])
        new_state = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w}

        grad_b = None
        if include_bias:
            b = state["bias"]
            grad_b = err.sum(axis=0)
            grad_b = GradientDescentBase.regularized(
                grad_b, b, hyper["weights_decay_bias"], hyper["l1_vs_l2"])
            new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                solver, b, grad_b.astype(b.dtype), state["accum_bias"],
                state["accum2_bias"], hyper["learning_rate_bias"],
                hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            new_state.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
        # numerics guard (docs/health.md): a non-finite gradient means
        # this update is SKIPPED — params and solver state keep their
        # pre-step values; the "skipped" flag rides the returned dict
        new_state = GradientDescentBase.finite_guard(
            state, new_state, grad_w, grad_b)
        return err_input, new_state


class GDSoftmax(GradientDescent):
    """The evaluator already produced d(CE+softmax)/dz; pass through."""

    MAPPING = "softmax"


class GDTanh(GradientDescent):
    """y = 1.7159*tanh(2/3 x)  =>  dy/dx = (B/A)*(A^2 - y^2)."""

    MAPPING = "all2all_tanh"

    @staticmethod
    def _activation_grad(y, err):
        from veles_tpu.models.all2all import All2AllTanh
        a, b = All2AllTanh.A, All2AllTanh.B
        return err * ((b / a) * (a * a - y * y))


class GDRELU(GradientDescent):
    """y = log(1+exp(x))  =>  dy/dx = 1 - exp(-y)."""

    MAPPING = "all2all_relu"

    @staticmethod
    def _activation_grad(y, err):
        import jax.numpy as jnp
        return err * (1.0 - jnp.exp(-y))


class GDStrictRELU(GradientDescent):
    """y = max(x, 0)  =>  dy/dx = [y > 0]."""

    MAPPING = "all2all_str"

    @staticmethod
    def _activation_grad(y, err):
        return err * (y > 0)


class GDSigmoid(GradientDescent):
    """y = sigmoid(x)  =>  dy/dx = y*(1-y)."""

    MAPPING = "all2all_sigmoid"

    @staticmethod
    def _activation_grad(y, err):
        return err * (y * (1.0 - y))
