"""Standard NN training workflow wiring.

Znicz-equivalent standard_workflow.StandardWorkflow: builds the classic
loop  repeater -> loader -> forwards -> evaluator -> decision -> gds ->
repeater  from a declarative ``layers`` list, with the stop path
decision.complete -> end_point.

A layer spec is a dict: {"type": "all2all_tanh",
"output_sample_shape": 100, ...hyperparameters...}; forward and GD
classes are looked up by their shared MAPPING name, mirroring the
reference's MappedUnitRegistry factories.
"""

from veles_tpu.models import all2all, gd as gd_module
from veles_tpu.models.decision import DecisionGD, DecisionMSE
from veles_tpu.models.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.plumbing import Repeater
from veles_tpu.workflow import Workflow

__all__ = ["StandardWorkflow", "forward_mapping", "gd_mapping"]


def _build_mapping(module, base):
    mapping = {}
    for name in dir(module):
        cls = getattr(module, name)
        if isinstance(cls, type) and issubclass(cls, base) and \
                getattr(cls, "MAPPING", None):
            mapping[cls.MAPPING] = cls
    return mapping


def forward_mapping():
    from veles_tpu.models import (
        activation, conv, deconv, dropout, pooling, rnn, transformer)
    from veles_tpu.models.nn_units import ForwardBase
    mapping = {}
    for module in (all2all, conv, pooling, dropout, activation, deconv,
                   rnn, transformer):
        mapping.update(_build_mapping(module, ForwardBase))
    return mapping


def gd_mapping():
    from veles_tpu.models import (
        activation, deconv, dropout, gd_conv, gd_pooling, rnn,
        transformer)
    from veles_tpu.models.nn_units import GradientDescentBase
    mapping = {}
    for module in (gd_module, gd_conv, gd_pooling, dropout, activation,
                   deconv, rnn, transformer):
        mapping.update(_build_mapping(module, GradientDescentBase))
    return mapping


class StandardWorkflow(Workflow):
    """loader_factory(workflow) -> Loader; layers: list of layer specs.

    kwargs: loss ("softmax" | "mse"), decision_config, loader_config
    passed through to the respective units.
    """

    hide_from_registry = True

    def __init__(self, workflow, layers, loader_factory, **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        self.layers_config = layers
        self.loss = kwargs.get("loss", "softmax")
        decision_config = kwargs.get("decision_config", {})

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader_factory(self)
        self.loader.link_from(self.repeater)

        # forwards
        fmap = forward_mapping()
        self.forwards = []
        src_unit, src_attr = self.loader, "minibatch_data"
        for spec in layers:
            spec = dict(spec)
            ltype = spec.pop("type")
            unit = fmap[ltype](self, **spec)
            unit.link_from(self.forwards[-1] if self.forwards
                           else self.loader)
            unit.link_attrs(src_unit, ("input", src_attr))
            if "minibatch_class" in unit._demanded:  # dropout et al.
                unit.link_attrs(self.loader, "minibatch_class")
            self.forwards.append(unit)
            src_unit, src_attr = unit, "output"

        # evaluator
        if self.loss == "softmax":
            self.evaluator = EvaluatorSoftmax(self)
            self.evaluator.link_attrs(self.loader,
                                      ("labels", "minibatch_labels"))
        elif self.loss == "mse":
            self.evaluator = EvaluatorMSE(self)
            self.evaluator.link_attrs(self.loader,
                                      ("target", "minibatch_targets"))
        else:
            raise ValueError("unknown loss %r" % self.loss)
        self.evaluator.link_from(self.forwards[-1])
        self.evaluator.link_attrs(self.forwards[-1], "output")
        self.evaluator.link_attrs(self.loader,
                                  ("batch_size", "minibatch_size"))

        # decision
        decision_cls = DecisionGD if self.loss == "softmax" else DecisionMSE
        self.decision = decision_cls(self, **decision_config)
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch", "epoch_ended",
            "epoch_number", "class_lengths")
        self.decision.evaluator = self.evaluator

        # gradient descent chain, last layer first
        gmap = gd_mapping()
        self.gds = [None] * len(layers)
        prev_gd = None
        for i in reversed(range(len(layers))):
            spec = dict(layers[i])
            ltype = spec.pop("type")
            spec.pop("output_sample_shape", None)
            spec.pop("output_shape", None)
            unit = gmap[ltype](self, need_err_input=(i > 0), **spec)
            fwd = self.forwards[i]
            unit.link_attrs(fwd, "input", "output", "weights", "bias")
            if "mask" in unit._demanded:  # dropout backward
                unit.link_attrs(fwd, "mask")
            if prev_gd is None:
                unit.link_from(self.decision)
                unit.link_attrs(self.evaluator, "err_output")
            else:
                unit.link_from(prev_gd)
                unit.link_attrs(prev_gd, ("err_output", "err_input"))
            # completion SKIPS the chain instead of blocking it: the
            # final cycle must still propagate through gds[0] to the
            # snapshotter (final improved checkpoint) and on to
            # end_point.  EVERY gd carries the complete term — if only
            # the first one did, an epoch ending on a TRAIN minibatch
            # (no-validation workflows) would skip-propagate the last
            # gd but RUN the rest against its stale err_input
            unit.gate_skip = self.decision.gd_skip | \
                self.decision.complete
            self.gds[i] = unit
            prev_gd = unit

        # the decision's divergence watchdog reads the gds' lazy skip
        # counters (the fused path rewires this to the trainer)
        self.decision.health_sources = [gd for gd in self.gds
                                        if gd is not None]
        #: LR multiplier applied by each divergence rollback
        self.divergence_lr_backoff = kwargs.get(
            "divergence_lr_backoff", 0.5)

        # close the loop and the exit path
        self.repeater.link_from(self.gds[0])
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete

        # standard snapshotting: when the config names a snapshot dir
        # (e.g. the CLI's --snapshot-dir), every improvement checkpoints
        # automatically — the reference wired a Snapshotter into every
        # standard workflow; restore with -w <file>
        self.snapshotter = None
        from veles_tpu.config import root as _root
        if _root.common.snapshot.get("dir"):
            from veles_tpu.snapshotter import Snapshotter
            self.snapshotter = Snapshotter(
                self, prefix=type(self).__name__)
            # The snapshotter runs at the QUIESCENT point of the
            # minibatch cycle — after the last gd applied its update,
            # before the repeater serves the next minibatch — so every
            # snapshot is an exact resume point (weights, loader
            # offsets, prng, decision accumulators all consistent).
            # Linking it from the decision instead would pickle TORN
            # state: the worklist interleaves it with the gd chain, so
            # some layers would carry the current minibatch's update
            # and some would not.
            self.snapshotter.link_from(self.gds[0])
            self.repeater.unlink_from(self.gds[0])
            self.repeater.link_from(self.snapshotter)
            # fire once per improved epoch: improved alone stays True
            # through the whole following epoch (it resets only at the
            # next judge-class end), which would export every minibatch
            self.snapshotter.gate_skip = ~(self.decision.improved &
                                           self.loader.epoch_ended)
            # the exit gate also waits on the snapshotter (reference
            # topology decision -> snapshotter -> end): otherwise the
            # worklist is abandoned at end_point before a queued
            # final-epoch snapshot runs
            self.end_point.link_from(self.snapshotter)

    def fuse(self, **kwargs):
        """Swap the per-unit chain for the single-dispatch fused train
        step (veles_tpu.models.fused); call before initialize().
        ``pipeline=True`` additionally overlaps host fill + H2D of the
        next minibatch with the running step."""
        from veles_tpu.models.fused import fuse_standard_workflow
        return fuse_standard_workflow(self, **kwargs)

    # -- numerics health: divergence recovery (docs/health.md) --------------

    def adopt_model_state(self, donor):
        """Copy the model state (forward params + gd solver
        accumulators) out of ``donor`` — a workflow unpickled from a
        verified snapshot — into THIS workflow's live Arrays.  Host
        copies become authoritative; device uploads happen lazily at
        the next access, and a fused trainer re-extracts its state on
        its next compile."""
        import numpy
        if len(donor.forwards) != len(self.forwards):
            raise ValueError(
                "snapshot workflow has %d forward layers, live one has "
                "%d — refusing to adopt" % (len(donor.forwards),
                                            len(self.forwards)))

        def copy_arrays(src_unit, dst_unit, names):
            for name in names:
                src = getattr(src_unit, name, None)
                dst = getattr(dst_unit, name, None)
                if src is None or dst is None or not src or not dst:
                    continue
                src.map_read()
                dst.map_invalidate()
                dst.mem = numpy.array(src.mem)

        for live, old in zip(self.forwards, donor.forwards):
            copy_arrays(old, live, ("weights", "bias"))
        for live, old in zip(self.gds, donor.gds):
            if live is None or old is None:
                continue
            copy_arrays(old, live, ("accum_weights", "accum_bias",
                                    "accum2_weights", "accum2_bias"))

    def on_divergence(self, reason):
        """The decision watchdog's recovery hook: roll the model back
        to the last verified snapshot, back off every layer's learning
        rate, reseed the fused dropout stream, and clear the health
        counters so the watchdog starts a fresh observation window.
        Without a snapshotter (or with the rollback budget spent) this
        raises — surviving bad math silently is not an option."""
        from veles_tpu.health import DivergenceError
        if self.snapshotter is None:
            raise DivergenceError(
                "training diverged (%s) and no snapshotter is attached "
                "— nothing to roll back to" % reason)
        path = self.snapshotter.rollback(reason=reason)
        backoff = self.divergence_lr_backoff
        for gd in self.gds:
            if gd is None:
                continue
            gd.learning_rate *= backoff
            gd.learning_rate_bias *= backoff
            gd.reset_health_counters()
        trainer = getattr(self, "fused_trainer", None)
        if trainer is not None:
            # recompiles against the restored Arrays and the
            # backed-off hyperparameters, with a fresh dropout stream
            trainer.reset_after_rollback(self.snapshotter.rollbacks)
        self.decision.reset_divergence()
        self.warning(
            "divergence recovery: restored %s, learning rates *= %g "
            "(rollback %d/%d); training continues", path, backoff,
            self.snapshotter.rollbacks, self.snapshotter.rollback_budget)

    def link_plotters(self):
        """Attach the standard plotter set (reference Znicz standard
        workflow behavior): per-class error curves, the confusion
        matrix, and per-layer weight histograms, all running after the
        decision each minibatch and publishing to the launcher's
        graphics server when one is attached."""
        from veles_tpu.plotting_units import (
            AccumulatingPlotter, MatrixPlotter, MultiHistogram)
        self.plotters = []
        decision = self.decision
        for cls_idx, cls_name in ((1, "validation"), (2, "train")):
            plot = AccumulatingPlotter(
                self, label="%s error %%" % cls_name)
            plot.input = decision

            def capture(plot=plot, idx=cls_idx):
                # one point per finished epoch
                if not bool(decision.epoch_ended):
                    return
                value = decision.epoch_metrics[idx]
                if value is not None:
                    plot.values.append(float(value))
            plot.capture = capture
            plot.link_from(self.decision)
            self.plotters.append(plot)
        if hasattr(self.evaluator, "confusion_matrix"):
            conf = MatrixPlotter(self)
            conf.input = self.evaluator.confusion_matrix
            conf.link_from(self.decision)
            self.plotters.append(conf)
        hist = MultiHistogram(self)
        hist.inputs = [f.weights for f in self.forwards
                       if f.weights is not None and hasattr(
                           f.weights, "map_read")]
        hist.link_from(self.decision)
        self.plotters.append(hist)
        return self.plotters

    def initialize(self, device=None, **kwargs):
        if self.workflow_mode == "slave":
            # one job = one pass: a slave must not loop the repeater; the
            # drained worklist ends the pass (master drives iteration)
            self.repeater.unlink_from(
                self.gds[0] if self.snapshotter is None
                else self.snapshotter)
        elif self.workflow_mode == "standalone":
            # standalone ONLY: in distributed runs master and slaves
            # exchange unit state by zipping their unit lists
            # positionally (workflow.py generate_data_for_slave /
            # apply_data_from_slave), so a fused master would
            # desynchronize from its unfused slaves
            device = self._maybe_auto_fuse(device)
        return super(StandardWorkflow, self).initialize(
            device=device, **kwargs)

    def _maybe_auto_fuse(self, device):
        """Fuse automatically when the resolved device is a TPU.

        The per-unit dispatch loop is the DEBUG path on TPU — measured
        8-25x slower than the fused step over a tunneled chip
        (QUALITY.json results_tpu history), so the product default is
        the fast path; ``--no-fuse`` / VELES_AUTO_FUSE=0 opts out.
        Distributed modes never auto-fuse — master and slaves exchange
        state by zipping unit lists positionally, so both sides must
        keep the same unit graph — and a workflow the compiler cannot
        plan falls back to the per-unit path with a warning instead of
        failing.
        Returns the RESOLVED device so initialize passes it down
        without a second backend auto-selection."""
        from veles_tpu.backends import Device
        from veles_tpu.config import root
        if device is None or isinstance(device, str):
            device = Device(backend=device)
        if (getattr(self, "fused_trainer", None) is None
                and root.common.engine.get("auto_fuse", True)
                and device.BACKEND == "tpu"):
            try:
                from veles_tpu.compiler import workflow_plan
                workflow_plan(self)  # structural check only
            except Exception as exc:
                self.warning(
                    "auto-fuse skipped (workflow not fusable: %s); "
                    "running the per-unit debug path on TPU", exc)
            else:
                self.info("TPU device: fusing the train loop into one "
                          "dispatch per minibatch (--no-fuse to keep "
                          "the per-unit debug path)")
                # async input pipeline rides along by default on real
                # hardware: host fill + H2D of minibatch k+1 overlap
                # step k (VELES_PIPELINE_INPUT=0 / engine.pipeline_input
                # opts out; the trainer falls back to the synchronous
                # serve automatically where pipelining is unsupported)
                self.fuse(pipeline=root.common.engine.get(
                    "pipeline_input", True))
        return device
