"""Fully-connected ("all-to-all") forward units.

Znicz-equivalent all2all family (docs/source/manualrst_veles_algorithms
.rst:18-40): linear, scaled-tanh, RELU (softplus form), StrictRELU,
sigmoid, and softmax output layers.

Weights are stored (fan_in, fan_out) so ``x @ W`` feeds the MXU directly
(the reference stored the transpose and paid a transposed gemm;
weights_transposed is therefore gone).  The matmul accumulates in f32 via
``preferred_element_type`` regardless of input dtype — on TPU this is the
precision-level guarantee the reference bought with Kahan summation
(SURVEY.md section 7 hard part 7).
"""

import numpy

from veles_tpu.memory import Array
from veles_tpu.models.nn_units import ForwardBase

__all__ = ["All2All", "All2AllTanh", "All2AllRELU", "All2AllStrictRELU",
           "All2AllSigmoid", "All2AllSoftmax"]


class All2All(ForwardBase):
    """y = activation(x @ W + b); base class is linear."""

    MAPPING = "all2all"

    def __init__(self, workflow, **kwargs):
        super(All2All, self).__init__(workflow, **kwargs)
        shape = kwargs.get("output_sample_shape", kwargs.get("output_shape"))
        if shape is None:
            raise ValueError("output_sample_shape is required")
        self.output_sample_shape = (
            (int(shape),) if isinstance(shape, (int, numpy.integer))
            else tuple(shape))

    @property
    def output_size(self):
        return int(numpy.prod(self.output_sample_shape))

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            # input shape not known yet -> workflow re-queues us
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        fan_in = self.input.sample_size
        if not self.output:
            self.output.mem = numpy.zeros(
                (self.input.shape[0], self.output_size), numpy.float32)
        if self.weights:
            return  # restored from snapshot
        weights = numpy.zeros((fan_in, self.output_size), numpy.float32)
        self.fill_array(weights, self.weights_filling, self.weights_stddev,
                        fan_in)
        self.weights.mem = weights
        if self.include_bias:
            bias = numpy.zeros((self.output_size,), numpy.float32)
            self.fill_array(bias, self.bias_filling, self.bias_stddev,
                            fan_in)
            self.bias.mem = bias

    # -- pure math ----------------------------------------------------------

    @staticmethod
    def _activate(z):
        return z

    @classmethod
    def apply(cls, params, x):
        import jax.numpy as jnp
        x2 = x.reshape(x.shape[0], -1)
        z = jnp.dot(x2, params["weights"],
                    preferred_element_type=jnp.float32)
        if params.get("bias") is not None:
            z = z + params["bias"]
        return cls._activate(z).astype(x2.dtype)


class All2AllTanh(All2All):
    """Scaled tanh y = 1.7159*tanh(2/3 x) (LeCun-efficient-backprop form
    used by Znicz)."""

    MAPPING = "all2all_tanh"
    A = 1.7159
    B = 0.6666

    @staticmethod
    def _activate(z):
        import jax.numpy as jnp
        return All2AllTanh.A * jnp.tanh(All2AllTanh.B * z)


class All2AllRELU(All2All):
    """Znicz 'RELU': y = log(1 + exp(x)) (softplus), numerically stable."""

    MAPPING = "all2all_relu"

    @staticmethod
    def _activate(z):
        import jax.numpy as jnp
        return jnp.where(z > 15, z, jnp.log1p(jnp.exp(jnp.minimum(z, 15))))


class All2AllStrictRELU(All2All):
    """y = max(x, 0)."""

    MAPPING = "all2all_str"

    @staticmethod
    def _activate(z):
        import jax.numpy as jnp
        return jnp.maximum(z, 0)


class All2AllSigmoid(All2All):
    """y = 1/(1+exp(-x))."""

    MAPPING = "all2all_sigmoid"

    @staticmethod
    def _activate(z):
        import jax
        return jax.nn.sigmoid(z)


class All2AllSoftmax(All2All):
    """Softmax output layer; also exposes ``max_idx`` (argmax per sample),
    which Znicz computed in-kernel for the evaluator."""

    MAPPING = "softmax"

    def __init__(self, workflow, **kwargs):
        super(All2AllSoftmax, self).__init__(workflow, **kwargs)
        self.max_idx = Array()

    @staticmethod
    def _activate(z):
        import jax
        return jax.nn.softmax(z, axis=-1)

    def _device_run(self):
        import jax
        if self._jit_fn_ is None:
            def fwd(params, x):
                y = All2AllSoftmax.apply(params, x)
                import jax.numpy as jnp
                return y, jnp.argmax(y, axis=-1).astype(jnp.int32)
            self._jit_fn_ = jax.jit(fwd)
        out, max_idx = self._jit_fn_(
                self.params_dict(), self.input.device_array(self.device))
        self.output.set_device_array(out, self.device)
        self.max_idx.set_device_array(max_idx, self.device)

    def _numpy_run(self):
        super(All2AllSoftmax, self)._numpy_run()
        self.max_idx.map_invalidate()
        self.max_idx.mem = numpy.argmax(
            self.output.mem, axis=-1).astype(numpy.int32)
