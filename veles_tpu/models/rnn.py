"""Recurrent layers: vanilla RNN and LSTM.

The reference's RNN/LSTM lived in Znicz and were marked untested
(manualrst_veles_algorithms.rst:113-135).  TPU-first design: the time
loop is ``lax.scan`` (single compiled loop, no Python unrolling), the
input projection for ALL timesteps is one big batched matmul feeding
the MXU, and BPTT comes from ``jax.vjp`` through the scan — no manual
backward kernels.

Input is (B, T, F); output (B, T, H) ("sequence" mode) or (B, H)
(final state, ``return_sequences=False``).
"""

import numpy

from veles_tpu.models.gd import GradientDescent
from veles_tpu.models.nn_units import ForwardBase, GradientDescentBase

__all__ = ["RNN", "LSTM", "GDRNN", "GDLSTM"]


class RecurrentBase(ForwardBase):
    def __init__(self, workflow, **kwargs):
        super(RecurrentBase, self).__init__(workflow, **kwargs)
        self.hidden_size = kwargs["hidden_size"]
        self.return_sequences = kwargs.get("return_sequences", True)

    def static_config(self):
        return {"return_sequences": self.return_sequences}

    #: gates per hidden unit (1 for RNN, 4 for LSTM)
    GATES = 1

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        batch, seq, features = self.input.shape
        h = self.hidden_size
        if not self.output:
            out_shape = (batch, seq, h) if self.return_sequences \
                else (batch, h)
            self.output.mem = numpy.zeros(out_shape, numpy.float32)
        if self.weights:
            return
        g = self.GATES
        # packed: input kernel (F, G*H) then recurrent kernel (H, G*H)
        weights = numpy.zeros((features + h, g * h), numpy.float32)
        self.fill_array(weights, self.weights_filling,
                        self.weights_stddev, features + h)
        self.weights.mem = weights
        if self.include_bias:
            self.bias.mem = numpy.zeros((g * h,), numpy.float32)


class RNN(RecurrentBase):
    """h_t = tanh(x_t Wx + h_{t-1} Wh + b)."""

    MAPPING = "rnn"
    GATES = 1

    @classmethod
    def apply(cls, params, x, *, return_sequences=True):
        import jax.numpy as jnp
        from jax import lax
        W = params["weights"]
        features = x.shape[-1]
        Wx, Wh = W[:features], W[features:]
        h_size = Wh.shape[0]
        b = params.get("bias")
        # one MXU matmul for every timestep's input projection
        xw = jnp.einsum("btf,fh->bth", x, Wx,
                        preferred_element_type=jnp.float32)
        if b is not None:
            xw = xw + b

        def step(h, xw_t):
            h = jnp.tanh(xw_t + jnp.dot(
                h, Wh, preferred_element_type=jnp.float32))
            return h.astype(x.dtype), h.astype(x.dtype)

        h0 = jnp.zeros((x.shape[0], h_size), x.dtype)
        h_last, hs = lax.scan(step, h0, jnp.swapaxes(xw, 0, 1))
        return (jnp.swapaxes(hs, 0, 1) if return_sequences
                else h_last).astype(x.dtype)


class LSTM(RecurrentBase):
    """Standard LSTM (gates i, f, g, o packed on the last axis)."""

    MAPPING = "lstm"
    GATES = 4

    @classmethod
    def apply(cls, params, x, *, return_sequences=True):
        import jax
        import jax.numpy as jnp
        from jax import lax
        W = params["weights"]
        features = x.shape[-1]
        Wx, Wh = W[:features], W[features:]
        h_size = Wh.shape[0]
        b = params.get("bias")
        xw = jnp.einsum("btf,fh->bth", x, Wx,
                        preferred_element_type=jnp.float32)
        if b is not None:
            xw = xw + b

        def step(carry, xw_t):
            h, c = carry
            z = xw_t + jnp.dot(h, Wh,
                               preferred_element_type=jnp.float32)
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            h = h.astype(x.dtype)
            return (h, c.astype(x.dtype)), h

        h0 = jnp.zeros((x.shape[0], h_size), x.dtype)
        (h_last, _), hs = lax.scan(
            step, (h0, h0), jnp.swapaxes(xw, 0, 1))
        return (jnp.swapaxes(hs, 0, 1) if return_sequences
                else h_last).astype(x.dtype)


class _GDRecurrent(GradientDescent):
    MAPPING = None  # abstract: do not register (would shadow all2all)
    FORWARD_CLS = None

    def __init__(self, workflow, **kwargs):
        super(_GDRecurrent, self).__init__(workflow, **kwargs)
        self.return_sequences = kwargs.get("return_sequences", True)

    def backward_static(self):
        return {"return_sequences": self.return_sequences}

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, return_sequences=True):
        import jax
        import jax.numpy as jnp
        W = state["weights"]
        b = state["bias"] if include_bias else None

        def fwd(W_, b_, x_):
            return cls.FORWARD_CLS.apply(
                {"weights": W_, "bias": b_}, x_,
                return_sequences=return_sequences)

        _, vjp = jax.vjp(fwd, W, b, x)
        grad_w, grad_b, err_input = vjp(err_output.astype(y.dtype))
        if not need_err_input:
            err_input = None
        grad_w = GradientDescentBase.regularized(
            grad_w.astype(jnp.float32), W, hyper["weights_decay"],
            hyper["l1_vs_l2"])
        new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
            solver, W, grad_w.astype(W.dtype), state["accum_weights"],
            state["accum2_weights"], hyper["learning_rate"],
            hyper["gradient_moment"], hyper["adadelta_rho"],
            hyper["solver_epsilon"])
        new_state = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w}
        if include_bias and grad_b is not None:
            new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                solver, b, grad_b.astype(b.dtype), state["accum_bias"],
                state["accum2_bias"], hyper["learning_rate_bias"],
                hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            new_state.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
        # numerics guard: skip the update on non-finite gradients
        # (docs/health.md; same semantics as the fully-connected family)
        new_state = GradientDescentBase.finite_guard(
            state, new_state, grad_w,
            grad_b if include_bias else None)
        return err_input, new_state


class GDRNN(_GDRecurrent):
    MAPPING = "rnn"
    FORWARD_CLS = RNN


class GDLSTM(_GDRecurrent):
    MAPPING = "lstm"
    FORWARD_CLS = LSTM
