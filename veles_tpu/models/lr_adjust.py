"""Learning-rate adjusting policies + weights rollback.

Znicz-equivalent lr_adjust / rollback (manualrst_veles_algorithms.rst:
"learning-rate adjusting & rollback").

Policies mirror Caffe-era Znicz: fixed, step_exp (gamma^floor(it/step)),
exp (gamma^it), inv (1/(1+gamma*it)^power), arbitrary (user fn).
The per-unit GD path passes hyperparameters as *traced* scalars, so
adjusting the learning rate costs NO recompilation; the fused compiler
path bakes hypers statically and recompiles once per change (adjust per
epoch, not per minibatch, when using the fused trainer).
"""

import numpy

from veles_tpu.memory import Array
from veles_tpu.units import Unit

__all__ = ["LearningRateAdjust", "Rollback",
           "fixed_policy", "step_exp_policy", "exp_policy", "inv_policy"]


def fixed_policy(base):
    return lambda it: base


def step_exp_policy(base, gamma, step):
    return lambda it: base * gamma ** (it // step)


def exp_policy(base, gamma):
    return lambda it: base * gamma ** it


def inv_policy(base, gamma, power=1.0):
    return lambda it: base * (1.0 + gamma * it) ** (-power)


class LearningRateAdjust(Unit):
    """Applies (lr_policy, bias_lr_policy) to the linked GD units each
    run; ``it`` counts minibatches (Znicz semantics)."""

    def __init__(self, workflow, **kwargs):
        super(LearningRateAdjust, self).__init__(workflow, **kwargs)
        self.lr_policy = kwargs.get("lr_policy")
        self.bias_lr_policy = kwargs.get("bias_lr_policy", self.lr_policy)
        self.gd_units = []
        self._iteration = 0

    def add_gd_unit(self, *units):
        self.gd_units.extend(units)
        return self

    def run(self):
        self._iteration += 1
        for gd in self.gd_units:
            if self.lr_policy is not None:
                gd.learning_rate = float(self.lr_policy(self._iteration))
            if self.bias_lr_policy is not None:
                gd.learning_rate_bias = float(
                    self.bias_lr_policy(self._iteration))


class Rollback(Unit):
    """Keeps the best parameter snapshot; on ``slip`` (no improvement)
    restores it and rescales the learning rate by ``lr_cut`` until
    ``lr_limit``; improvement refreshes the snapshot.

    Link: ``improved`` from decision, gd units via add_gd_unit.
    """

    def __init__(self, workflow, **kwargs):
        super(Rollback, self).__init__(workflow, **kwargs)
        self.lr_cut = kwargs.get("lr_cut", 0.5)
        self.lr_limit = kwargs.get("lr_limit", 1e-8)
        self.improved = None  # linked Bool from decision
        self.gd_units = []
        self._best = {}
        self.demand("improved")

    def add_gd_unit(self, *units):
        self.gd_units.extend(units)
        return self

    def _param_arrays(self, gd):
        out = []
        for name in ("weights", "bias", "accum_weights", "accum_bias",
                     "accum2_weights", "accum2_bias"):
            arr = getattr(gd, name, None)
            if isinstance(arr, Array) and arr:
                out.append((name, arr))
        return out

    def run(self):
        if bool(self.improved) or not self._best:
            for i, gd in enumerate(self.gd_units):
                for name, arr in self._param_arrays(gd):
                    arr.map_read()
                    self._best[(i, name)] = numpy.array(arr.mem)
            return
        # slip: restore best params, cut the learning rate
        for i, gd in enumerate(self.gd_units):
            for name, arr in self._param_arrays(gd):
                saved = self._best.get((i, name))
                if saved is not None:
                    arr.map_invalidate()
                    arr.mem = numpy.array(saved)
            if gd.learning_rate * self.lr_cut >= self.lr_limit:
                gd.learning_rate *= self.lr_cut
                gd.learning_rate_bias *= self.lr_cut
