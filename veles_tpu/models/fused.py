"""FusedTrainer — run the whole forward+loss+backward+update chain as
ONE jitted dispatch inside a standard workflow.

This is the performance path promised by veles_tpu.compiler: the unit
graph keeps orchestrating (loader serves minibatches, decision stops
training, snapshotter checkpoints), but between loader and decision a
single FusedTrainer replaces forwards + evaluator + GD units.  Per
minibatch there is exactly one XLA computation and zero host transfers
besides the scalar metrics the decision unit needs.

``StandardWorkflow.fuse()`` rewires an existing workflow in place, so
every already-written config gains the fused path without changes.
"""

import numpy

from veles_tpu.backends import NumpyDevice
from veles_tpu.loader.base import TRAIN
from veles_tpu.units import Unit

__all__ = ["FusedTrainer", "fuse_standard_workflow"]


class FusedTrainer(Unit):
    """Wraps compiler.build_train_step over a StandardWorkflow's
    layers; exposes evaluator-compatible metrics (n_err / mse_sum) so
    the decision unit works unchanged."""

    def __init__(self, workflow, sw, **kwargs):
        super(FusedTrainer, self).__init__(workflow, **kwargs)
        self.sw = sw
        self.loss = sw.loss
        self.device = None
        self._step_fn = None
        self._state = None
        self._dropout_base_key = kwargs.get("dropout_seed", 0)
        self._iteration = 0
        # evaluator-compatible surface for DecisionGD / DecisionMSE
        self.n_err = 0
        self.mse_sum = 0.0
        self.n_samples = 0
        self.last_loss = None

    def initialize(self, device=None, **kwargs):
        self.device = device
        super(FusedTrainer, self).initialize(**kwargs)
        return True

    def _compile(self):
        import jax

        from veles_tpu.compiler import (
            build_train_step, extract_state, workflow_plan)
        plans = workflow_plan(self.sw)
        self._plans = plans
        self._step_fn = build_train_step(
            plans, loss=self.loss, donate=True)
        self._forward_only = jax.jit(
            __import__("veles_tpu.compiler", fromlist=["x"])
            .build_forward(plans))
        self._state = extract_state(self.sw)
        self._has_dropout = any(
            p.static.get("dropout_ratio") is not None for p in plans)

    def sync(self):
        """Write the fused state back into the unit Arrays (on demand:
        snapshots, plotting, package export)."""
        from veles_tpu.compiler import adopt_state
        if self._state is not None:
            adopt_state(self.sw, self._state, self.device)

    _sync_state_to_units = sync

    def run(self):
        import jax

        if self._step_fn is None:
            self._compile()
        loader = self.sw.loader
        x = loader.minibatch_data.device_array(self.device)
        if self.loss == "softmax":
            target = loader.minibatch_labels.device_array(self.device)
        else:
            target = loader.minibatch_targets.device_array(self.device)
        batch_size = numpy.float32(loader.minibatch_size)

        if loader.minibatch_class == TRAIN:
            self._iteration += 1
            key = None
            if self._has_dropout:
                key = jax.random.fold_in(
                    jax.random.PRNGKey(self._dropout_base_key),
                    self._iteration)
            if key is not None:
                self._state, metrics = self._step_fn(
                    self._state, x, target, batch_size, key)
            else:
                self._state, metrics = self._step_fn(
                    self._state, x, target, batch_size)
            self.last_loss = float(metrics["loss"])
            self.n_err = int(metrics["n_err"])
            # mse_sum from the step's aux metric matches EvaluatorMSE's
            # definition (per-feature mean, summed over samples); the
            # scalar loss is SSE/batch over ALL elements and would
            # inflate epoch RMSE by sqrt(num_features)
            self.mse_sum = float(metrics.get(
                "mse_sum", self.last_loss * float(batch_size)))
        else:
            # eval minibatch: forward only, metrics on device
            params = [{"weights": s["weights"], "bias": s["bias"]}
                      for s in self._state]
            out = self._forward_only(params, x)
            if self.loss == "softmax":
                import jax.numpy as jnp
                labels = target
                valid = numpy.asarray(labels) >= 0
                pred = numpy.asarray(jnp.argmax(out, axis=-1))
                self.n_err = int(
                    ((pred != numpy.asarray(labels)) & valid).sum())
            else:
                diff = (numpy.asarray(out).reshape(out.shape[0], -1) -
                        numpy.asarray(target).reshape(out.shape[0], -1))
                mask = numpy.arange(out.shape[0]) < int(batch_size)
                self.mse_sum = float(
                    (diff[mask] ** 2).mean(axis=1).sum())
        self.n_samples = int(batch_size)

    def __getstate__(self):
        # state lives in the unit Arrays for snapshots
        self._sync_state_to_units()
        state = super(FusedTrainer, self).__getstate__()
        state["_step_fn"] = None
        state["_state"] = None
        state["_forward_only"] = None
        state["_plans"] = None
        return state


def fuse_standard_workflow(sw, dropout_seed=0):
    """Rewire a StandardWorkflow: loader -> FusedTrainer -> decision.

    The forward/GD units stay constructed (they own the param Arrays and
    the snapshot format) but leave the control graph.
    """
    trainer = FusedTrainer(sw, sw, dropout_seed=dropout_seed)
    # detach the old chain from control flow
    for unit in sw.forwards + [sw.evaluator] + sw.gds:
        unit.unlink_all()
    trainer.link_from(sw.loader)
    sw.decision.link_from(trainer)
    # decision reads its metrics from the trainer now
    sw.decision.evaluator = trainer
    sw.repeater.link_from(sw.decision)
    sw.end_point.link_from(sw.decision)
    sw.end_point.gate_block = ~sw.decision.complete
    sw.fused_trainer = trainer
    return trainer
