"""FusedTrainer — run the whole forward+loss+backward+update chain as
ONE jitted dispatch inside a standard workflow.

This is the performance path promised by veles_tpu.compiler: the unit
graph keeps orchestrating (loader serves minibatches, decision stops
training, snapshotter checkpoints), but between loader and decision a
single FusedTrainer replaces forwards + evaluator + GD units.  Per
minibatch there is exactly one XLA computation and zero host transfers
besides the scalar metrics the decision unit needs.

``StandardWorkflow.fuse()`` rewires an existing workflow in place, so
every already-written config gains the fused path without changes.
"""

import time

import numpy

from veles_tpu import chaos
from veles_tpu.loader.base import TRAIN
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.profile import profiler_step
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.units import Unit

__all__ = ["FusedTrainer", "fuse_standard_workflow"]


class FusedTrainer(Unit):
    """Wraps compiler.build_train_step over a StandardWorkflow's
    layers; exposes evaluator-compatible metrics (n_err / mse_sum) so
    the decision unit works unchanged."""

    def __init__(self, workflow, sw, **kwargs):
        super(FusedTrainer, self).__init__(workflow, **kwargs)
        self.sw = sw
        self.loss = sw.loss
        self.device = None
        self._step_fn = None
        self._state = None
        self._dropout_seed = kwargs.get("dropout_seed", 0)
        self._dropout_base_key = self._dropout_seed
        self._iteration = 0
        # numerics health (docs/health.md): per-step skip flags stay
        # lazy device scalars; the decision unit syncs them once per
        # finished class, never on the hot path
        self.skip_count = 0
        self.consecutive_skips = 0
        self.last_step_finite = True
        self.grad_norm = None
        #: async input pipeline knob (pipeline_input.Prefetcher): serve
        #: minibatch k+1 (host fill + async H2D) while step k runs
        self.pipeline = kwargs.get("pipeline", False)
        self.pipeline_depth = kwargs.get("pipeline_depth", 1)
        self._prefetcher = None
        #: SPMD data plane (docs/distributed.md): with a mesh, the
        #: step compiles as shard_map over ``data_axis`` and the
        #: gradient merge is the bucketed overlapped all-reduce
        #: (parallel/bucketed.py) instead of a flat pjit psum
        self.mesh = kwargs.get("mesh")
        self.data_axis = kwargs.get("data_axis", "data")
        self.grad_bucket_mb = kwargs.get("grad_bucket_mb")
        #: "bf16" halves gradient wire bytes; auto-falls back to f32
        #: when the health watchdog sees a skipped (non-finite) step
        self.grad_compress = kwargs.get("grad_compress")
        # evaluator-compatible surface for DecisionGD / DecisionMSE
        self.n_err = 0
        self.mse_sum = 0.0
        self.n_samples = 0
        self.last_loss = None

    def init_unpickled(self):
        super(FusedTrainer, self).init_unpickled()
        # telemetry handles (trailing underscore: transient, re-created
        # after unpickling).  The step histograms measure the graph
        # thread's dispatch wall time — the honest steady-state step
        # time under device backpressure, with zero extra host syncs
        self._m_train_step_ = _registry.histogram("step.train_s")
        self._m_eval_step_ = _registry.histogram("step.eval_s")
        self._m_steps_ = _registry.counter("train.steps")
        self._m_samples_ = _registry.counter("train.samples")
        #: XLA cost-model FLOPs of one compiled step (None until the
        #: first step ran; 0.0 when cost analysis is unavailable)
        self._step_flops_ = None
        #: comm receipt state (SPMD mode): published once, at the
        #: first post-compile step whose wall time is clean
        self._comm_published_ = False
        #: skip count already attributed at the last health sync —
        #: growth while compression is on triggers the f32 fallback
        self._compress_skips_seen_ = 0

    def _restore_mesh(self):
        """Rebuild the SPMD mesh after unpickling (a Mesh holds live
        device handles, so snapshots carry its AXES instead): same
        shape when the host still has the devices; a single-axis
        (pure-DP) mesh re-spans whatever devices exist now; a
        multi-axis shape that no longer fits fails LOUDLY rather than
        silently degrading to a single-device step."""
        axes = getattr(self, "_spmd_axes_", None)
        if not axes or self.mesh is not None:
            return
        from veles_tpu.parallel import auto_mesh, make_mesh
        try:
            self.mesh = make_mesh(dict(axes))
        except ValueError as exc:
            if len(axes) == 1:
                self.mesh = auto_mesh(next(iter(axes)))
                self.warning(
                    "resumed SPMD mesh %s does not fit this host "
                    "(%s); re-spanning the data axis over %d devices",
                    dict(axes), exc,
                    self.mesh.shape[next(iter(axes))])
            else:
                raise ValueError(
                    "cannot rebuild the resumed SPMD mesh %s on this "
                    "host: %s — re-fuse with an explicit mesh"
                    % (dict(axes), exc))

    def initialize(self, device=None, **kwargs):
        self.device = device
        self._restore_mesh()
        if (self.pipeline and self._prefetcher is None
                and self.mesh is None
                and device is not None
                and getattr(device, "exists", False)
                and self.sw.workflow_mode == "standalone"):
            from veles_tpu.pipeline_input import Prefetcher
            self._prefetcher = Prefetcher(
                self.sw.loader, device,
                depth=self.pipeline_depth).attach()
        super(FusedTrainer, self).initialize(**kwargs)
        return True

    def _compile(self):
        import jax

        from veles_tpu.compiler import (
            build_forward, build_train_step, extract_state,
            step_compiler_options, workflow_plan)
        from veles_tpu.observe import xla_introspect as _xla

        # install the jax.monitoring compile listener BEFORE building,
        # so this compile (and any later recompile storm) is counted
        _xla.ensure_installed()
        plans = workflow_plan(self.sw)
        self._plans = plans
        # the step that triggers a (re)compile pays the compile in its
        # wall time; the comm receipt must be sized on a CLEAN step,
        # so publication waits two iterations past ANY compile (the
        # bf16->f32 fallback recompiles mid-run)
        self._compiled_at_iter_ = self._iteration
        if self.mesh is not None:
            from veles_tpu.parallel.bucketed import DEFAULT_BUCKET_MB
            bucket_mb = (self.grad_bucket_mb
                         if self.grad_bucket_mb is not None
                         else DEFAULT_BUCKET_MB)
            self._step_fn = build_train_step(
                plans, loss=self.loss, mesh=self.mesh,
                data_axis=self.data_axis, grad_bucket_mb=bucket_mb,
                grad_compress=self.grad_compress, donate=True,
                compiler_options=step_compiler_options())
        else:
            self._step_fn = build_train_step(
                plans, loss=self.loss, donate=True,
                compiler_options=step_compiler_options())
        forward = build_forward(plans)

        # eval metrics fused INTO the forward dispatch: one async call
        # per eval minibatch, no eager ops (each eager op costs a
        # full remote round trip on a tunneled chip)
        import jax.numpy as jnp
        if self.loss == "softmax":
            def eval_metrics(params, x, labels):
                out = forward(params, x)
                valid = labels >= 0
                pred = jnp.argmax(out, axis=-1)
                return ((pred != labels) & valid).sum()
        else:
            def eval_metrics(params, x, target, batch_size):
                out = forward(params, x)
                diff = (out.reshape(out.shape[0], -1) -
                        target.reshape(target.shape[0], -1))
                mask = jnp.arange(out.shape[0]) < batch_size
                return jnp.sum(jnp.mean(diff * diff, axis=1) * mask)
        self._eval_metrics = jax.jit(eval_metrics)
        self._state = extract_state(self.sw)
        if self.mesh is not None:
            # replicate over the WHOLE mesh (copies — the unit Arrays
            # stay authoritative on host); eval reuses these replicated
            # params, so its jit runs on the same device set
            from veles_tpu.parallel.api import replicate
            self._state = replicate(self.mesh, self._state)
        self._has_dropout = any(
            p.static.get("dropout_ratio") is not None for p in plans)
        # recompile detection (docs/observability.md): each of these
        # should settle on a handful of signatures — growth past that
        # is the recompile storm the watcher warns about
        _xla.watch(self._step_fn, "fused.step")
        _xla.watch(self._eval_metrics, "fused.eval")

    def _publish_step_flops(self, x, target, batch_size, key, poisons):
        """XLA's own cost model for ONE fused step, from abstract
        avals of the arguments the step was just called with — the
        same number bench.py reports offline, now feeding the live
        ``mfu_pct`` gauge.  One-time at the first train step, entirely
        off the per-step path afterwards; any failure publishes 0.0 so
        the attempt is never retried per step."""
        import jax

        from veles_tpu.observe import xla_introspect as _xla
        self._step_flops_ = 0.0
        try:
            def aval(leaf):
                if leaf is None:
                    return None
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
                return leaf
            args = [jax.tree.map(aval, self._state,
                                 is_leaf=lambda v: v is None),
                    aval(x), aval(target), aval(batch_size)]
            kwargs = {k: aval(v) for k, v in poisons.items()}
            if key is not None or poisons:
                args.append(aval(key))
            # pre-compile estimate ONLY: a .compile() fallback would
            # synchronously rebuild a step that can take minutes on a
            # real chip and log a phantom compile.count entry — on a
            # jax without Lowered.cost_analysis we just skip FLOPs
            # publication (mfu stays null) instead
            cost = self._step_fn.lower(*args, **kwargs).cost_analysis()
            flops = self._cost_flops(cost)
            if flops > 0:
                self._step_flops_ = flops
                _xla.set_step_flops(flops)
            # forward-only FLOPs from the eval dispatch's lowering (the
            # same layer composition as the step's forward): feeds the
            # live fwd/bwd attribution — bwd.step_ms / bwd.mfu_pct
            # gauges next to mfu_pct (xla_introspect.bwd_snapshot,
            # docs/kernels.md)
            params = [{"weights": aval(s["weights"]),
                       "bias": aval(s["bias"])} for s in self._state]
            if self.loss == "softmax":
                fwd_cost = self._eval_metrics.lower(
                    params, aval(x), aval(target)).cost_analysis()
            else:
                fwd_cost = self._eval_metrics.lower(
                    params, aval(x), aval(target),
                    aval(batch_size)).cost_analysis()
            fwd_flops = self._cost_flops(fwd_cost)
            if 0 < fwd_flops < flops:
                _xla.set_fwd_flops(fwd_flops)
        except Exception as exc:
            self.debug("step cost analysis unavailable: %s", exc)

    @staticmethod
    def _cost_flops(cost):
        """One flops extraction for cost_analysis()'s dict/list-of-dict
        return variants across jax releases."""
        if isinstance(cost, (list, tuple)):
            return sum(float(c.get("flops", 0.0)) for c in cost
                       if isinstance(c, dict))
        return float((cost or {}).get("flops", 0.0))

    def _stage_sharded(self, arr):
        """Stage one minibatch Array onto the mesh, leading dim over
        ``data_axis``.  Multi-host processes stitch their local slice
        (parallel.shard_host_batch); single-process meshes device_put
        the full batch.  The host buffer is COPIED first: XLA:CPU's
        device_put adopts host memory zero-copy, and the loader refills
        ``mem`` on the next serve (the PR 1 hazard)."""
        from veles_tpu.parallel.api import shard_host_batch
        arr.map_read()
        host = numpy.array(arr.mem)
        if host.shape[0] % self.mesh.shape[self.data_axis]:
            raise ValueError(
                "minibatch rows %d not divisible by mesh axis %r=%d"
                % (host.shape[0], self.data_axis,
                   self.mesh.shape[self.data_axis]))
        return shard_host_batch(self.mesh, host, self.data_axis)

    def _publish_comm(self, step_seconds):
        """One-time comm receipt (SPMD mode): the exact bucket
        partition the compiled step runs (plan_buckets is
        deterministic) plus the modeled overlap schedule, published as
        ``comm.*`` gauges and per-bucket spans (docs/observability.md).
        ``step_seconds`` is the first clean post-compile step wall."""
        import jax

        from veles_tpu.parallel import bucketed as _bucketed
        self._comm_published_ = True
        try:
            grads_like = [{"weights": s["weights"], "bias": s["bias"]}
                          for s in self._state]
            leaves = jax.tree_util.tree_leaves(grads_like)
            receipt = _bucketed.comm_receipt(
                leaves, self.mesh.shape[self.data_axis],
                bucket_bytes=getattr(self._step_fn, "bucket_bytes",
                                     None),
                step_seconds=step_seconds,
                compress=self.grad_compress)
            _bucketed.publish_comm_receipt(receipt)
            self.info(
                "SPMD comm: %d bucket(s), %.1f MB gradients, modeled "
                "overlap %.1f%%",
                len(receipt["bucket_bytes"]),
                receipt["allreduce_bytes"] / 2.0 ** 20,
                receipt["model"]["overlap_pct"])
        except Exception as exc:
            self.debug("comm receipt unavailable: %s", exc)

    def on_health_sync(self, skips, consec):
        """Health-watchdog hook (decision._health_counters, the
        existing once-per-class device sync): a skipped step while
        bf16 gradient compression is on means the compressed wire
        format may have produced the non-finite — fall back to f32
        (drop the compiled step; the next run() recompiles) rather
        than risk skipping every step of a run that f32 would carry.
        The skipped update itself was already discarded bit-exactly by
        the in-graph guard, so the fallback costs one recompile and
        nothing else (docs/health.md)."""
        if (self.grad_compress is not None
                and skips > self._compress_skips_seen_):
            self.warning(
                "non-finite step under %s gradient compression; "
                "falling back to f32 all-reduce (recompile)",
                self.grad_compress)
            _registry.counter("comm.compress_fallbacks").inc()
            # write the live fused state back into the unit Arrays
            # BEFORE dropping it: the recompile re-extracts from the
            # Arrays, whose old device buffers were donated into the
            # compressed step and no longer exist
            self.sync()
            self.grad_compress = None
            self._step_fn = None
            self._state = None
            self._comm_published_ = False
        self._compress_skips_seen_ = skips

    def sync(self):
        """Write the fused state back into the unit Arrays (on demand:
        snapshots, plotting, package export)."""
        from veles_tpu.compiler import adopt_state
        if self._state is not None:
            adopt_state(self.sw, self._state, self.device)

    _sync_state_to_units = sync

    def run(self):
        import jax

        t0 = time.perf_counter()
        if self._step_fn is None:
            self._compile()
        loader = self.sw.loader
        is_train = loader.minibatch_class == TRAIN
        prefetched = (self._prefetcher.current
                      if self._prefetcher is not None else None)
        if self.mesh is not None:
            x = self._stage_sharded(loader.minibatch_data)
            target = self._stage_sharded(
                loader.minibatch_labels if self.loss == "softmax"
                else loader.minibatch_targets)
        elif prefetched is not None:
            # pipelined path: the worker already filled + H2D'd this
            # minibatch one step ahead; its device arrays ARE the input
            x = prefetched.data
            target = (prefetched.labels if self.loss == "softmax"
                      else prefetched.targets)
        else:
            x = loader.minibatch_data.device_array(self.device)
            if self.loss == "softmax":
                target = loader.minibatch_labels.device_array(self.device)
            else:
                target = loader.minibatch_targets.device_array(self.device)
        batch_size = numpy.float32(loader.minibatch_size)

        if is_train:
            self._iteration += 1
            key = None
            if self._has_dropout:
                key = jax.random.fold_in(
                    jax.random.PRNGKey(self._dropout_base_key),
                    self._iteration)
            poisons = {}
            if chaos.plan is not None:
                # nan-injection rides INSIDE the jitted step as traced
                # scalars (compiler.py); the healthy path never pays
                for point, kwarg in (("step.grad", "grad_poison"),
                                     ("step.loss", "loss_poison")):
                    fault = chaos.plan.fire(point)
                    if fault is not None:
                        poisons[kwarg] = numpy.float32(
                            numpy.nan if fault.param is None
                            else fault.param)
            if key is not None or poisons:
                self._state, metrics = self._step_fn(
                    self._state, x, target, batch_size, key, **poisons)
            else:
                self._state, metrics = self._step_fn(
                    self._state, x, target, batch_size)
            # all lazy device scalars: the decision unit forces the
            # sync once per finished class, so the fused path stays
            # one async dispatch per step even on a tunneled chip
            self.last_loss = metrics["loss"]
            self.n_err = metrics["n_err"]
            self.grad_norm = metrics["grad_norm"]
            self.last_step_finite = metrics["finite"]
            from veles_tpu.models.evaluator import lazy_add, lazy_consec
            self.skip_count = lazy_add(self.skip_count,
                                       metrics["skipped"])
            self.consecutive_skips = lazy_consec(
                self.consecutive_skips, metrics["skipped"])
            # mse_sum from the step's aux metric matches EvaluatorMSE's
            # definition (per-feature mean, summed over samples); the
            # scalar loss is SSE/batch over ALL elements and would
            # inflate epoch RMSE by sqrt(num_features).  The fallback
            # product only exists inside the conditional — an eager
            # default arg would dispatch one remote op per step
            if "mse_sum" in metrics:
                self.mse_sum = metrics["mse_sum"]
            elif self.loss != "softmax":
                self.mse_sum = metrics["loss"] * batch_size
            if self._step_flops_ is None:
                self._publish_step_flops(
                    x, target, batch_size, key, poisons)
        else:
            # eval minibatch: ONE jitted forward+metrics dispatch,
            # result stays lazy on device until class end
            params = [{"weights": s["weights"], "bias": s["bias"]}
                      for s in self._state]
            if self.loss == "softmax":
                self.n_err = self._eval_metrics(params, x, target)
            else:
                self.mse_sum = self._eval_metrics(
                    params, x, target, batch_size)
        self.n_samples = int(batch_size)
        elapsed = time.perf_counter() - t0
        if (is_train and self.mesh is not None
                and not self._comm_published_
                and self._iteration >=
                getattr(self, "_compiled_at_iter_", 0) + 2):
            # the first post-compile step's wall includes the compile;
            # this one is the first clean step time the overlap model
            # can be sized on
            self._publish_comm(elapsed)
        if is_train:
            self._m_train_step_.observe(elapsed)
            self._m_steps_.inc()
            self._m_samples_.inc(self.n_samples)
            profiler_step()
        else:
            self._m_eval_step_.observe(elapsed)
        if _tracer.active:
            # .active, not .enabled: the always-on flight recorder
            # keeps the last N step spans for post-mortem dumps even
            # when full tracing is off (docs/observability.md)
            _tracer.complete(
                "fused.train_step" if is_train else "fused.eval_step",
                t0, elapsed, cat="step",
                args={"iteration": self._iteration})

    def reset_health_counters(self):
        """Zero the skip accounting (after the decision's divergence
        handler finished a rollback, so the next epoch's check starts
        clean)."""
        self.skip_count = 0
        self.consecutive_skips = 0
        self.last_step_finite = True

    def reset_after_rollback(self, rollbacks):
        """Post-rollback reset: drop the compiled step and the fused
        device state so the next run re-reads the (restored) unit
        Arrays AND the (backed-off) gd hyperparameters, and reseed the
        dropout stream — replaying the exact noise that accompanied a
        divergence wastes one retry of the bounded budget."""
        self._step_fn = None
        self._state = None
        self._eval_metrics = None
        # deterministic but distinct per rollback (golden-ratio hash
        # increment keeps streams well separated for small seeds)
        self._dropout_base_key = (
            self._dropout_seed + rollbacks * 0x9E3779B1) & 0x7FFFFFFF
        self.reset_health_counters()

    def __getstate__(self):
        # state lives in the unit Arrays for snapshots
        self._sync_state_to_units()
        state = super(FusedTrainer, self).__getstate__()
        state["_step_fn"] = None
        state["_state"] = None
        state["_eval_metrics"] = None
        state["_plans"] = None
        # a Mesh holds live device handles, so only its AXES pickle;
        # initialize() -> _restore_mesh rebuilds it on resume
        state["mesh"] = None
        state["_spmd_axes_"] = (dict(self.mesh.shape)
                                if self.mesh is not None else None)
        # re-created (and re-attached to the loader) at initialize
        state["_prefetcher"] = None
        # concretize lazy device metrics for the pickle
        state["n_err"] = int(self.n_err)
        state["mse_sum"] = float(self.mse_sum)
        if self.last_loss is not None:
            state["last_loss"] = float(self.last_loss)
        state["skip_count"] = int(self.skip_count)
        state["consecutive_skips"] = int(self.consecutive_skips)
        state["last_step_finite"] = bool(self.last_step_finite)
        state["grad_norm"] = (None if self.grad_norm is None
                              else float(self.grad_norm))
        return state


def fuse_standard_workflow(sw, dropout_seed=0, pipeline=False,
                           pipeline_depth=1, mesh=None, data_axis="data",
                           grad_bucket_mb=None, grad_compress=None):
    """Rewire a StandardWorkflow: loader -> FusedTrainer -> decision.

    The forward/GD units stay constructed (they own the param Arrays and
    the snapshot format) but leave the control graph.  ``pipeline=True``
    additionally overlaps host fill + H2D of minibatch k+1 with step k
    (pipeline_input.Prefetcher); it falls back to the synchronous serve
    on devices without real hardware or in distributed modes.

    ``mesh`` switches the trainer to the SPMD data plane: the step
    compiles as shard_map over ``data_axis`` with the bucketed
    overlapped gradient all-reduce (``grad_bucket_mb``, default ~25 MB
    via ``--grad-bucket-mb``; ``grad_compress="bf16"`` via
    ``--grad-compress``).  With a mesh the master-slave protocol
    carries CONTROL records only — per-step gradients ride ICI — so
    the workflow flips to the single-traversal inline update
    validation (docs/distributed.md, ``Workflow.update_validation``).
    """
    from veles_tpu.config import root
    train_cfg = root.common.train
    if grad_bucket_mb is None:
        grad_bucket_mb = train_cfg.get("grad_bucket_mb")
    if grad_compress is None:
        grad_compress = train_cfg.get("grad_compress")
    trainer = FusedTrainer(sw, sw, dropout_seed=dropout_seed,
                           pipeline=pipeline,
                           pipeline_depth=pipeline_depth,
                           mesh=mesh, data_axis=data_axis,
                           grad_bucket_mb=grad_bucket_mb,
                           grad_compress=grad_compress)
    if mesh is not None:
        sw.update_validation = "inline"
    # detach the old chain from control flow
    for unit in sw.forwards + [sw.evaluator] + sw.gds:
        unit.unlink_all()
    trainer.link_from(sw.loader)
    sw.decision.link_from(trainer)
    # decision reads its metrics from the trainer now
    sw.decision.evaluator = trainer
    # ...and its numerics-health counters (skip_count /
    # consecutive_skips) from the trainer instead of the severed gds
    sw.decision.health_sources = [trainer]
    snapshotter = getattr(sw, "snapshotter", None)
    if snapshotter is not None:
        # the fused step is atomic, so post-decision state is already
        # quiescent: ride decision -> snapshotter -> repeater (the
        # per-unit graph hangs it off gds[0] instead, which fuse just
        # severed); gate unchanged — once per improved epoch
        snapshotter.unlink_all()
        snapshotter.link_from(sw.decision)
        sw.repeater.link_from(snapshotter)
        sw.end_point.link_from(snapshotter)
        snapshotter.gate_skip = ~(sw.decision.improved &
                                  sw.loader.epoch_ended)
    else:
        sw.repeater.link_from(sw.decision)
    sw.end_point.link_from(sw.decision)
    sw.end_point.gate_block = ~sw.decision.complete
    sw.fused_trainer = trainer
    return trainer
