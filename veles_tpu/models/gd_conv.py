"""Gradient-descent units for conv layers.

Znicz-equivalent gd_conv family.  The backward runs as ONE jitted call:
activation derivative (in terms of y), then ``jax.vjp`` of the pure
linear conv — XLA emits the transposed-conv kernels for dW and dx the
same way the hand-written CUDA backward kernels did, but fused and
MXU-tiled.
"""

from veles_tpu.models.conv import _norm_padding
from veles_tpu.models.gd import (
    GDRELU, GDSigmoid, GDStrictRELU, GDTanh, GradientDescent)
from veles_tpu.models.nn_units import GradientDescentBase

__all__ = ["GDConv", "GDConvTanh", "GDConvRELU", "GDConvStrictRELU",
           "GDConvSigmoid"]


class GDConv(GradientDescent):
    MAPPING = "conv"

    def __init__(self, workflow, **kwargs):
        super(GDConv, self).__init__(workflow, **kwargs)
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        self.padding = _norm_padding(kwargs.get("padding", 0))

    def backward_static(self):
        return {"padding": self.padding, "sliding": self.sliding}

    #: epilogue name for the fused conv-VJP family (matches the
    #: forward class's ACTIVATION; docs/kernels.md)
    ACTIVATION = "linear"

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input,
                 padding=(0, 0, 0, 0), sliding=(1, 1)):
        import jax.numpy as jnp

        from veles_tpu.ops.common import pallas_bwd_enabled
        W = state["weights"]
        if pallas_bwd_enabled():
            # hand-scheduled backward (ops/conv_vjp.py): activation
            # backward + bias reduction fused into the Pallas wgrad
            # tiles, dgrad as the explicit lhs-dilated conv.  The
            # finite_guard below sees the same grad tensors either
            # way, so a poisoned step still skips bit-exactly.
            from veles_tpu.ops.conv_vjp import fused_conv_vjp
            err_input, grad_w, grad_b_raw = fused_conv_vjp(
                x, W, y, err_output, activation=cls.ACTIVATION,
                padding=padding, sliding=sliding,
                include_bias=include_bias,
                need_err_input=need_err_input)
        else:
            # the ONE stock formulation (also fused_conv_vjp's
            # many-tap fallback), so the bit-exact knob-off contract
            # has a single definition to hold to
            from veles_tpu.ops.conv_vjp import _autodiff_conv_vjp
            err_input, grad_w, grad_b_raw = _autodiff_conv_vjp(
                x, W, y, err_output, activation=cls.ACTIVATION,
                padding=padding, sliding=sliding,
                include_bias=include_bias,
                need_err_input=need_err_input)

        grad_w = GradientDescentBase.regularized(
            grad_w.astype(jnp.float32), W, hyper["weights_decay"],
            hyper["l1_vs_l2"])
        new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
            solver, W, grad_w.astype(W.dtype), state["accum_weights"],
            state["accum2_weights"], hyper["learning_rate"],
            hyper["gradient_moment"], hyper["adadelta_rho"],
            hyper["solver_epsilon"])
        new_state = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w}

        grad_b = None
        if include_bias:
            b = state["bias"]
            grad_b = GradientDescentBase.regularized(
                grad_b_raw, b, hyper["weights_decay_bias"],
                hyper["l1_vs_l2"])
            new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                solver, b, grad_b.astype(b.dtype), state["accum_bias"],
                state["accum2_bias"], hyper["learning_rate_bias"],
                hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            new_state.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
        # numerics guard: skip the update on non-finite gradients
        # (docs/health.md; same semantics as the fully-connected family)
        new_state = GradientDescentBase.finite_guard(
            state, new_state, grad_w, grad_b)
        return err_input, new_state


class GDConvTanh(GDConv):
    MAPPING = "conv_tanh"
    ACTIVATION = "tanh"
    _activation_grad = staticmethod(GDTanh._activation_grad)


class GDConvRELU(GDConv):
    MAPPING = "conv_relu"
    ACTIVATION = "relu_log"
    _activation_grad = staticmethod(GDRELU._activation_grad)


class GDConvStrictRELU(GDConv):
    MAPPING = "conv_str"
    ACTIVATION = "strict_relu"
    _activation_grad = staticmethod(GDStrictRELU._activation_grad)


class GDConvSigmoid(GDConv):
    MAPPING = "conv_sigmoid"
    ACTIVATION = "sigmoid"
    _activation_grad = staticmethod(GDSigmoid._activation_grad)
