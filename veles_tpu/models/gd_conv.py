"""Gradient-descent units for conv layers.

Znicz-equivalent gd_conv family.  The backward runs as ONE jitted call:
activation derivative (in terms of y), then ``jax.vjp`` of the pure
linear conv — XLA emits the transposed-conv kernels for dW and dx the
same way the hand-written CUDA backward kernels did, but fused and
MXU-tiled.
"""

from veles_tpu.models.conv import Conv, _norm_padding
from veles_tpu.models.gd import (
    GDRELU, GDSigmoid, GDStrictRELU, GDTanh, GradientDescent)
from veles_tpu.models.nn_units import GradientDescentBase

__all__ = ["GDConv", "GDConvTanh", "GDConvRELU", "GDConvStrictRELU",
           "GDConvSigmoid"]


class GDConv(GradientDescent):
    MAPPING = "conv"

    def __init__(self, workflow, **kwargs):
        super(GDConv, self).__init__(workflow, **kwargs)
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        self.padding = _norm_padding(kwargs.get("padding", 0))

    def backward_static(self):
        return {"padding": self.padding, "sliding": self.sliding}

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input,
                 padding=(0, 0, 0, 0), sliding=(1, 1)):
        import jax
        import jax.numpy as jnp
        W = state["weights"]
        err = cls._activation_grad(y, err_output).astype(x.dtype)

        def lin(W_, x_):
            return Conv.apply({"weights": W_, "bias": None}, x_,
                              padding=padding, sliding=sliding)

        _, vjp = jax.vjp(lin, W, x)
        grad_w, err_input = vjp(err)
        if not need_err_input:
            err_input = None

        grad_w = GradientDescentBase.regularized(
            grad_w.astype(jnp.float32), W, hyper["weights_decay"],
            hyper["l1_vs_l2"])
        new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
            solver, W, grad_w.astype(W.dtype), state["accum_weights"],
            state["accum2_weights"], hyper["learning_rate"],
            hyper["gradient_moment"], hyper["adadelta_rho"],
            hyper["solver_epsilon"])
        new_state = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w}

        grad_b = None
        if include_bias:
            b = state["bias"]
            grad_b = err.astype(jnp.float32).sum(axis=(0, 1, 2))
            grad_b = GradientDescentBase.regularized(
                grad_b, b, hyper["weights_decay_bias"], hyper["l1_vs_l2"])
            new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                solver, b, grad_b.astype(b.dtype), state["accum_bias"],
                state["accum2_bias"], hyper["learning_rate_bias"],
                hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            new_state.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
        # numerics guard: skip the update on non-finite gradients
        # (docs/health.md; same semantics as the fully-connected family)
        new_state = GradientDescentBase.finite_guard(
            state, new_state, grad_w, grad_b)
        return err_input, new_state


class GDConvTanh(GDConv):
    MAPPING = "conv_tanh"
    _activation_grad = staticmethod(GDTanh._activation_grad)


class GDConvRELU(GDConv):
    MAPPING = "conv_relu"
    _activation_grad = staticmethod(GDRELU._activation_grad)


class GDConvStrictRELU(GDConv):
    MAPPING = "conv_str"
    _activation_grad = staticmethod(GDStrictRELU._activation_grad)


class GDConvSigmoid(GDConv):
    MAPPING = "conv_sigmoid"
    _activation_grad = staticmethod(GDSigmoid._activation_grad)
