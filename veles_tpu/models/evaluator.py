"""Evaluator units: loss gradients + per-minibatch metrics.

Znicz-equivalent evaluator_softmax / evaluator_mse
(manualrst_veles_algorithms.rst: softmax & MSE losses).

Design notes:
- ``err_output`` is the MEAN-loss gradient (divided by the current
  minibatch size), so learning rates are batch-size invariant — a
  deliberate departure from the reference's summed gradient, documented
  here for anyone porting configs.
- short (padded) minibatches are masked by ``labels >= 0`` /
  an explicit sample mask, matching the loader's padding convention;
- metrics (n_err, confusion, loss sums) are computed in the same jitted
  call and stay LAZY on device (jax scalars): the decision unit
  accumulates them asynchronously and forces a host sync only at
  class/epoch boundaries.  A per-minibatch ``int(n_err)`` costs a full
  blocking round trip (~0.2 s on a tunneled chip — it dominated the
  round-2 on-TPU wall time at 94 %), so nothing here synchronizes.
"""

import numpy

from veles_tpu.backends import NumpyDevice
from veles_tpu.memory import Array
from veles_tpu.units import Unit

__all__ = ["EvaluatorBase", "EvaluatorSoftmax", "EvaluatorMSE",
           "lazy_add", "lazy_consec"]

_JIT_ADD = None
_JIT_CONSEC = None


def lazy_add(a, b):
    """a + b for metric accumulation without eager-op overhead.

    Eager jax ops dispatch one remote call each (~160 ms measured over
    the axon tunnel vs ~4 ms jitted), so accumulating lazy metrics
    with plain ``+`` silently re-serializes training on the host.
    Jitted when either side is a jax array; plain Python + otherwise
    (numpy-backend workflows never touch jax here)."""
    if not (hasattr(a, "aval") or hasattr(b, "aval")):
        return a + b
    global _JIT_ADD
    if _JIT_ADD is None:
        import jax
        _JIT_ADD = jax.jit(lambda p, q: p + q)
    return _JIT_ADD(a, b)


def lazy_consec(prev, skipped):
    """Consecutive-skip counter update for the numerics watchdog
    (docs/health.md) without a host sync: ``skipped`` is a lazy 0/1
    scalar, so ``(prev + s) * s`` increments on a skipped step and
    resets to 0 on any applied one.  Jitted like :func:`lazy_add`;
    plain arithmetic for host-side (numpy-backend) callers."""
    if not (hasattr(prev, "aval") or hasattr(skipped, "aval")):
        return (prev + skipped) * skipped
    global _JIT_CONSEC
    if _JIT_CONSEC is None:
        import jax
        _JIT_CONSEC = jax.jit(lambda p, s: (p + s) * s)
    return _JIT_CONSEC(prev, skipped)


class EvaluatorBase(Unit):
    """Common plumbing: demands output + batch_size, owns err_output."""

    def __init__(self, workflow, **kwargs):
        super(EvaluatorBase, self).__init__(workflow, **kwargs)
        self.output = None          # linked from the last forward unit
        self.batch_size = None      # linked from loader.minibatch_size
        self.err_output = Array()
        self.device = None
        self._jit_fn_ = None
        self.demand("output", "batch_size")

    def init_unpickled(self):
        super(EvaluatorBase, self).init_unpickled()
        self._jit_fn_ = None

    def on_device(self):
        return (self.device is not None and self.device.exists and
                not isinstance(self.device, NumpyDevice))

    def initialize(self, device=None, **kwargs):
        self.device = device
        return super(EvaluatorBase, self).initialize(**kwargs)


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy on softmax probabilities.

    err_output = (probs - onehot(label)) / batch_size, zero for padded
    samples; metrics: n_err (misclassifications), confusion_matrix row =
    truth, column = prediction.
    """

    def __init__(self, workflow, **kwargs):
        super(EvaluatorSoftmax, self).__init__(workflow, **kwargs)
        self.labels = None          # linked from loader.minibatch_labels
        self.n_err = 0              # per-minibatch, read by decision
        self.confusion_matrix = Array()
        self.compute_confusion = kwargs.get("compute_confusion", True)
        self.demand("labels")

    @staticmethod
    def compute(probs, labels, batch_size, n_classes):
        import jax.numpy as jnp
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        onehot = jnp.zeros_like(probs).at[
            jnp.arange(probs.shape[0]), safe].set(1.0)
        err = (probs - onehot) * valid[:, None] / batch_size
        pred = jnp.argmax(probs, axis=-1)
        n_err = jnp.sum((pred != safe) & valid)
        confusion = jnp.zeros((n_classes, n_classes), jnp.int32).at[
            safe, pred].add(valid.astype(jnp.int32))
        return err.astype(probs.dtype), n_err, confusion

    def init_unpickled(self):
        super(EvaluatorSoftmax, self).init_unpickled()
        self._confusion_acc_ = None

    def run(self):
        n_classes = self.output.shape[-1]
        if self.on_device():
            import functools
            import jax
            import jax.numpy as jnp
            if self._jit_fn_ is None:
                self._jit_fn_ = jax.jit(functools.partial(
                    EvaluatorSoftmax.compute, n_classes=n_classes))
            err, n_err, confusion = self._jit_fn_(
                self.output.device_array(self.device),
                self.labels.device_array(self.device),
                numpy.float32(self.batch_size))
            self.err_output.set_device_array(err, self.device)
            # lazy: the decision unit syncs at class end, not per step
            self.n_err = n_err
            if self.compute_confusion:
                acc = self._confusion_acc_
                if acc is None and self.confusion_matrix:
                    # snapshot-restored history seeds the accumulator
                    acc = jnp.asarray(self.confusion_matrix.mem)
                self._confusion_acc_ = (confusion if acc is None
                                        else lazy_add(acc, confusion))
                self.confusion_matrix.set_device_array(
                    self._confusion_acc_, self.device)
            return
        from veles_tpu.backends import host_compute_context
        self.output.map_read()
        self.labels.map_read()
        with host_compute_context(self.device):
            err, n_err, confusion = EvaluatorSoftmax.compute(
                self.output.mem, self.labels.mem,
                numpy.float32(self.batch_size), n_classes)
        self.err_output.map_invalidate()
        self.err_output.mem = numpy.asarray(err)
        self.n_err = int(n_err)
        conf = numpy.asarray(confusion)
        if self.compute_confusion:
            if not self.confusion_matrix:
                self.confusion_matrix.mem = numpy.zeros_like(conf)
            self.confusion_matrix.map_write()
            self.confusion_matrix.mem += conf

    def __getstate__(self):
        # snapshots must carry plain scalars, not device handles
        state = super(EvaluatorSoftmax, self).__getstate__()
        if "n_err" in state:
            state["n_err"] = int(self.n_err)
        return state


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error: err_output = 2*(y - target)/batch (masked),
    metric: summed squared error for RMSE aggregation."""

    def __init__(self, workflow, **kwargs):
        super(EvaluatorMSE, self).__init__(workflow, **kwargs)
        self.target = None          # linked from loader.minibatch_targets
        self.mse_sum = 0.0          # per-minibatch sum of sample MSEs
        self.n_samples = 0
        self.demand("target")

    @staticmethod
    def compute(y, target, batch_size, max_batch):
        import jax.numpy as jnp
        y2 = y.reshape(y.shape[0], -1)
        t2 = target.reshape(target.shape[0], -1)
        mask = (jnp.arange(y2.shape[0]) < batch_size).astype(y2.dtype)
        diff = (y2 - t2) * mask[:, None]
        err = (2.0 * diff / batch_size).astype(y.dtype).reshape(y.shape)
        mse_sum = jnp.sum(jnp.mean(diff * diff, axis=1))
        return err, mse_sum

    def run(self):
        if self.on_device():
            import jax
            if self._jit_fn_ is None:
                self._jit_fn_ = jax.jit(EvaluatorMSE.compute)
            err, mse_sum = self._jit_fn_(
                self.output.device_array(self.device),
                self.target.device_array(self.device),
                numpy.float32(self.batch_size),
                self.output.shape[0])
            self.err_output.set_device_array(err, self.device)
            # lazy (see module docstring): synced at class end
            self.mse_sum = mse_sum
            self.n_samples = int(self.batch_size)
            return
        from veles_tpu.backends import host_compute_context
        self.output.map_read()
        self.target.map_read()
        with host_compute_context(self.device):
            err, mse_sum = EvaluatorMSE.compute(
                self.output.mem, self.target.mem,
                numpy.float32(self.batch_size), self.output.shape[0])
        self.err_output.map_invalidate()
        self.err_output.mem = numpy.asarray(err)
        self.mse_sum = float(mse_sum)
        self.n_samples = int(self.batch_size)

    def __getstate__(self):
        state = super(EvaluatorMSE, self).__getstate__()
        if "mse_sum" in state:
            state["mse_sum"] = float(self.mse_sum)
        return state
