"""Deconvolution (transposed conv) and depooling forward units +
their GD counterparts — the convolutional-autoencoder building blocks
(manualrst_veles_algorithms.rst: deconv / depooling).

Deconv here is the gradient of Conv w.r.t. its input expressed as a
forward op (lax.conv_transpose), matching how Znicz's deconv mirrored
its conv unit.  Depooling upsamples by the pooling window (nearest for
avg-depool, zero-stuffing handled by deconv in practice).
"""

import numpy

from veles_tpu.models.conv import _norm_padding
from veles_tpu.models.gd import GradientDescent
from veles_tpu.models.nn_units import ForwardBase, GradientDescentBase

__all__ = ["Deconv", "GDDeconv", "Depooling"]


class Deconv(ForwardBase):
    """y = conv_transpose(x, W); weights (ky, kx, out_ch, in_ch) so a
    (conv, deconv) pair can SHARE weights (tied autoencoder)."""

    MAPPING = "deconv"

    def __init__(self, workflow, **kwargs):
        super(Deconv, self).__init__(workflow, **kwargs)
        self.n_output_channels = kwargs["n_output_channels"]
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        self.padding = _norm_padding(kwargs.get("padding", 0))
        self.include_bias = kwargs.get("include_bias", False)

    @classmethod
    def apply(cls, params, x, *, padding=(0, 0, 0, 0), sliding=(1, 1)):
        import jax.numpy as jnp
        from jax import lax
        W = params["weights"]  # (ky, kx, out_ch, in_ch)
        if x.ndim == 3:
            x = x[..., None]
        left, top, right, bottom = padding
        sx, sy = sliding
        ky, kx = W.shape[0], W.shape[1]
        # `padding` follows the FORWARD conv convention (the pair's conv
        # unit); lax.conv_transpose wants raw dilated-conv padding,
        # which for forward padding p is k - 1 - p
        # see conv.py: f32-preferred output breaks the bf16 transpose
        # rule; the MXU accumulates in f32 in hardware either way
        pet = jnp.float32 if x.dtype == jnp.float32 else None
        z = lax.conv_transpose(
            x, W,
            strides=(sy, sx),
            padding=((ky - 1 - top, ky - 1 - bottom),
                     (kx - 1 - left, kx - 1 - right)),
            dimension_numbers=("NHWC", "HWOI", "NHWC"),
            preferred_element_type=pet)
        if params.get("bias") is not None:
            z = z + params["bias"]
        return z.astype(x.dtype)

    def static_config(self):
        return {"padding": self.padding, "sliding": self.sliding}

    def output_spatial(self, in_h, in_w):
        left, top, right, bottom = self.padding
        sx, sy = self.sliding
        out_h = (in_h - 1) * sy + self.ky - top - bottom
        out_w = (in_w - 1) * sx + self.kx - left - right
        return out_h, out_w

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        shape = self.input.shape
        batch, in_h, in_w, in_ch = (
            shape if len(shape) == 4 else shape + (1,))
        if not self.output:
            out_h, out_w = self.output_spatial(in_h, in_w)
            self.output.mem = numpy.zeros(
                (batch, out_h, out_w, self.n_output_channels),
                numpy.float32)
        if self.weights:
            return
        fan_in = self.kx * self.ky * in_ch
        weights = numpy.zeros(
            (self.ky, self.kx, self.n_output_channels, in_ch),
            numpy.float32)
        self.fill_array(weights, self.weights_filling,
                        self.weights_stddev, fan_in)
        self.weights.mem = weights
        if self.include_bias:
            self.bias.mem = numpy.zeros(
                (self.n_output_channels,), numpy.float32)


class GDDeconv(GradientDescent):
    MAPPING = "deconv"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("include_bias", False)
        super(GDDeconv, self).__init__(workflow, **kwargs)
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        self.padding = _norm_padding(kwargs.get("padding", 0))

    def backward_static(self):
        return {"padding": self.padding, "sliding": self.sliding}

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input,
                 padding=(0, 0, 0, 0), sliding=(1, 1)):
        import jax
        import jax.numpy as jnp
        W = state["weights"]
        err = err_output.astype(x.dtype)

        def lin(W_, x_):
            return Deconv.apply({"weights": W_, "bias": None}, x_,
                                padding=padding, sliding=sliding)

        _, vjp = jax.vjp(lin, W, x)
        grad_w, err_input = vjp(err)
        if not need_err_input:
            err_input = None
        grad_w = GradientDescentBase.regularized(
            grad_w.astype(jnp.float32), W, hyper["weights_decay"],
            hyper["l1_vs_l2"])
        new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
            solver, W, grad_w.astype(W.dtype), state["accum_weights"],
            state["accum2_weights"], hyper["learning_rate"],
            hyper["gradient_moment"], hyper["adadelta_rho"],
            hyper["solver_epsilon"])
        new_state = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w}
        grad_b = None
        if include_bias:
            b = state["bias"]
            grad_b = err.astype(jnp.float32).sum(axis=(0, 1, 2))
            new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                solver, b, grad_b.astype(b.dtype), state["accum_bias"],
                state["accum2_bias"], hyper["learning_rate_bias"],
                hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            new_state.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
        # numerics guard: skip the update on non-finite gradients
        # (docs/health.md; same semantics as the fully-connected family)
        new_state = GradientDescentBase.finite_guard(
            state, new_state, grad_w, grad_b)
        return err_input, new_state


class Depooling(ForwardBase):
    """Nearest-neighbour upsample by the pooling window — the
    avg-depooling inverse used by conv autoencoders."""

    MAPPING = "depooling"

    def __init__(self, workflow, **kwargs):
        super(Depooling, self).__init__(workflow, **kwargs)
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.include_bias = False

    def static_config(self):
        return {"window": (self.ky, self.kx)}

    def param_arrays(self):
        return []

    def params_dict(self):
        return {}

    def params_numpy(self):
        return {}

    @classmethod
    def apply(cls, params, x, *, window):
        import jax.numpy as jnp
        if x.ndim == 3:
            x = x[..., None]
        ky, kx = window
        return jnp.repeat(jnp.repeat(x, ky, axis=1), kx, axis=2)

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        if not self.output:
            b, h, w, c = self.input.shape
            self.output.mem = numpy.zeros(
                (b, h * self.ky, w * self.kx, c), numpy.float32)


class GDDepooling(GradientDescentBase):
    MAPPING = "depooling"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("include_bias", False)
        super(GDDepooling, self).__init__(workflow, **kwargs)
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self._demanded.discard("weights")

    def _init_solver_state(self):
        pass

    def backward_static(self):
        return {"window": (self.ky, self.kx)}

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, window):
        import jax
        def fwd(x_):
            return Depooling.apply({}, x_, window=window)
        _, vjp = jax.vjp(fwd, x)
        (err_input,) = vjp(err_output.astype(x.dtype))
        return err_input, {}
