"""Standalone activation units (Znicz-equivalent activation.py):
forward/backward pairs insertable between any two layers.

Each pair shares its math with the fused all2all/conv variants; backward
derivatives are expressed in terms of the forward OUTPUT y.
"""

import numpy

from veles_tpu.models.all2all import (
    All2AllRELU, All2AllSigmoid, All2AllStrictRELU, All2AllTanh)
from veles_tpu.models.gd import (
    GDRELU, GDSigmoid, GDStrictRELU, GDTanh)
from veles_tpu.models.nn_units import ForwardBase, GradientDescentBase

__all__ = [
    "ActivationForward", "ActivationBackward",
    "ForwardTanh", "BackwardTanh", "ForwardRELU", "BackwardRELU",
    "ForwardStrictRELU", "BackwardStrictRELU", "ForwardSigmoid",
    "BackwardSigmoid", "ForwardLog", "BackwardLog", "ForwardMul",
    "BackwardMul",
]


class ActivationForward(ForwardBase):
    """Elementwise y = f(x); no params."""

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        if not self.output:
            self.output.mem = numpy.zeros(
                self.input.shape, numpy.float32)

    def param_arrays(self):
        return []

    def params_dict(self):
        return {}

    def params_numpy(self):
        return {}

    @classmethod
    def apply(cls, params, x, **static):
        return cls._activate(x)


class ActivationBackward(GradientDescentBase):
    """err_input = f'(y) * err_output; no params."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("include_bias", False)
        super(ActivationBackward, self).__init__(workflow, **kwargs)
        self._demanded.discard("weights")
        self._demanded.discard("input")

    def _init_solver_state(self):
        pass

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, **static):
        return cls._activation_grad(y, err_output), {}

    def run(self):
        # x is unused; substitute y to satisfy the generic signature
        if self.input is None:
            self.input = self.output
        super(ActivationBackward, self).run()


class ForwardTanh(ActivationForward):
    MAPPING = "activation_tanh"
    _activate = staticmethod(All2AllTanh._activate)


class BackwardTanh(ActivationBackward):
    MAPPING = "activation_tanh"
    _activation_grad = staticmethod(GDTanh._activation_grad)


class ForwardRELU(ActivationForward):
    MAPPING = "activation_relu"
    _activate = staticmethod(All2AllRELU._activate)


class BackwardRELU(ActivationBackward):
    MAPPING = "activation_relu"
    _activation_grad = staticmethod(GDRELU._activation_grad)


class ForwardStrictRELU(ActivationForward):
    MAPPING = "activation_str"
    _activate = staticmethod(All2AllStrictRELU._activate)


class BackwardStrictRELU(ActivationBackward):
    MAPPING = "activation_str"
    _activation_grad = staticmethod(GDStrictRELU._activation_grad)


class ForwardSigmoid(ActivationForward):
    MAPPING = "activation_sigmoid"
    _activate = staticmethod(All2AllSigmoid._activate)


class BackwardSigmoid(ActivationBackward):
    MAPPING = "activation_sigmoid"
    _activation_grad = staticmethod(GDSigmoid._activation_grad)


class ForwardLog(ActivationForward):
    """y = log(x + sqrt(x^2 + 1)) (asinh), Znicz activation_log."""

    MAPPING = "activation_log"

    @staticmethod
    def _activate(z):
        import jax.numpy as jnp
        return jnp.arcsinh(z)


class BackwardLog(ActivationBackward):
    MAPPING = "activation_log"

    @staticmethod
    def _activation_grad(y, err):
        import jax.numpy as jnp
        # x = sinh(y); dy/dx = 1/sqrt(x^2+1) = 1/cosh(y)
        return err / jnp.cosh(y)


class ForwardMul(ActivationForward):
    """y = k * x (Znicz activation_mul)."""

    MAPPING = "activation_mul"

    def __init__(self, workflow, **kwargs):
        super(ForwardMul, self).__init__(workflow, **kwargs)
        self.factor = kwargs.get("factor", 1.0)

    def static_config(self):
        return {"factor": self.factor}

    @classmethod
    def apply(cls, params, x, *, factor=1.0):
        return x * factor


class BackwardMul(ActivationBackward):
    MAPPING = "activation_mul"

    def __init__(self, workflow, **kwargs):
        super(BackwardMul, self).__init__(workflow, **kwargs)
        self.factor = kwargs.get("factor", 1.0)

    def backward_static(self):
        return {"factor": self.factor}

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, factor=1.0):
        return err_output * factor, {}
