"""Restricted Boltzmann Machine units
(manualrst_veles_algorithms.rst: RBM; Znicz submodule empty — fresh
design).

Binary-binary RBM trained with CD-k contrastive divergence.  The whole
CD step — up, k Gibbs alternations, down, gradient, update — is one
jitted call using counter-based jax.random for the stochastic binary
states (reproducible, nothing to checkpoint beyond the step counter).
"""

import numpy

from veles_tpu import prng as prng_module
from veles_tpu.memory import Array
from veles_tpu.units import Unit

__all__ = ["RBM"]


class RBM(Unit):
    def __init__(self, workflow, **kwargs):
        super(RBM, self).__init__(workflow, **kwargs)
        self.hidden_size = kwargs["hidden_size"]
        self.learning_rate = kwargs.get("learning_rate", 0.1)
        self.cd_k = kwargs.get("cd_k", 1)
        self.input = None  # linked minibatch (values in [0, 1])
        self.weights = Array()
        self.hidden_bias = Array()
        self.visible_bias = Array()
        self.prng = kwargs.get("prng", prng_module.get())
        self.device = None
        self._jit_fn_ = None
        self._step = 0
        self.reconstruction_error = 0.0
        self.demand("input")

    def init_unpickled(self):
        super(RBM, self).init_unpickled()
        self._jit_fn_ = None

    def initialize(self, device=None, **kwargs):
        self.device = device
        super(RBM, self).initialize(**kwargs)
        if not self.input or self.input.sample_size == 0:
            raise AttributeError("%s: input shape unknown" % self.name)
        visible = self.input.sample_size
        if not self.weights:
            w = numpy.zeros((visible, self.hidden_size), numpy.float32)
            self.prng.fill_normal(w, 0.0, 0.01)
            self.weights.mem = w
            self.hidden_bias.mem = numpy.zeros(
                self.hidden_size, numpy.float32)
            self.visible_bias.mem = numpy.zeros(visible, numpy.float32)
        return True

    @staticmethod
    def cd_step(key, W, hb, vb, v0, lr, cd_k):
        import jax
        import jax.numpy as jnp

        def h_probs(v):
            return jax.nn.sigmoid(
                jnp.dot(v, W, preferred_element_type=jnp.float32) + hb)

        def v_probs(h):
            return jax.nn.sigmoid(
                jnp.dot(h, W.T, preferred_element_type=jnp.float32) + vb)

        v0 = v0.reshape(v0.shape[0], -1)
        ph0 = h_probs(v0)
        key, sub = jax.random.split(key)
        h = (jax.random.uniform(sub, ph0.shape) < ph0).astype(
            jnp.float32)
        vk = v0
        for _ in range(cd_k):
            vk = v_probs(h)  # probabilities (common CD practice)
            phk = h_probs(vk)
            key, sub = jax.random.split(key)
            h = (jax.random.uniform(sub, phk.shape) < phk).astype(
                jnp.float32)
        phk = h_probs(vk)
        batch = v0.shape[0]
        grad_w = (jnp.dot(v0.T, ph0,
                          preferred_element_type=jnp.float32) -
                  jnp.dot(vk.T, phk,
                          preferred_element_type=jnp.float32)) / batch
        grad_hb = jnp.mean(ph0 - phk, axis=0)
        grad_vb = jnp.mean(v0 - vk, axis=0)
        err = jnp.mean((v0 - vk) ** 2)
        return (W + lr * grad_w, hb + lr * grad_hb, vb + lr * grad_vb,
                err)

    def run(self):
        import functools

        import jax
        if self._jit_fn_ is None:
            self._jit_fn_ = jax.jit(functools.partial(
                RBM.cd_step, cd_k=self.cd_k))
        self._step += 1
        from veles_tpu.backends import host_compute_context
        for arr in (self.input, self.weights, self.hidden_bias,
                    self.visible_bias):
            arr.map_read()
        # host arrays in, host arrays out: pin the jit AND the eager
        # key construction to the host CPU so a numpy-backend run
        # never round-trips a remote default device per minibatch
        with host_compute_context(self.device):
            key = jax.random.fold_in(
                jax.random.PRNGKey(self.prng.seed_value or 0),
                self._step)
            new_w, new_hb, new_vb, err = self._jit_fn_(
                key, self.weights.mem, self.hidden_bias.mem,
                self.visible_bias.mem, self.input.mem,
                numpy.float32(self.learning_rate))
        self.weights.map_invalidate()
        self.weights.mem = numpy.asarray(new_w)
        self.hidden_bias.map_invalidate()
        self.hidden_bias.mem = numpy.asarray(new_hb)
        self.visible_bias.map_invalidate()
        self.visible_bias.mem = numpy.asarray(new_vb)
        self.reconstruction_error = float(err)

    def reconstruct_error(self, data):
        """Deterministic mean-field v -> h -> v reconstruction MSE on
        arbitrary data (the held-out quality readout; no sampling)."""
        import jax
        import jax.numpy as jnp
        for arr in (self.weights, self.hidden_bias,
                    self.visible_bias):
            arr.map_read()
        v = jnp.asarray(numpy.reshape(data, (len(data), -1)),
                        jnp.float32)
        h = jax.nn.sigmoid(
            jnp.dot(v, jnp.asarray(self.weights.mem),
                    preferred_element_type=jnp.float32) +
            jnp.asarray(self.hidden_bias.mem))
        vr = jax.nn.sigmoid(
            jnp.dot(h, jnp.asarray(self.weights.mem).T,
                    preferred_element_type=jnp.float32) +
            jnp.asarray(self.visible_bias.mem))
        return float(jnp.mean((v - vr) ** 2))
