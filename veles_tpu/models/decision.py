"""Decision unit — epoch bookkeeping and the stop criterion.

Znicz-equivalent decision.DecisionGD: accumulates the evaluator's
per-minibatch metrics into per-class epoch totals, tracks the best
validation error, raises ``improved`` when a new best is reached, skips
gradient descent on non-TRAIN minibatches via the shared ``gd_skip``
Bool, and sets ``complete`` when ``fail_iterations`` epochs pass without
improvement or ``max_epochs`` is reached.
"""

from veles_tpu.loader.base import CLASS_NAME, TRAIN, VALID
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit

__all__ = ["DecisionBase", "DecisionGD", "DecisionMSE"]


class DecisionBase(Unit):
    """Epoch metric aggregation + stop control."""

    def __init__(self, workflow, **kwargs):
        super(DecisionBase, self).__init__(workflow, **kwargs)
        self.max_epochs = kwargs.get("max_epochs", None)
        self.fail_iterations = kwargs.get("fail_iterations", 100)
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.train_improved = Bool(False)
        self.gd_skip = Bool(False)
        # linked from loader:
        self.minibatch_class = None
        self.last_minibatch = None
        self.epoch_ended = None
        self.epoch_number = None
        self.class_lengths = None
        self.demand("minibatch_class", "last_minibatch", "class_lengths",
                    "epoch_ended", "epoch_number")
        self.epoch_metrics = [None, None, None]
        self.best_metric = None
        self.best_epoch = 0
        self.best_train_metric = None

    def initialize(self, **kwargs):
        super(DecisionBase, self).initialize(**kwargs)
        self._reset_epoch_accumulators()
        return True

    def _reset_epoch_accumulators(self):
        raise NotImplementedError

    def _accumulate_minibatch(self):
        raise NotImplementedError

    def _epoch_class_metric(self, class_index):
        """Finished class -> scalar metric (lower is better)."""
        raise NotImplementedError

    def run(self):
        self.gd_skip <<= (self.minibatch_class != TRAIN)
        self._accumulate_minibatch()
        if bool(self.last_minibatch):
            cls = self.minibatch_class
            self.epoch_metrics[cls] = self._epoch_class_metric(cls)
            self._on_class_ended(cls)
        if bool(self.epoch_ended):
            self._on_epoch_ended()

    def _on_class_ended(self, cls):
        # improvement is judged on VALID when present, else on TRAIN
        judge = VALID if self.class_lengths[VALID] > 0 else TRAIN
        if cls == judge:
            metric = self.epoch_metrics[cls]
            if self.best_metric is None or metric < self.best_metric:
                self.best_metric = metric
                self.best_epoch = self.epoch_number
                self.improved <<= True
            else:
                self.improved <<= False
        if cls == TRAIN:
            metric = self.epoch_metrics[TRAIN]
            better = (self.best_train_metric is None or
                      metric < self.best_train_metric)
            if better:
                self.best_train_metric = metric
            self.train_improved <<= better

    def get_metric_names(self):
        return {"Errors", "Best metric", "Best epoch"}

    def get_metric_values(self):
        return {
            "Errors": {CLASS_NAME[i]: self.epoch_metrics[i]
                       for i in range(3)},
            "Best metric": self.best_metric,
            "Best epoch": self.best_epoch,
        }

    def _on_epoch_ended(self):
        self.info("Epoch %d metrics: test %s, validation %s, train %s",
                  self.epoch_number,
                  self.epoch_metrics[0], self.epoch_metrics[1],
                  self.epoch_metrics[2])
        stop = False
        if self.max_epochs is not None and \
                self.epoch_number >= self.max_epochs:
            stop = True
        if self.best_metric is not None and \
                self.epoch_number - self.best_epoch > self.fail_iterations:
            stop = True
        if stop:
            self.complete <<= True
        self._reset_epoch_accumulators()


class DecisionGD(DecisionBase):
    """Classification: metric = error percentage from evaluator.n_err."""

    def __init__(self, workflow, **kwargs):
        super(DecisionGD, self).__init__(workflow, **kwargs)
        self.evaluator = None  # linked: needs .n_err per minibatch
        self.demand("evaluator")
        self.epoch_n_err = [0, 0, 0]

    def _reset_epoch_accumulators(self):
        self.epoch_n_err = [0, 0, 0]

    def _accumulate_minibatch(self):
        # evaluator.n_err may be a LAZY device scalar — lazy_add keeps
        # the accumulation an async jitted dispatch; the float() below
        # is the only sync point
        from veles_tpu.models.evaluator import lazy_add
        cls = self.minibatch_class
        self.epoch_n_err[cls] = lazy_add(self.epoch_n_err[cls],
                                         self.evaluator.n_err)

    def _epoch_class_metric(self, class_index):
        length = self.class_lengths[class_index]
        if length == 0:
            return None
        # forces the device sync (once per finished class, not per
        # minibatch) and normalizes to a plain float for logs/JSON
        return float(100.0 * self.epoch_n_err[class_index] / length)

    # -- master-slave contract: slaves ship per-job error counts; the
    # master merges them and performs the class/epoch-end bookkeeping
    # using its loader's flags (exact in sync mode, VELES-style
    # approximation under async pipelining).

    def generate_data_for_slave(self, slave=None):
        return {"complete": bool(self.complete)}

    def apply_data_from_master(self, data):
        self.complete <<= data.get("complete", False)

    def generate_data_for_master(self):
        # wire payload: concretize any lazy device scalars
        delta = [int(v) for v in self.epoch_n_err]
        self._reset_epoch_accumulators()
        return {"n_err": delta}

    def __getstate__(self):
        state = super(DecisionGD, self).__getstate__()
        if "epoch_n_err" in state:
            state["epoch_n_err"] = [int(v) for v in self.epoch_n_err]
        return state

    def apply_data_from_slave(self, data, slave=None):
        if not data:
            return
        for i, n in enumerate(data.get("n_err", ())):
            self.epoch_n_err[i] += n
        if bool(self.last_minibatch):
            cls = self.minibatch_class
            self.epoch_metrics[cls] = self._epoch_class_metric(cls)
            self._on_class_ended(cls)
        if bool(self.epoch_ended):
            self._on_epoch_ended()
        if bool(self.complete) and self.workflow is not None:
            self.workflow.on_workflow_finished()


class DecisionMSE(DecisionBase):
    """Regression: metric = epoch RMSE from evaluator.mse_sum."""

    def __init__(self, workflow, **kwargs):
        super(DecisionMSE, self).__init__(workflow, **kwargs)
        self.evaluator = None  # linked: needs .mse_sum / .n_samples
        self.demand("evaluator")
        self.epoch_sse = [0.0, 0.0, 0.0]

    def _reset_epoch_accumulators(self):
        self.epoch_sse = [0.0, 0.0, 0.0]

    def _accumulate_minibatch(self):
        from veles_tpu.models.evaluator import lazy_add
        cls = self.minibatch_class
        self.epoch_sse[cls] = lazy_add(self.epoch_sse[cls],
                                       self.evaluator.mse_sum)

    def _epoch_class_metric(self, class_index):
        import math
        length = self.class_lengths[class_index]
        if length == 0:
            return None
        # float() is the once-per-class device sync (see DecisionGD)
        return math.sqrt(float(self.epoch_sse[class_index]) / length)

    def __getstate__(self):
        state = super(DecisionMSE, self).__getstate__()
        if "epoch_sse" in state:
            state["epoch_sse"] = [float(v) for v in self.epoch_sse]
        return state
