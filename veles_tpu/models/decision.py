"""Decision unit — epoch bookkeeping and the stop criterion.

Znicz-equivalent decision.DecisionGD: accumulates the evaluator's
per-minibatch metrics into per-class epoch totals, tracks the best
validation error, raises ``improved`` when a new best is reached, skips
gradient descent on non-TRAIN minibatches via the shared ``gd_skip``
Bool, and sets ``complete`` when ``fail_iterations`` epochs pass without
improvement or ``max_epochs`` is reached.

Numerics health (docs/health.md): a non-finite metric is NEVER recorded
as improved/best (``NaN < best`` is silently False, and a NaN could
otherwise *become* best when no best exists yet), and the decision
doubles as the training-health watchdog — at each train-class end it
checks the consecutive-skip counters the guarded train steps maintain
and an EMA loss-spike threshold, raising ``diverged`` and invoking the
owning workflow's ``on_divergence`` hook (snapshot rollback + LR
backoff in StandardWorkflow) when training has gone off the rails.
"""

from veles_tpu.health import (
    DivergenceError, EmaSpikeWatch, is_finite_metric)
from veles_tpu.loader.base import CLASS_NAME, TRAIN, VALID
from veles_tpu.mutable import Bool
from veles_tpu.observe.flight import flight as _flight
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.units import Unit

__all__ = ["DecisionBase", "DecisionGD", "DecisionMSE"]


class DecisionBase(Unit):
    """Epoch metric aggregation + stop control + divergence watchdog.

    Watchdog kwargs (defaults are deliberately conservative so healthy
    noisy runs never trip):

    - ``watchdog`` (True): master switch for divergence detection.
    - ``skip_budget`` (16): consecutive guarded-step skips that count
      as divergence (sustained non-finite gradients/loss).
    - ``spike_factor`` (10.0) / ``spike_floor`` (1.0) / ``ema_beta``
      (0.5): trip when the train metric exceeds ``spike_factor *
      max(EMA, spike_floor)`` — the floor keeps near-zero converged
      metrics from turning ordinary noise into "spikes".
    """

    def __init__(self, workflow, **kwargs):
        super(DecisionBase, self).__init__(workflow, **kwargs)
        self.max_epochs = kwargs.get("max_epochs", None)
        self.fail_iterations = kwargs.get("fail_iterations", 100)
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.train_improved = Bool(False)
        self.gd_skip = Bool(False)
        # divergence watchdog
        self.diverged = Bool(False)
        self.watchdog = kwargs.get("watchdog", True)
        self.skip_budget = kwargs.get("skip_budget", 16)
        self.spike_factor = kwargs.get("spike_factor", 10.0)
        self.spike_floor = kwargs.get("spike_floor", 1.0)
        self.ema_beta = kwargs.get("ema_beta", 0.5)
        #: units exposing lazy skip_count / consecutive_skips counters
        #: (the gds, or the fused trainer); wired by the workflow
        self.health_sources = []
        # the ONE EMA spike discipline (health.EmaSpikeWatch), shared
        # with the serve canary comparator (docs/serving.md)
        self._spike_watch = EmaSpikeWatch(
            spike_factor=self.spike_factor,
            spike_floor=self.spike_floor, beta=self.ema_beta,
            label="train metric")
        self._skips_seen = 0
        # linked from loader:
        self.minibatch_class = None
        self.last_minibatch = None
        self.epoch_ended = None
        self.epoch_number = None
        self.class_lengths = None
        self.demand("minibatch_class", "last_minibatch", "class_lengths",
                    "epoch_ended", "epoch_number")
        self.epoch_metrics = [None, None, None]
        self.best_metric = None
        self.best_epoch = 0
        self.best_train_metric = None

    def initialize(self, **kwargs):
        super(DecisionBase, self).initialize(**kwargs)
        self._reset_epoch_accumulators()
        return True

    def _reset_epoch_accumulators(self):
        raise NotImplementedError

    def _accumulate_minibatch(self):
        raise NotImplementedError

    def _epoch_class_metric(self, class_index):
        """Finished class -> scalar metric (lower is better)."""
        raise NotImplementedError

    def run(self):
        self.gd_skip <<= (self.minibatch_class != TRAIN)
        self._accumulate_minibatch()
        if bool(self.last_minibatch):
            self._record_class_metric(self.minibatch_class)
            self._on_class_ended(self.minibatch_class)
        if bool(self.epoch_ended):
            self._on_epoch_ended()

    def _record_class_metric(self, cls):
        """Finished class: compute the metric and publish it to the
        telemetry registry (here and in the master's
        apply_data_from_slave path — already a plain float, the
        class-end sync happened in _epoch_class_metric).  Non-finite
        metrics stay out of the gauge: the heartbeat/status files must
        remain strict JSON, and the watchdog reports the divergence
        through its own channel."""
        metric = self._epoch_class_metric(cls)
        self.epoch_metrics[cls] = metric
        if metric is not None and is_finite_metric(metric):
            _registry.gauge("metric.%s" % CLASS_NAME[cls]).set(metric)

    @staticmethod
    def _metric_improves(metric, best):
        """True when ``metric`` is a real improvement over ``best``.
        Non-finite metrics NEVER improve: ``NaN < best`` is silently
        False, but ``best is None or NaN < best`` would record NaN as
        the first best — poisoning every later comparison (nothing
        beats NaN, so ``improved`` would never fire again)."""
        if not is_finite_metric(metric):
            return False
        return best is None or metric < best

    def _on_class_ended(self, cls):
        # improvement is judged on VALID when present, else on TRAIN
        judge = VALID if self.class_lengths[VALID] > 0 else TRAIN
        if cls == judge:
            metric = self.epoch_metrics[cls]
            if self._metric_improves(metric, self.best_metric):
                self.best_metric = metric
                self.best_epoch = self.epoch_number
                self.improved <<= True
            else:
                self.improved <<= False
        if cls == TRAIN:
            metric = self.epoch_metrics[TRAIN]
            better = self._metric_improves(metric,
                                           self.best_train_metric)
            if better:
                self.best_train_metric = metric
            self.train_improved <<= better
            self._check_divergence()

    # -- divergence watchdog (docs/health.md) -------------------------------

    def _health_counters(self):
        """Sync the health sources' lazy counters (once per finished
        train class — the same cadence as the metric sync, never per
        minibatch).  Returns (total_skips, max_consecutive_skips)."""
        total = 0
        consec = 0
        for unit in self.health_sources:
            skips = int(unit.skip_count)
            unit_consec = int(unit.consecutive_skips)
            total += skips
            consec = max(consec, unit_consec)
            hook = getattr(unit, "on_health_sync", None)
            if hook is not None:
                # ride the existing sync: e.g. the fused trainer's
                # bf16-compression -> f32 fallback reacts to fresh
                # skips here without ever adding a per-step host sync
                hook(skips=skips, consec=unit_consec)
        # publish to the telemetry registry HERE — this is the existing
        # once-per-class device sync, so dashboards/heartbeats read the
        # counters as plain ints without ever touching the device
        _registry.gauge("health.skip_count").set(total)
        _registry.gauge("health.consecutive_skips").set(consec)
        return total, consec

    def _check_divergence(self):
        if not self.watchdog or bool(self.diverged):
            return
        if self.workflow is not None and \
                self.workflow.workflow_mode == "slave":
            return  # the master owns recovery; slaves just ship metrics
        reasons = []
        total, consec = self._health_counters()
        fresh = total - self._skips_seen
        self._skips_seen = total
        if consec >= self.skip_budget:
            reasons.append(
                "%d consecutive non-finite train steps skipped "
                "(budget %d)" % (consec, self.skip_budget))
        metric = self.epoch_metrics[TRAIN]
        if metric is not None:
            if not is_finite_metric(metric):
                reasons.append("non-finite train metric %r" % (metric,))
            else:
                spike = self._spike_watch.update(metric)
                if spike is not None:
                    reasons.append(spike)
        if fresh and not reasons:
            self.warning(
                "numerics guard skipped %d non-finite train step(s) "
                "this epoch (consecutive max %d, budget %d)",
                fresh, consec, self.skip_budget)
        if reasons:
            self._trip("; ".join(reasons))

    def _trip(self, reason):
        """Divergence detected: raise the flag and hand recovery to the
        owning workflow (StandardWorkflow rolls back to the last
        verified snapshot and backs off the learning rate).  Without a
        handler this FAILS LOUDLY — converging to garbage silently is
        the one outcome the watchdog exists to prevent."""
        self.diverged <<= True
        self.error("training diverged at epoch %s: %s",
                   self.epoch_number, reason)
        # black-box dump BEFORE recovery mutates anything: the ring
        # holds the step spans and heartbeats leading into divergence
        _flight.dump(reason="divergence")
        handler = getattr(self.workflow, "on_divergence", None)
        if handler is None:
            raise DivergenceError(
                "training diverged (%s) and the workflow has no "
                "on_divergence recovery hook" % reason)
        handler(reason)

    def reset_divergence(self):
        """Post-rollback reset (called by the workflow's recovery hook
        after counters were zeroed): the watchdog starts a fresh
        observation window."""
        self.diverged <<= False
        self._spike_watch.reset()
        self._skips_seen = 0

    def get_metric_names(self):
        return {"Errors", "Best metric", "Best epoch"}

    def get_metric_values(self):
        return {
            "Errors": {CLASS_NAME[i]: self.epoch_metrics[i]
                       for i in range(3)},
            "Best metric": self.best_metric,
            "Best epoch": self.best_epoch,
        }

    def _on_epoch_ended(self):
        _registry.gauge("train.epoch").set(int(self.epoch_number))
        self.info("Epoch %d metrics: test %s, validation %s, train %s",
                  self.epoch_number,
                  self.epoch_metrics[0], self.epoch_metrics[1],
                  self.epoch_metrics[2])
        stop = False
        if self.max_epochs is not None and \
                self.epoch_number >= self.max_epochs:
            stop = True
        if self.best_metric is not None and \
                self.epoch_number - self.best_epoch > self.fail_iterations:
            stop = True
        if stop:
            self.complete <<= True
        self._reset_epoch_accumulators()


class DecisionGD(DecisionBase):
    """Classification: metric = error percentage from evaluator.n_err."""

    def __init__(self, workflow, **kwargs):
        super(DecisionGD, self).__init__(workflow, **kwargs)
        self.evaluator = None  # linked: needs .n_err per minibatch
        self.demand("evaluator")
        self.epoch_n_err = [0, 0, 0]

    def _reset_epoch_accumulators(self):
        self.epoch_n_err = [0, 0, 0]

    def _accumulate_minibatch(self):
        # evaluator.n_err may be a LAZY device scalar — lazy_add keeps
        # the accumulation an async jitted dispatch; the float() below
        # is the only sync point
        from veles_tpu.models.evaluator import lazy_add
        cls = self.minibatch_class
        self.epoch_n_err[cls] = lazy_add(self.epoch_n_err[cls],
                                         self.evaluator.n_err)

    def _epoch_class_metric(self, class_index):
        length = self.class_lengths[class_index]
        if length == 0:
            return None
        # forces the device sync (once per finished class, not per
        # minibatch) and normalizes to a plain float for logs/JSON
        return float(100.0 * self.epoch_n_err[class_index] / length)

    # -- master-slave contract: slaves ship per-job error counts; the
    # master merges them and performs the class/epoch-end bookkeeping
    # using its loader's flags (exact in sync mode, VELES-style
    # approximation under async pipelining).

    def generate_data_for_slave(self, slave=None):
        return {"complete": bool(self.complete)}

    def apply_data_from_master(self, data):
        self.complete <<= data.get("complete", False)

    def generate_data_for_master(self):
        # wire payload: concretize any lazy device scalars
        delta = [int(v) for v in self.epoch_n_err]
        self._reset_epoch_accumulators()
        return {"n_err": delta}

    def __getstate__(self):
        state = super(DecisionGD, self).__getstate__()
        if "epoch_n_err" in state:
            state["epoch_n_err"] = [int(v) for v in self.epoch_n_err]
        return state

    def apply_data_from_slave(self, data, slave=None):
        if not data:
            return
        for i, n in enumerate(data.get("n_err", ())):
            self.epoch_n_err[i] += n
        if bool(self.last_minibatch):
            # same class-end path as run(): the master's telemetry
            # (metric gauges, health counters) must not go dark just
            # because the hot loop runs on the slaves
            self._record_class_metric(self.minibatch_class)
            self._on_class_ended(self.minibatch_class)
        if bool(self.epoch_ended):
            self._on_epoch_ended()
        if bool(self.complete) and self.workflow is not None:
            self.workflow.on_workflow_finished()


class DecisionMSE(DecisionBase):
    """Regression: metric = epoch RMSE from evaluator.mse_sum."""

    def __init__(self, workflow, **kwargs):
        super(DecisionMSE, self).__init__(workflow, **kwargs)
        self.evaluator = None  # linked: needs .mse_sum / .n_samples
        self.demand("evaluator")
        self.epoch_sse = [0.0, 0.0, 0.0]

    def _reset_epoch_accumulators(self):
        self.epoch_sse = [0.0, 0.0, 0.0]

    def _accumulate_minibatch(self):
        from veles_tpu.models.evaluator import lazy_add
        cls = self.minibatch_class
        self.epoch_sse[cls] = lazy_add(self.epoch_sse[cls],
                                       self.evaluator.mse_sum)

    def _epoch_class_metric(self, class_index):
        import math
        length = self.class_lengths[class_index]
        if length == 0:
            return None
        # float() is the once-per-class device sync (see DecisionGD)
        return math.sqrt(float(self.epoch_sse[class_index]) / length)

    def __getstate__(self):
        state = super(DecisionMSE, self).__getstate__()
        if "epoch_sse" in state:
            state["epoch_sse"] = [float(v) for v in self.epoch_sse]
        return state
