"""Transformer units: LayerNorm, MultiHeadAttention, TransformerBlock.

No reference behavior to match (the 2015 platform predates attention);
this is the model zoo's first post-recurrent sequence family, built on
the unit contracts the rest of the zoo uses:

- forward math lives in pure ``apply(params, x, **static)`` class
  methods, so the same code serves the per-unit jit path, the fused
  whole-step compiler (``StandardWorkflow.fuse``), and the numpy
  fallback;
- parameters pack into the ONE (weights, bias) Array pair per unit
  (the LSTM precedent: gates pack on an axis) — ``MultiHeadAttention``
  stores ``(D, 4D)`` = [Wq | Wk | Wv | Wo], ``TransformerBlock`` packs
  its six matrices/gains into one flat f32 vector with static offsets
  (solver updates are elementwise, so packing never changes them);
- backwards are stock ``jax.vjp`` through the forward (the rnn.py
  pattern) guarded by ``finite_guard``, so a poisoned cotangent
  cascades and the whole chain skips the step together.  When
  ``VELES_PALLAS_BWD`` resolves on, the attention inside ``apply`` is
  :func:`veles_tpu.ops.attention.flash_attention` — a custom_vjp whose
  backward is the hand-scheduled Pallas pair — so the SAME vjp drives
  the flash backward; knob off runs
  :func:`~veles_tpu.ops.attention.attention_reference` with stock
  autodiff (the documented bit-exact fallback).

Blocks are pre-LN (``x + attn(ln(x))``; ``h + ffn(ln(h))``) with a
position-wise strict-ReLU MLP; activations keep (B, T, D), so blocks
compose into homogeneous stacks — exactly the shape contract the
pipeline-parallel stage split needs (parallel/pipeline.py).
"""

import numpy

from veles_tpu.models.nn_units import ForwardBase, GradientDescentBase

__all__ = ["LayerNorm", "MultiHeadAttention", "TransformerBlock",
           "GDLayerNorm", "GDMultiHeadAttention", "GDTransformerBlock",
           "layer_norm", "multi_head_attention", "attention_heads",
           "position_wise_mlp", "block_param_sizes",
           "split_block_params"]


# -- pure math (shared by the unit classes and the parallel layer) ----------


def layer_norm(x, gamma, beta, eps=1e-5):
    """Per-token normalization over the feature axis, f32 statistics."""
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * (1.0 / jnp.sqrt(var + eps))
    return (y * gamma + beta).astype(x.dtype)


def _attend(q, k, v, pallas_bwd):
    """Route one (B*H, T, dh) attention through the flash kernel or
    the stock reference per the VELES_PALLAS_BWD contract."""
    from veles_tpu.ops.attention import (attention_reference,
                                         flash_attention)
    if pallas_bwd is None:
        from veles_tpu.ops.common import pallas_bwd_enabled
        pallas_bwd = pallas_bwd_enabled()
    fn = flash_attention if pallas_bwd else attention_reference
    return fn(q, k, v)


def attention_heads(x, w_qkv, b_qkv, heads, pallas_bwd=None):
    """QKV projection + per-head attention + head merge over (B, T, *):
    the sub-layer shared VERBATIM by the single-device block and the
    tensor-parallel shard (parallel/tensor.py slices ``w_qkv`` to its
    heads' columns and passes its local head count — the head dim
    ``dh`` comes from the PROJECTION width, so local and global calls
    run identical per-head math).  Returns the merged (B, T, width/3)
    activations in x's dtype, BEFORE the output projection."""
    import jax.numpy as jnp
    b, t = x.shape[0], x.shape[1]
    dh = w_qkv.shape[1] // 3 // heads
    z = jnp.einsum("btf,fg->btg", x, w_qkv,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    if b_qkv is not None:
        z = z + b_qkv.astype(x.dtype)
    q, k, v = jnp.split(z, 3, axis=-1)

    def fold(a):  # (B, T, H*dh) -> (B*H, T, dh)
        a = a.reshape(b, t, heads, dh)
        return a.transpose(0, 2, 1, 3).reshape(b * heads, t, dh)

    o = _attend(fold(q), fold(k), fold(v), pallas_bwd)
    return o.reshape(b, heads, t, dh).transpose(0, 2, 1, 3).reshape(
        b, t, heads * dh)


def position_wise_mlp(x, w1, b1, w2):
    """ReLU(x W1 + b1) W2 in f32 — the block's MLP core shared with
    the tensor-parallel shard (which passes column/row slices), kept
    BEFORE the final bias so the TP path can psum the partial first.
    Returns the f32 pre-b2 activations."""
    import jax.numpy as jnp
    z = jnp.einsum("btf,fg->btg", x, w1,
                   preferred_element_type=jnp.float32) + b1
    z = jnp.maximum(z, 0)
    return jnp.einsum("btf,fg->btg", z.astype(x.dtype), w2,
                      preferred_element_type=jnp.float32)


def multi_head_attention(x, w_qkv, b_qkv, w_o, b_o, heads,
                         pallas_bwd=None):
    """Multi-head scaled-dot-product attention over (B, T, D):
    one packed QKV projection, heads folded into the leading dim for
    the kernel, merged output projection."""
    import jax.numpy as jnp
    o = attention_heads(x, w_qkv, b_qkv, heads, pallas_bwd)
    out = jnp.einsum("btf,fg->btg", o, w_o,
                     preferred_element_type=jnp.float32)
    if b_o is not None:
        out = out + b_o
    return out.astype(x.dtype)


def block_param_sizes(d, hidden):
    """(name, shape) layout of one TransformerBlock's packed weights
    and bias vectors — the ONE definition the unit packer, the fused
    apply, and the tensor-parallel splitter all read."""
    weights = [("ln1_gamma", (d,)), ("w_qkv", (d, 3 * d)),
               ("w_o", (d, d)), ("ln2_gamma", (d,)),
               ("w1", (d, hidden)), ("w2", (hidden, d))]
    bias = [("ln1_beta", (d,)), ("b_qkv", (3 * d,)), ("b_o", (d,)),
            ("ln2_beta", (d,)), ("b1", (hidden,)), ("b2", (d,))]
    return weights, bias


def _unpack(vec, layout):
    pieces, offset = {}, 0
    for name, shape in layout:
        size = int(numpy.prod(shape))
        pieces[name] = vec[offset:offset + size].reshape(shape)
        offset += size
    return pieces


def split_block_params(weights, bias, d, hidden):
    """Packed flat (weights, bias) -> name->array dicts."""
    w_layout, b_layout = block_param_sizes(d, hidden)
    return _unpack(weights, w_layout), _unpack(bias, b_layout)


def transformer_block(x, w, b, *, heads, hidden, eps=1e-5,
                      pallas_bwd=None):
    """One pre-LN block over packed flat params:
    ``h = x + MHA(LN1(x)); y = h + ReLU(LN2(h) W1 + b1) W2 + b2``."""
    d = x.shape[-1]
    wp, bp = split_block_params(w, b, d, hidden)
    h = x + multi_head_attention(
        layer_norm(x, wp["ln1_gamma"], bp["ln1_beta"], eps),
        wp["w_qkv"], bp["b_qkv"], wp["w_o"], bp["b_o"], heads,
        pallas_bwd)
    z = position_wise_mlp(
        layer_norm(h, wp["ln2_gamma"], bp["ln2_beta"], eps),
        wp["w1"], bp["b1"], wp["w2"]) + bp["b2"]
    return (h + z.astype(x.dtype)).astype(x.dtype)


def _uniform(rng, shape, fan_in):
    bound = 1.0 / numpy.sqrt(fan_in) if fan_in else 0.01
    return rng.uniform(-bound, bound, shape).astype(numpy.float32)


def init_block_params(d, hidden, rng):
    """Packed (weights, bias) init: LN gains 1, matrices 1/sqrt(fan_in)
    uniform, every bias/beta 0."""
    w_layout, b_layout = block_param_sizes(d, hidden)
    pieces = []
    for name, shape in w_layout:
        if name.endswith("gamma"):
            pieces.append(numpy.ones(shape, numpy.float32))
        else:
            pieces.append(_uniform(rng, shape, shape[0]).ravel())
    weights = numpy.concatenate([p.ravel() for p in pieces])
    bias = numpy.zeros(sum(int(numpy.prod(s)) for _, s in b_layout),
                       numpy.float32)
    return weights, bias


# -- forward units -----------------------------------------------------------


class _SequenceUnit(ForwardBase):
    """Shared (B, T, D)-preserving plumbing: output shape mirrors the
    input, the feature dim comes from the linked input at initialize."""

    def _seq_shape(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        shape = self.input.shape
        if len(shape) != 3:
            raise ValueError(
                "%s expects (batch, time, features) input, got %s"
                % (type(self).__name__, (shape,)))
        return shape

    def _ensure_output(self, shape):
        if not self.output:
            self.output.mem = numpy.zeros(shape, numpy.float32)


class LayerNorm(_SequenceUnit):
    """y = gamma * (x - mean) / sqrt(var + eps) + beta over the
    feature axis; weights = gamma, bias = beta."""

    MAPPING = "layer_norm"

    def __init__(self, workflow, **kwargs):
        super(LayerNorm, self).__init__(workflow, **kwargs)
        self.eps = kwargs.get("eps", 1e-5)

    def static_config(self):
        return {"eps": self.eps}

    def create_params(self):
        shape = self._seq_shape()
        self._ensure_output(shape)
        if self.weights:
            return  # restored from snapshot
        d = shape[-1]
        self.weights.mem = numpy.ones((d,), numpy.float32)
        if self.include_bias:
            self.bias.mem = numpy.zeros((d,), numpy.float32)

    @classmethod
    def apply(cls, params, x, *, eps=1e-5):
        import jax.numpy as jnp
        bias = params.get("bias")
        beta = jnp.zeros((), x.dtype) if bias is None else bias
        return layer_norm(x, params["weights"], beta, eps)


class MultiHeadAttention(_SequenceUnit):
    """Multi-head scaled-dot-product attention, (B, T, D) -> same.
    weights pack (D, 4D) = [Wq | Wk | Wv | Wo]; bias packs (4D,)."""

    MAPPING = "attention"

    def __init__(self, workflow, **kwargs):
        super(MultiHeadAttention, self).__init__(workflow, **kwargs)
        self.heads = kwargs.get("heads", 1)

    def static_config(self):
        return {"heads": self.heads}

    def create_params(self):
        shape = self._seq_shape()
        d = shape[-1]
        if d % self.heads:
            raise ValueError("features %d %% heads %d != 0"
                             % (d, self.heads))
        self._ensure_output(shape)
        if self.weights:
            return
        weights = numpy.zeros((d, 4 * d), numpy.float32)
        self.fill_array(weights, self.weights_filling,
                        self.weights_stddev, d)
        self.weights.mem = weights
        if self.include_bias:
            self.bias.mem = numpy.zeros((4 * d,), numpy.float32)

    @classmethod
    def apply(cls, params, x, *, heads, pallas_bwd=None):
        d = x.shape[-1]
        w = params["weights"]
        b = params.get("bias")
        return multi_head_attention(
            x, w[:, :3 * d], None if b is None else b[:3 * d],
            w[:, 3 * d:], None if b is None else b[3 * d:], heads,
            pallas_bwd)


class TransformerBlock(_SequenceUnit):
    """One pre-LN transformer block (attention + position-wise MLP
    with residuals), packed into one flat (weights, bias) pair — see
    :func:`block_param_sizes` for the layout."""

    MAPPING = "transformer"

    def __init__(self, workflow, **kwargs):
        super(TransformerBlock, self).__init__(workflow, **kwargs)
        self.heads = kwargs.get("heads", 1)
        self.hidden = kwargs.get("hidden")
        self.eps = kwargs.get("eps", 1e-5)

    def static_config(self):
        return {"heads": self.heads, "hidden": self.hidden,
                "eps": self.eps}

    def create_params(self):
        shape = self._seq_shape()
        d = shape[-1]
        if self.hidden is None:
            self.hidden = 4 * d
        if d % self.heads:
            raise ValueError("features %d %% heads %d != 0"
                             % (d, self.heads))
        self._ensure_output(shape)
        if self.weights:
            return
        w_layout, b_layout = block_param_sizes(d, self.hidden)
        pieces = []
        for name, piece_shape in w_layout:
            if name.endswith("gamma"):
                pieces.append(numpy.ones(piece_shape, numpy.float32))
            else:
                arr = numpy.zeros(piece_shape, numpy.float32)
                self.fill_array(arr, self.weights_filling,
                                self.weights_stddev, piece_shape[0])
                pieces.append(arr)
        self.weights.mem = numpy.concatenate(
            [p.ravel() for p in pieces])
        if self.include_bias:
            self.bias.mem = numpy.zeros(
                sum(int(numpy.prod(s)) for _, s in b_layout),
                numpy.float32)

    @classmethod
    def apply(cls, params, x, *, heads, hidden, eps=1e-5,
              pallas_bwd=None):
        return transformer_block(x, params["weights"], params["bias"],
                                 heads=heads, hidden=hidden, eps=eps,
                                 pallas_bwd=pallas_bwd)


# -- gradient-descent units --------------------------------------------------


class _GDAutodiff(GradientDescentBase):
    """Stock-vjp backward over FORWARD_CLS.apply (the rnn.py pattern):
    one jitted call produces err_input + the guarded solver update.
    The vjp drives whatever backward the forward's static config
    routes to — with VELES_PALLAS_BWD on, attention's custom_vjp runs
    the hand-scheduled Pallas pair."""

    MAPPING = None  # abstract
    FORWARD_CLS = None

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, **static):
        import jax
        import jax.numpy as jnp
        W = state["weights"]
        b = state["bias"] if include_bias else None

        def fwd(W_, b_, x_):
            return cls.FORWARD_CLS.apply(
                {"weights": W_, "bias": b_}, x_, **static)

        _, vjp = jax.vjp(fwd, W, b, x)
        grad_w, grad_b, err_input = vjp(err_output.astype(y.dtype))
        if not need_err_input:
            err_input = None
        grad_w = GradientDescentBase.regularized(
            grad_w.astype(jnp.float32), W, hyper["weights_decay"],
            hyper["l1_vs_l2"])
        new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
            solver, W, grad_w.astype(W.dtype), state["accum_weights"],
            state["accum2_weights"], hyper["learning_rate"],
            hyper["gradient_moment"], hyper["adadelta_rho"],
            hyper["solver_epsilon"])
        new_state = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w}
        if include_bias and grad_b is not None:
            new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                solver, b, grad_b.astype(b.dtype), state["accum_bias"],
                state["accum2_bias"], hyper["learning_rate_bias"],
                hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            new_state.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
        # numerics guard: a non-finite gradient skips the update and
        # cascades through err_input so the whole chain skips together
        # (docs/health.md)
        new_state = GradientDescentBase.finite_guard(
            state, new_state, grad_w,
            grad_b if include_bias else None)
        return err_input, new_state


class GDLayerNorm(_GDAutodiff):
    MAPPING = "layer_norm"
    FORWARD_CLS = LayerNorm

    def __init__(self, workflow, **kwargs):
        super(GDLayerNorm, self).__init__(workflow, **kwargs)
        self.eps = kwargs.get("eps", 1e-5)

    def backward_static(self):
        return {"eps": self.eps}


class GDMultiHeadAttention(_GDAutodiff):
    MAPPING = "attention"
    FORWARD_CLS = MultiHeadAttention

    def __init__(self, workflow, **kwargs):
        super(GDMultiHeadAttention, self).__init__(workflow, **kwargs)
        self.heads = kwargs.get("heads", 1)

    def backward_static(self):
        return {"heads": self.heads}


class GDTransformerBlock(_GDAutodiff):
    MAPPING = "transformer"
    FORWARD_CLS = TransformerBlock

    def __init__(self, workflow, **kwargs):
        super(GDTransformerBlock, self).__init__(workflow, **kwargs)
        self.heads = kwargs.get("heads", 1)
        self.hidden = kwargs.get("hidden")
        self.eps = kwargs.get("eps", 1e-5)

    def backward_static(self):
        hidden = self.hidden
        if hidden is None:
            # the forward resolved hidden = 4*D at create_params; the
            # packed length determines it uniquely: L = 2D + 4D^2 +
            # 2*D*hidden, with weights linked BY OBJECT from the fwd
            d = self.input.shape[-1]
            packed = int(numpy.prod(self.weights.shape))
            self.hidden = (packed - 2 * d - 4 * d * d) // (2 * d)
        return {"heads": self.heads, "hidden": self.hidden,
                "eps": self.eps}
