"""Base classes for forward and gradient-descent units.

Counterpart of Znicz's nn_units.Forward / nn_units.GradientDescentBase
(empty submodule; capabilities per docs/source/manualrst_veles_algorithms
.rst:150-165 — weight-init schemes, per-layer hyperparameters, L1/L2
regularization, solvers).

Design: parameters (weights/bias + solver state) are veles_tpu Arrays
shared BY OBJECT between the forward unit and its GD unit, so a device-side
update by one is immediately visible to the other with no host traffic.
Forward math lives in pure static methods over (params, x) so the same
code serves three paths: per-unit jit (here), the fused whole-step
compiler, and the numpy fallback backend.
"""

import numpy

from veles_tpu import prng
from veles_tpu.backends import NumpyDevice
from veles_tpu.config import root
from veles_tpu.memory import Array
from veles_tpu.units import Unit

__all__ = ["ForwardBase", "GradientDescentBase"]


def _is_jax_device(device):
    return device is not None and device.exists and \
        not isinstance(device, NumpyDevice)


class ForwardBase(Unit):
    """Forward propagation unit: input -> output with trainable params.

    kwargs (per-layer hyperparameters):
      weights_filling: "uniform" | "gaussian" | "constant"
      weights_stddev: spread; default 1/sqrt(fan_in) for uniform
      bias_filling / bias_stddev: likewise for bias
      include_bias: bool (default True)
      weights_transposed: kept for reference-parity introspection; this
        build always stores (fan_in, fan_out) which is the natural MXU
        layout (the reference stored (fan_out, fan_in)).
    """

    def __init__(self, workflow, **kwargs):
        super(ForwardBase, self).__init__(workflow, **kwargs)
        self.input = None  # linked from loader/previous unit (Array)
        self.output = Array()
        self.weights = Array()
        self.bias = Array()
        self.include_bias = kwargs.get("include_bias", True)
        self.weights_filling = kwargs.get("weights_filling", "uniform")
        self.weights_stddev = kwargs.get("weights_stddev", None)
        self.bias_filling = kwargs.get("bias_filling", "uniform")
        self.bias_stddev = kwargs.get("bias_stddev", None)
        self.prng = kwargs.get("prng", prng.get())
        self.device = None
        self._jit_fn_ = None
        self.demand("input")

    def init_unpickled(self):
        super(ForwardBase, self).init_unpickled()
        self._jit_fn_ = None

    # -- parameter creation -------------------------------------------------

    def fill_array(self, arr, filling, stddev, fan_in):
        """Weight-init schemes (manualrst_veles_algorithms.rst:150-165)."""
        if stddev is None:
            stddev = 1.0 / numpy.sqrt(fan_in) if fan_in else 0.01
        if filling == "uniform":
            self.prng.fill(arr, -stddev, stddev)
        elif filling == "gaussian":
            self.prng.fill_normal(arr, 0.0, stddev)
        elif filling == "constant":
            arr[:] = stddev
        else:
            raise ValueError("unknown filling %r" % filling)

    # -- device plumbing ----------------------------------------------------

    def on_device(self):
        return _is_jax_device(self.device)

    def initialize(self, device=None, **kwargs):
        self.device = device
        super(ForwardBase, self).initialize(**kwargs)
        self.create_params()
        for arr in self.param_arrays():
            if arr:
                arr.initialize(self.device)
        return True

    def create_params(self):
        """Allocate weights/bias from the input shape; idempotent on
        snapshot restore."""
        raise NotImplementedError

    def param_arrays(self):
        return [self.weights, self.bias]

    # -- the pure functions -------------------------------------------------

    @staticmethod
    def apply(params, x, **static):
        """params dict, x device array -> output device array.  ``static``
        holds compile-time layer config (strides, padding, ...)."""
        raise NotImplementedError

    def static_config(self):
        """Compile-time kwargs baked into the jitted apply."""
        return {}

    def params_dict(self):
        return {"weights": self.weights.devmem,
                "bias": self.bias.devmem if self.include_bias else None}

    def params_numpy(self):
        self.weights.map_read()
        if self.include_bias:
            self.bias.map_read()
        return {"weights": self.weights.mem,
                "bias": self.bias.mem if self.include_bias else None}

    # -- execution ----------------------------------------------------------

    def run(self):
        if self.on_device():
            self._device_run()
        else:
            self._numpy_run()

    def _device_run(self):
        import functools
        import jax
        if self._jit_fn_ is None:
            self._jit_fn_ = jax.jit(functools.partial(
                type(self).apply, **self.static_config()))
        out = self._jit_fn_(self.params_dict(),
                            self.input.device_array(self.device))
        self.output.set_device_array(out, self.device)
        if root.common.get("sync_run", False):
            # honest per-unit timings (reference --sync-run,
            # accelerated_units.py:186-193)
            jax.block_until_ready(out)

    def _numpy_run(self):
        from veles_tpu.backends import host_compute_context
        params = self.params_numpy()
        self.input.map_read()
        with host_compute_context(self.device):
            out = numpy.asarray(type(self).apply(
                params, self.input.mem, **self.static_config()))
        self.output.map_invalidate()
        self.output.mem = out

    # -- master-slave contract (job-farming DP, SURVEY.md section 2.6) -----
    #
    # Master ships canonical params with each job; the slave trains on its
    # minibatch and returns the param DELTA; the master merges deltas
    # additively (Downpour-style async SGD).  On-pod DP does NOT use this
    # path — it rides ICI psum via veles_tpu.parallel.

    def generate_data_for_slave(self, slave=None):
        payload = {}
        for name, arr in (("weights", self.weights), ("bias", self.bias)):
            if arr:
                arr.map_read()
                payload[name] = numpy.array(arr.mem)
        return payload or None

    def apply_data_from_master(self, data):
        if not data:
            return
        self._job_start_params_ = {}
        for name, arr in (("weights", self.weights), ("bias", self.bias)):
            value = data.get(name)
            if value is not None and arr:
                arr.map_invalidate()
                arr.mem = numpy.array(value)
                self._job_start_params_[name] = numpy.array(value)

    def generate_data_for_master(self):
        start = getattr(self, "_job_start_params_", None)
        if not start:
            return None
        delta = {}
        for name, arr in (("weights", self.weights), ("bias", self.bias)):
            if name in start and arr:
                arr.map_read()
                delta[name] = arr.mem - start[name]
        return delta or None

    def apply_data_from_slave(self, data, slave=None):
        if not data:
            return
        for name, arr in (("weights", self.weights), ("bias", self.bias)):
            value = data.get(name)
            if value is not None and arr:
                arr.map_write()
                arr.mem += value


class GradientDescentBase(Unit):
    """Backward + parameter update for one forward unit.

    kwargs: learning_rate, learning_rate_bias, weights_decay (L2/L1 per
    l1_vs_l2 blend), gradient_moment (momentum), solver
    ("momentum" | "adagrad" | "adadelta"), adadelta_rho, solver_epsilon.

    Reference-parity semantics: err_output is dL/d(output) arriving from
    the NEXT unit (or the evaluator); run() produces err_input =
    dL/d(input) for the PREVIOUS unit and applies the update in the same
    fused jitted call.
    """

    def __init__(self, workflow, **kwargs):
        super(GradientDescentBase, self).__init__(workflow, **kwargs)
        self.input = None
        self.output = None
        self.err_output = None   # linked: next gd's err_input / evaluator
        self.err_input = Array()
        self.weights = None      # linked BY OBJECT from the forward unit
        self.bias = None
        self.include_bias = kwargs.get("include_bias", True)
        self.learning_rate = kwargs.get("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.get(
            "learning_rate_bias", kwargs.get("learning_rate", 0.01))
        self.weights_decay = kwargs.get("weights_decay", 0.0)
        self.weights_decay_bias = kwargs.get("weights_decay_bias", 0.0)
        self.l1_vs_l2 = kwargs.get("l1_vs_l2", 0.0)
        self.gradient_moment = kwargs.get("gradient_moment", 0.0)
        self.gradient_moment_bias = kwargs.get(
            "gradient_moment_bias", kwargs.get("gradient_moment", 0.0))
        self.solver = kwargs.get("solver", "momentum")
        self.adadelta_rho = kwargs.get("adadelta_rho", 0.95)
        self.solver_epsilon = kwargs.get("solver_epsilon", 1e-6)
        self.need_err_input = kwargs.get("need_err_input", True)
        self.device = None
        self._jit_fn_ = None
        # solver state (velocity / grad accumulators), created lazily
        self.accum_weights = Array()
        self.accum_bias = Array()
        self.accum2_weights = Array()
        self.accum2_bias = Array()
        # numerics health (docs/health.md): updates whose gradients
        # were non-finite are SKIPPED; both counters stay lazy device
        # scalars, synced by the decision once per finished class
        self.skip_count = 0
        self.consecutive_skips = 0
        self.demand("input", "output", "err_output", "weights")

    def init_unpickled(self):
        super(GradientDescentBase, self).init_unpickled()
        self._jit_fn_ = None

    def on_device(self):
        return _is_jax_device(self.device)

    def initialize(self, device=None, **kwargs):
        self.device = device
        super(GradientDescentBase, self).initialize(**kwargs)
        self._init_solver_state()
        return True

    def _init_solver_state(self):
        pairs = [(self.accum_weights, self.weights),
                 (self.accum_bias,
                  self.bias if self.include_bias else None)]
        if self.solver == "adadelta":
            pairs += [(self.accum2_weights, self.weights),
                      (self.accum2_bias,
                       self.bias if self.include_bias else None)]
        for accum, param in pairs:
            if param and not accum:
                accum.mem = numpy.zeros(param.shape, param.dtype)
            if accum:  # (re)attach, incl. after snapshot restore
                accum.initialize(self.device)

    # -- hyperparameters bundled for the pure function ----------------------

    def hyper_dict(self):
        return {
            "learning_rate": self.learning_rate,
            "learning_rate_bias": self.learning_rate_bias,
            "weights_decay": self.weights_decay,
            "weights_decay_bias": self.weights_decay_bias,
            "l1_vs_l2": self.l1_vs_l2,
            "gradient_moment": self.gradient_moment,
            "gradient_moment_bias": self.gradient_moment_bias,
            "adadelta_rho": self.adadelta_rho,
            "solver_epsilon": self.solver_epsilon,
        }

    @staticmethod
    def regularized(grad, param, decay, l1_vs_l2):
        """L1/L2-blended weight decay gradient term."""
        import jax.numpy as jnp
        return grad + decay * ((1.0 - l1_vs_l2) * param +
                               l1_vs_l2 * jnp.sign(param))

    @staticmethod
    def select_state(finite, new_state, old_state):
        """``where(finite, new, old)`` over one state dict's leaves —
        the single definition of the skip-step fallback, shared by the
        per-unit guard below and the fused step (compiler.py) so the
        two paths can never drift apart.  ``None`` leaves and leaves
        that ARE the old object (param-less passthroughs) are kept
        as-is."""
        import jax.numpy as jnp
        selected = {}
        for key, value in new_state.items():
            old = old_state.get(key)
            selected[key] = value if (value is None or old is None or
                                      value is old) else \
                jnp.where(finite, value, old)
        return selected

    @staticmethod
    def finite_guard(state, new_state, *grads):
        """Skip-step guard shared by every guarded backward: when any
        gradient in ``grads`` carries a non-finite value, every leaf of
        ``new_state`` falls back to its pre-step value in ``state`` —
        params AND solver accumulators stay bit-identical to never
        having run the step.  Adds the int32 ``"skipped"`` flag (0/1)
        to the returned dict; callers pop it for their lazy skip
        accounting (it never reaches ``_adopt_state``'s fixed key
        set)."""
        import jax.numpy as jnp
        finite = jnp.asarray(True)
        for grad in grads:
            if grad is not None:
                finite = finite & jnp.isfinite(grad).all()
        guarded = GradientDescentBase.select_state(finite, new_state,
                                                   state)
        guarded["skipped"] = (~finite).astype(jnp.int32)
        return guarded

    @staticmethod
    def solver_update(solver, param, grad, accum, accum2, lr, moment,
                      rho, eps):
        """One solver step; returns (new_param, new_accum, new_accum2).

        momentum:  v = moment*v + lr*g;            p -= v
        adagrad:   a += g*g;                       p -= lr*g/sqrt(a+eps)
        adadelta:  a  = rho*a + (1-rho)*g*g
                   d  = g*sqrt(a2+eps)/sqrt(a+eps); p -= lr*d
                   a2 = rho*a2 + (1-rho)*d*d
        (manualrst_veles_algorithms.rst solver list: SGD+momentum /
        AdaGrad / AdaDelta.)
        """
        import jax.numpy as jnp
        if solver == "momentum":
            v = moment * accum + lr * grad
            return param - v, v, accum2
        if solver == "adagrad":
            a = accum + grad * grad
            return param - lr * grad / jnp.sqrt(a + eps), a, accum2
        if solver == "adadelta":
            a = rho * accum + (1.0 - rho) * grad * grad
            d = grad * jnp.sqrt(accum2 + eps) / jnp.sqrt(a + eps)
            a2 = rho * accum2 + (1.0 - rho) * d * d
            return param - lr * d, a, a2
        raise ValueError("unknown solver %r" % solver)

    # -- the pure backward --------------------------------------------------

    @staticmethod
    def backward(state, hyper, x, y, err_output, *, solver, include_bias,
                 need_err_input, **static):
        """state dict (weights/bias/accums) -> (err_input, new_state)."""
        raise NotImplementedError

    def backward_static(self):
        """Compile-time kwargs baked into the jitted backward."""
        return {}

    def state_dict(self):
        d = {"weights": self.weights.devmem,
             "accum_weights": self.accum_weights.devmem,
             "accum2_weights": (self.accum2_weights.devmem
                                if self.accum2_weights else None)}
        if self.include_bias and self.bias:
            d["bias"] = self.bias.devmem
            d["accum_bias"] = self.accum_bias.devmem
            d["accum2_bias"] = (self.accum2_bias.devmem
                                if self.accum2_bias else None)
        else:
            d["bias"] = d["accum_bias"] = d["accum2_bias"] = None
        return d

    def state_numpy(self):
        arrays = [self.weights, self.accum_weights, self.accum2_weights,
                  self.bias, self.accum_bias, self.accum2_bias]
        for arr in arrays:
            if arr:
                arr.map_read()
        return {
            "weights": self.weights.mem,
            "accum_weights": self.accum_weights.mem,
            "accum2_weights": (self.accum2_weights.mem
                               if self.accum2_weights else None),
            "bias": self.bias.mem if self.include_bias and self.bias
            else None,
            "accum_bias": (self.accum_bias.mem
                           if self.include_bias and self.accum_bias
                           else None),
            "accum2_bias": (self.accum2_bias.mem
                            if self.accum2_bias else None),
        }

    # -- master-slave contract (job-farming DP, SURVEY.md section 2.6) -----
    #
    # The forward unit ships canonical PARAMS per job; this unit ships
    # canonical SOLVER STATE (momentum velocity / adagrad / adadelta
    # accumulators) the same way and merges the slave's accumulator
    # deltas additively — so a momentum run farms out bit-faithfully
    # instead of every slave re-warming velocity from zero on each job.

    def _accum_pairs(self):
        return (("accum_weights", self.accum_weights),
                ("accum_bias", self.accum_bias),
                ("accum2_weights", self.accum2_weights),
                ("accum2_bias", self.accum2_bias))

    def generate_data_for_slave(self, slave=None):
        payload = {}
        for name, arr in self._accum_pairs():
            if arr:
                arr.map_read()
                payload[name] = numpy.array(arr.mem)
        return payload or None

    def apply_data_from_master(self, data):
        if not data:
            return
        self._job_start_accums_ = {}
        for name, arr in self._accum_pairs():
            value = data.get(name)
            if value is not None and arr:
                arr.map_invalidate()
                arr.mem = numpy.array(value)
                self._job_start_accums_[name] = numpy.array(value)

    def generate_data_for_master(self):
        start = getattr(self, "_job_start_accums_", None)
        if not start:
            return None
        delta = {}
        for name, arr in self._accum_pairs():
            if name in start and arr:
                arr.map_read()
                delta[name] = arr.mem - start[name]
        return delta or None

    def apply_data_from_slave(self, data, slave=None):
        if not data:
            return
        for name, arr in self._accum_pairs():
            value = data.get(name)
            if value is not None and arr:
                arr.map_write()
                arr.mem += value

    def __getstate__(self):
        # snapshots carry plain ints, not lazy device scalars
        state = super(GradientDescentBase, self).__getstate__()
        if "skip_count" in state:
            state["skip_count"] = int(self.skip_count)
        if "consecutive_skips" in state:
            state["consecutive_skips"] = int(self.consecutive_skips)
        return state

    def _adopt_state(self, new_state, device_side):
        pairs = (("weights", self.weights),
                 ("accum_weights", self.accum_weights),
                 ("accum2_weights", self.accum2_weights),
                 ("bias", self.bias),
                 ("accum_bias", self.accum_bias),
                 ("accum2_bias", self.accum2_bias))
        for key, arr in pairs:
            value = new_state.get(key)
            if value is None or arr is None or not arr:
                continue
            if device_side:
                arr.set_device_array(value, self.device)
            else:
                arr.map_invalidate()
                arr.mem = numpy.asarray(value)

    # -- execution ----------------------------------------------------------

    def run(self):
        from veles_tpu import chaos
        poison = None
        if chaos.plan is not None:
            # nan-injection (docs/health.md): poisoning err_output
            # makes this layer's gradients non-finite AND propagates a
            # non-finite err_input upstream, so the whole chain skips
            # the step — the same blast radius a real NaN has
            fault = chaos.plan.fire("step.grad")
            if fault is not None:
                poison = numpy.float32(
                    numpy.nan if fault.param is None else fault.param)
        if self.on_device():
            self._device_run(poison)
        else:
            self._numpy_run(poison)

    def _account_skip(self, skipped):
        """Lazy skip accounting; ``skipped`` is the guarded backward's
        0/1 flag (popped before _adopt_state sees the dict)."""
        from veles_tpu.models.evaluator import lazy_add, lazy_consec
        self.skip_count = lazy_add(self.skip_count, skipped)
        self.consecutive_skips = lazy_consec(self.consecutive_skips,
                                             skipped)

    def reset_health_counters(self):
        self.skip_count = 0
        self.consecutive_skips = 0

    def _device_run(self, poison=None):
        import functools
        import jax
        if self._jit_fn_ is None:
            self._jit_fn_ = jax.jit(functools.partial(
                type(self).backward, solver=self.solver,
                include_bias=self.include_bias and bool(self.bias),
                need_err_input=self.need_err_input,
                **self.backward_static()))
        err_output = self.err_output.devmem
        if poison is not None:
            err_output = err_output + poison
        err_input, new_state = self._jit_fn_(
            self.state_dict(), self.hyper_dict(),
            self.input.devmem, self.output.devmem, err_output)
        skipped = new_state.pop("skipped", None)
        if skipped is not None:
            self._account_skip(skipped)
        if self.need_err_input and err_input is not None:
            self.err_input.set_device_array(err_input, self.device)
        self._adopt_state(new_state, device_side=True)
        if root.common.get("sync_run", False):
            import jax
            jax.block_until_ready(new_state)

    def _numpy_run(self, poison=None):
        from veles_tpu.backends import host_compute_context
        for arr in (self.input, self.output, self.err_output):
            arr.map_read()
        err_output = self.err_output.mem
        if poison is not None:
            err_output = err_output + poison
        with host_compute_context(self.device):
            err_input, new_state = type(self).backward(
                self.state_numpy(), self.hyper_dict(),
                self.input.mem, self.output.mem, err_output,
                solver=self.solver,
                include_bias=self.include_bias and bool(self.bias),
                need_err_input=self.need_err_input,
                **self.backward_static())
        skipped = new_state.pop("skipped", None)
        if skipped is not None:
            self._account_skip(int(numpy.asarray(skipped)))
        if self.need_err_input and err_input is not None:
            self.err_input.map_invalidate()
            self.err_input.mem = numpy.asarray(err_input)
        self._adopt_state(new_state, device_side=False)
