"""Pooling forward units (Znicz-equivalent pooling / max_pooling /
avg_pooling with stride "sliding"; depooling lives with the autoencoder
family).  ``lax.reduce_window`` lowers straight to the TPU vector unit.

Znicz's MaxPooling recorded arg-offsets into ``input_offset`` for the
backward pass; here the backward (gd_pooling) recomputes the routing via
``jax.vjp`` of this same pure function, which XLA turns into the
select-and-scatter op — no stored indices, no HBM traffic for them.
"""

import numpy

from veles_tpu.models.nn_units import ForwardBase

__all__ = ["MaxPooling", "AvgPooling", "MaxAbsPooling"]


class PoolingBase(ForwardBase):
    """kwargs: kx, ky (window), sliding=(sx, sy) default = window."""

    def __init__(self, workflow, **kwargs):
        super(PoolingBase, self).__init__(workflow, **kwargs)
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.sliding = tuple(kwargs.get("sliding", (self.kx, self.ky)))
        self.include_bias = False

    def static_config(self):
        return {"window": (self.ky, self.kx), "sliding": self.sliding}

    def param_arrays(self):
        return []

    def params_dict(self):
        return {}

    def params_numpy(self):
        return {}

    def output_spatial(self, in_h, in_w):
        return (_out_len(in_h, self.ky, self.sliding[1]),
                _out_len(in_w, self.kx, self.sliding[0]))

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        shape = self.input.shape
        if len(shape) == 3:
            batch, in_h, in_w, ch = shape + (1,)
        else:
            batch, in_h, in_w, ch = shape
        if not self.output:
            out_h, out_w = self.output_spatial(in_h, in_w)
            self.output.mem = numpy.zeros(
                (batch, out_h, out_w, ch), numpy.float32)


def _out_len(in_len, k, stride):
    """ceil-mode output length: partial windows at the edge count
    (Znicz covered the whole input)."""
    if in_len <= k:
        return 1
    return -(-(in_len - k) // stride) + 1


def _pool(x, window, sliding, init, op):
    from jax import lax
    ky, kx = window
    sx, sy = sliding
    pad_h = max(0, (_out_len(x.shape[1], ky, sy) - 1) * sy + ky -
                x.shape[1])
    pad_w = max(0, (_out_len(x.shape[2], kx, sx) - 1) * sx + kx -
                x.shape[2])
    return lax.reduce_window(
        x, init, op,
        window_dimensions=(1, ky, kx, 1),
        window_strides=(1, sy, sx, 1),
        padding=((0, 0), (0, pad_h), (0, pad_w), (0, 0)))


class MaxPooling(PoolingBase):
    MAPPING = "max_pooling"

    @classmethod
    def apply(cls, params, x, *, window, sliding, pallas_bwd=None):
        from jax import lax
        if x.ndim == 3:
            x = x[..., None]
        if pallas_bwd is None:
            from veles_tpu.ops.common import pallas_bwd_enabled
            pallas_bwd = pallas_bwd_enabled()
        if pallas_bwd:
            # same reduce_window forward, backward = the scheduled
            # select-and-scatter Pallas kernel (ops/pool_bwd.py,
            # docs/kernels.md); pallas_bwd=False keeps the stock
            # autodiff select-and-scatter below bit-exactly
            from veles_tpu.ops.pool_bwd import max_pool
            return max_pool(x, window=window, sliding=sliding)
        return _pool(x, window, sliding, -numpy.inf, lax.max)


class MaxAbsPooling(PoolingBase):
    """Znicz max_abs: the element with the largest |value| (sign kept)."""

    MAPPING = "maxabs_pooling"

    @classmethod
    def apply(cls, params, x, *, window, sliding):
        import jax.numpy as jnp
        from jax import lax
        if x.ndim == 3:
            x = x[..., None]
        pos = _pool(x, window, sliding, -numpy.inf, lax.max)
        neg = _pool(-x, window, sliding, -numpy.inf, lax.max)
        return jnp.where(pos >= neg, pos, -neg)


class AvgPooling(PoolingBase):
    MAPPING = "avg_pooling"

    @classmethod
    def apply(cls, params, x, *, window, sliding):
        from jax import lax
        if x.ndim == 3:
            x = x[..., None]
        summed = _pool(x, window, sliding, 0.0, lax.add)
        return summed / (window[0] * window[1])
