"""Reference model zoo: AlexNet and VGG layer specs
(manualrst_veles_algorithms.rst:157 names AlexNet & VGG as the
reference models).

Each builder returns a ``layers`` list for StandardWorkflow; the specs
are also what bench.py's images/sec measurement compiles through the
fused train step.  bf16-friendly: all the FLOPs sit in conv/fc layers
that the compiler lowers onto the MXU.
"""

__all__ = ["alexnet_layers", "vgg_layers", "mnist_mlp_layers",
           "autoencoder_layers", "transformer_layers",
           "build_plans_and_state"]


def build_plans_and_state(specs, input_shape, seed=0):
    """Compile LayerPlans + an initial fused-step state for a spec list
    WITHOUT building the unit graph (used by bench.py and the graft
    entry, where no loader exists).  input_shape excludes batch."""
    import numpy

    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.nn_workflow import forward_mapping

    fmap = forward_mapping()
    rng = numpy.random.RandomState(seed)
    plans, state = [], []
    shape = tuple(input_shape)

    def entry(w_shape, b_shape):
        fan_in = int(numpy.prod(w_shape[:-1]))
        weights = (rng.uniform(-1, 1, w_shape) /
                   numpy.sqrt(fan_in)).astype(numpy.float32)
        return {
            "weights": weights,
            "bias": numpy.zeros(b_shape, numpy.float32),
            "accum_weights": numpy.zeros(w_shape, numpy.float32),
            "accum_bias": numpy.zeros(b_shape, numpy.float32),
            "accum2_weights": None, "accum2_bias": None}

    def none_entry():
        return {"weights": None, "bias": None, "accum_weights": None,
                "accum_bias": None, "accum2_weights": None,
                "accum2_bias": None}

    for spec in specs:
        spec = dict(spec)
        ltype = spec.pop("type")
        cls = fmap[ltype]
        hyper = {k: spec[k] for k in
                 ("learning_rate", "gradient_moment", "weights_decay",
                  "l1_vs_l2") if k in spec}
        if ltype in ("conv", "conv_tanh", "conv_relu", "conv_str",
                     "conv_sigmoid"):
            from veles_tpu.models.conv import _norm_padding
            k = spec["kx"]
            n = spec["n_kernels"]
            sx, sy = spec.get("sliding", (1, 1))
            left, top, right, bottom = _norm_padding(
                spec.get("padding", 0))
            h, w = shape[0], shape[1]
            ch = shape[2] if len(shape) > 2 else 1
            out_h = (h + top + bottom - spec["ky"]) // sy + 1
            out_w = (w + left + right - k) // sx + 1
            plans.append(LayerPlan(
                cls, hyper=hyper,
                static={"padding": (left, top, right, bottom),
                        "sliding": (sx, sy)}))
            state.append(entry((spec["ky"], k, ch, n), (n,)))
            shape = (out_h, out_w, n)
        elif ltype in ("max_pooling", "avg_pooling", "maxabs_pooling"):
            from veles_tpu.models.pooling import _out_len
            kx, ky = spec["kx"], spec["ky"]
            sx, sy = spec.get("sliding", (kx, ky))
            plans.append(LayerPlan(
                cls, include_bias=False,
                static={"window": (ky, kx), "sliding": (sx, sy)}))
            state.append(none_entry())
            shape = (_out_len(shape[0], ky, sy),
                     _out_len(shape[1], kx, sx),
                     shape[2] if len(shape) > 2 else 1)
        elif ltype == "dropout":
            plans.append(LayerPlan(
                cls, include_bias=False,
                static={"dropout_ratio": spec.get("dropout_ratio",
                                                  0.5)}))
            state.append(none_entry())
        elif ltype == "transformer":
            from veles_tpu.models.transformer import init_block_params
            d = shape[-1]
            heads = spec.get("heads", 1)
            if d % heads:
                # the unit path's clear error, not a deep-jit reshape
                # failure at first trace
                raise ValueError("features %d %% heads %d != 0"
                                 % (d, heads))
            hidden = spec.get("hidden") or 4 * d
            plans.append(LayerPlan(
                cls, hyper=hyper,
                static={"heads": heads, "hidden": hidden,
                        "eps": spec.get("eps", 1e-5)}))
            weights, bias = init_block_params(d, hidden, rng)
            state.append({
                "weights": weights, "bias": bias,
                "accum_weights": numpy.zeros_like(weights),
                "accum_bias": numpy.zeros_like(bias),
                "accum2_weights": None, "accum2_bias": None})
        elif ltype == "attention":
            d = shape[-1]
            heads = spec.get("heads", 1)
            if d % heads:
                raise ValueError("features %d %% heads %d != 0"
                                 % (d, heads))
            plans.append(LayerPlan(
                cls, hyper=hyper, static={"heads": heads}))
            state.append(entry((d, 4 * d), (4 * d,)))
        elif ltype == "layer_norm":
            d = shape[-1]
            plans.append(LayerPlan(
                cls, hyper=hyper,
                static={"eps": spec.get("eps", 1e-5)}))
            gamma = numpy.ones((d,), numpy.float32)
            state.append({
                "weights": gamma,
                "bias": numpy.zeros((d,), numpy.float32),
                "accum_weights": numpy.zeros_like(gamma),
                "accum_bias": numpy.zeros((d,), numpy.float32),
                "accum2_weights": None, "accum2_bias": None})
        else:  # all2all family
            fan_in = int(numpy.prod(shape))
            out = spec["output_sample_shape"]
            out = int(numpy.prod(out)) if not isinstance(out, int) \
                else out
            plans.append(LayerPlan(cls, hyper=hyper))
            state.append(entry((fan_in, out), (out,)))
            shape = (out,)
    return plans, state, shape


def transformer_layers(blocks=2, heads=2, hidden=None, classes=10,
                       lr=0.05, moment=0.9):
    """Sequence-classification transformer: a homogeneous pre-LN block
    stack over (B, T, D) input with a softmax head flattening the
    final sequence — the workload the flash-attention kernel, the
    tensor-parallel head sharding, and the pipeline stage split all
    drive (docs/distributed.md "Model parallelism")."""
    spec = [{"type": "transformer", "heads": heads, "hidden": hidden,
             "learning_rate": lr, "gradient_moment": moment}
            for _ in range(blocks)]
    spec.append({"type": "softmax", "output_sample_shape": classes,
                 "learning_rate": lr, "gradient_moment": moment})
    return spec


def mnist_mlp_layers(hidden=100, classes=10, lr=0.1, moment=0.9):
    """BASELINE config 1: the 784-hidden-10 fully-connected net."""
    return [
        {"type": "all2all_tanh", "output_sample_shape": hidden,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "softmax", "output_sample_shape": classes,
         "learning_rate": lr, "gradient_moment": moment},
    ]


def autoencoder_layers(bottleneck=16, hidden=64, out_features=None,
                       lr=0.01, moment=0.9):
    """MNIST-style MLP autoencoder (validation RMSE baseline 0.5478)."""
    spec = [
        {"type": "all2all_tanh", "output_sample_shape": hidden,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "all2all_tanh", "output_sample_shape": bottleneck,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "all2all_tanh", "output_sample_shape": hidden,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "all2all", "output_sample_shape": out_features,
         "learning_rate": lr, "gradient_moment": moment},
    ]
    return spec


def _conv(n, k, lr, moment, stride=1, pad=None, act="conv_str"):
    spec = {"type": act, "n_kernels": n, "kx": k, "ky": k,
            "learning_rate": lr, "gradient_moment": moment}
    if stride != 1:
        spec["sliding"] = (stride, stride)
    spec["padding"] = (k // 2) if pad is None else pad
    return spec


def _pool(k=3, stride=2):
    return {"type": "max_pooling", "kx": k, "ky": k,
            "sliding": (stride, stride)}


def alexnet_layers(classes=1000, lr=0.01, moment=0.9, dropout=0.5):
    """AlexNet (227x227x3 input)."""
    return [
        _conv(96, 11, lr, moment, stride=4, pad=0),
        _pool(),
        _conv(256, 5, lr, moment),
        _pool(),
        _conv(384, 3, lr, moment),
        _conv(384, 3, lr, moment),
        _conv(256, 3, lr, moment),
        _pool(),
        {"type": "all2all_str", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "all2all_str", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "softmax", "output_sample_shape": classes,
         "learning_rate": lr, "gradient_moment": moment},
    ]


def vgg_layers(classes=1000, lr=0.01, moment=0.9, dropout=0.5,
               config="D"):
    """VGG (224x224x3).  config "A"=VGG11, "D"=VGG16, "E"=VGG19."""
    plan = {
        "A": [(64, 1), (128, 1), (256, 2), (512, 2), (512, 2)],
        "D": [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)],
        "E": [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)],
    }[config]
    layers = []
    for channels, repeats in plan:
        for _ in range(repeats):
            layers.append(_conv(channels, 3, lr, moment))
        layers.append(_pool(k=2, stride=2))
    layers += [
        {"type": "all2all_str", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "all2all_str", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "softmax", "output_sample_shape": classes,
         "learning_rate": lr, "gradient_moment": moment},
    ]
    return layers
