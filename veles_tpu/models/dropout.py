"""Dropout forward/backward units (Znicz-equivalent dropout).

The reference generated the mask with the device xorshift PRNG
(veles/prng/uniform.py) and multiplied activations by it.  Here the mask
comes from the counter-based ``jax.random`` (threefry) keyed off the
reproducible host PRNG — same reproducibility guarantee, no mutable
device RNG state to checkpoint (veles_tpu.ops.random keeps the bit-exact
xorshift kernels for anyone needing stream parity).

Inverted dropout: kept activations are scaled by 1/(1-p) at train time so
inference needs no rescale.  Dropout only applies on TRAIN minibatches
(``minibatch_class`` linked from the loader); evaluation passes through.
"""

import numpy

from veles_tpu import prng
from veles_tpu.loader.base import TRAIN
from veles_tpu.memory import Array
from veles_tpu.models.nn_units import ForwardBase, GradientDescentBase

__all__ = ["DropoutForward", "DropoutBackward"]


class DropoutForward(ForwardBase):
    """kwargs: dropout_ratio (probability of DROPPING a unit)."""

    MAPPING = "dropout"

    def __init__(self, workflow, **kwargs):
        super(DropoutForward, self).__init__(workflow, **kwargs)
        self.dropout_ratio = kwargs.get("dropout_ratio", 0.5)
        self.minibatch_class = None  # linked from loader
        self.mask = Array()
        self.prng = kwargs.get("prng", prng.get())
        self.demand("minibatch_class")
        self._step = 0

    def static_config(self):
        return {"dropout_ratio": self.dropout_ratio}

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        if not self.output:
            self.output.mem = numpy.zeros(self.input.shape, numpy.float32)

    def param_arrays(self):
        return []

    @staticmethod
    def make_mask(key, shape, ratio, dtype):
        import jax
        keep = 1.0 - ratio
        bern = jax.random.bernoulli(key, keep, shape)
        return bern.astype(dtype) / keep

    def run(self):
        import jax
        self._step += 1
        if self.minibatch_class != TRAIN:
            # pass-through on eval minibatches
            if self.on_device():
                self.output.set_device_array(self.input.devmem, self.device)
            else:
                self.input.map_read()
                self.output.map_invalidate()
                self.output.mem = numpy.array(self.input.mem)
            self.mask.reset()
            return
        seed = numpy.uint32((self.prng.seed_value or 0) & 0xffffffff)
        step = numpy.uint32(self._step & 0xffffffff)
        if self.on_device():
            if self._jit_fn_ is None:
                # seed/step ride as jit ARGUMENTS and the key is built
                # inside the program: eager PRNGKey+fold_in per
                # minibatch would cost two remote round trips each on
                # a tunneled chip
                def fwd(seed, step, x, ratio):
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(seed), step)
                    mask = DropoutForward.make_mask(
                        key, x.shape, ratio, x.dtype)
                    return x * mask, mask
                self._jit_fn_ = jax.jit(fwd, static_argnums=(3,))
            out, mask = self._jit_fn_(seed, step, self.input.devmem,
                                      self.dropout_ratio)
            self.output.set_device_array(out, self.device)
            self.mask.set_device_array(mask, self.device)
        else:
            from veles_tpu.backends import host_compute_context
            self.input.map_read()
            with host_compute_context(self.device):
                key = jax.random.fold_in(
                    jax.random.PRNGKey(seed), step)
                mask = numpy.asarray(DropoutForward.make_mask(
                    key, self.input.mem.shape, self.dropout_ratio,
                    self.input.mem.dtype))
            self.output.map_invalidate()
            self.output.mem = self.input.mem * mask
            self.mask.map_invalidate()
            self.mask.mem = mask


class DropoutBackward(GradientDescentBase):
    """err_input = err_output * mask (identity on eval minibatches)."""

    MAPPING = "dropout"

    def __init__(self, workflow, **kwargs):
        super(DropoutBackward, self).__init__(workflow, **kwargs)
        self.mask = None  # linked from DropoutForward
        self._demanded -= {"weights", "output", "input"}
        self.demand("mask")

    def _init_solver_state(self):
        pass

    def run(self):
        if not self.mask:  # eval minibatch: mask was reset
            if self.on_device() and self.err_output.devmem is not None:
                self.err_input.set_device_array(
                    self.err_output.devmem, self.device)
            else:
                self.err_output.map_read()
                self.err_input.map_invalidate()
                self.err_input.mem = numpy.array(self.err_output.mem)
            return
        if self.on_device():
            import jax
            if self._jit_fn_ is None:
                self._jit_fn_ = jax.jit(lambda e, m: e * m)
            self.err_input.set_device_array(
                self._jit_fn_(self.err_output.devmem, self.mask.devmem),
                self.device)
        else:
            self.err_output.map_read()
            self.mask.map_read()
            self.err_input.map_invalidate()
            self.err_input.mem = self.err_output.mem * self.mask.mem
