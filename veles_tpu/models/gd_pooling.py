"""Backward units for pooling layers (Znicz-equivalent gd_pooling).

No trainable state; err_input comes from ``jax.vjp`` of the pooling
forward — XLA emits select-and-scatter for max pooling (replacing the
reference's stored-offset scatter kernel) and a uniform spread for avg.
"""

from veles_tpu.models.nn_units import GradientDescentBase
from veles_tpu.models.pooling import AvgPooling, MaxAbsPooling, MaxPooling

__all__ = ["GDMaxPooling", "GDAvgPooling", "GDMaxAbsPooling"]


class GDPoolingBase(GradientDescentBase):
    FORWARD_CLS = None

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("include_bias", False)
        super(GDPoolingBase, self).__init__(workflow, **kwargs)
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.sliding = tuple(kwargs.get("sliding", (self.kx, self.ky)))
        # pooling has no params; drop the weights demand
        self._demanded.discard("weights")

    def backward_static(self):
        return {"window": (self.ky, self.kx), "sliding": self.sliding}

    def _init_solver_state(self):
        pass

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, window=None, sliding=None):
        import jax
        fwd = cls.FORWARD_CLS

        def pool(x_):
            return fwd.apply({}, x_, window=window, sliding=sliding)

        _, vjp = jax.vjp(pool, x)
        (err_input,) = vjp(err_output.astype(x.dtype))
        return err_input, {}


class GDMaxPooling(GDPoolingBase):
    MAPPING = "max_pooling"
    FORWARD_CLS = MaxPooling

    @classmethod
    def backward(cls, state, hyper, x, y, err_output, *, solver,
                 include_bias, need_err_input, window=None,
                 sliding=None):
        from veles_tpu.ops.common import pallas_bwd_enabled
        if pallas_bwd_enabled():
            # scheduled select-and-scatter kernel (ops/pool_bwd.py),
            # fed the STORED forward output y — no pooling recompute,
            # and the incoming err cascade multiplies the routing mask
            # inside the kernel (docs/kernels.md)
            from veles_tpu.ops.pool_bwd import max_pool_bwd
            return max_pool_bwd(x, y, err_output, window=window,
                                sliding=sliding), {}
        return super(GDMaxPooling, cls).backward(
            state, hyper, x, y, err_output, solver=solver,
            include_bias=include_bias, need_err_input=need_err_input,
            window=window, sliding=sliding)


class GDMaxAbsPooling(GDPoolingBase):
    MAPPING = "maxabs_pooling"
    FORWARD_CLS = MaxAbsPooling


class GDAvgPooling(GDPoolingBase):
    MAPPING = "avg_pooling"
    FORWARD_CLS = AvgPooling
