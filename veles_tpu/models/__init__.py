"""Model layer — the Znicz-equivalent neural-network units.

The reference's NN engine (Znicz) is an empty submodule in the checkout;
its capability list comes from docs/source/manualrst_veles_algorithms.rst
(SURVEY.md section 2.8): fully-connected, convolutional (+pooling,
deconv/depool), autoencoders, dropout, activation functions, L1/L2
regularization, SGD+momentum / AdaGrad / AdaDelta solvers, softmax & MSE
losses, per-layer hyperparameters, weight-init schemes, Kohonen, RBM,
RNN/LSTM, reference models AlexNet & VGG.

TPU-first design: every unit exposes a PURE function (``apply`` /
``backward``) over a params pytree; the unit graph is orchestration.  In
per-unit mode each run() is one jitted XLA call whose inputs/outputs stay
on device (no host sync between layers); the workflow compiler
(veles_tpu.compiler) can fuse the whole forward+backward+update pass of a
standard workflow into a single jitted train-step — the idiomatic
replacement for the reference's per-unit kernel-launch chain.
"""

from veles_tpu.models.nn_units import ForwardBase, GradientDescentBase  # noqa
from veles_tpu.models.all2all import (  # noqa: F401
    All2All, All2AllTanh, All2AllRELU, All2AllStrictRELU, All2AllSigmoid,
    All2AllSoftmax)
from veles_tpu.models.evaluator import (  # noqa: F401
    EvaluatorSoftmax, EvaluatorMSE)
from veles_tpu.models.gd import (  # noqa: F401
    GradientDescent, GDTanh, GDRELU, GDStrictRELU, GDSigmoid, GDSoftmax)
from veles_tpu.models.decision import DecisionGD, DecisionMSE  # noqa: F401
from veles_tpu.models.conv import (  # noqa: F401
    Conv, ConvTanh, ConvRELU, ConvStrictRELU, ConvSigmoid)
from veles_tpu.models.pooling import (  # noqa: F401
    MaxPooling, AvgPooling, MaxAbsPooling)
from veles_tpu.models.gd_conv import (  # noqa: F401
    GDConv, GDConvTanh, GDConvRELU, GDConvStrictRELU, GDConvSigmoid)
from veles_tpu.models.gd_pooling import (  # noqa: F401
    GDMaxPooling, GDAvgPooling, GDMaxAbsPooling)
from veles_tpu.models.dropout import (  # noqa: F401
    DropoutForward, DropoutBackward)
