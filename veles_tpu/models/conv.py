"""Convolutional forward units.

Znicz-equivalent conv family (manualrst_veles_algorithms.rst: conv with
padding and "sliding" stride, plus activation fusions).  Layout is NHWC
with HWIO kernels — the layout XLA:TPU prefers for feeding the MXU — and
the conv itself is ``lax.conv_general_dilated`` with f32 accumulation;
the activation fuses into the same XLA computation.

kwargs: n_kernels, kx, ky (kernel width/height), sliding=(sx, sy),
padding=(left, top, right, bottom) or int, plus the ForwardBase
weight-init kwargs.
"""


import numpy

from veles_tpu.models.all2all import (
    All2AllRELU, All2AllSigmoid, All2AllStrictRELU, All2AllTanh)
from veles_tpu.models.nn_units import ForwardBase

__all__ = ["Conv", "ConvTanh", "ConvRELU", "ConvStrictRELU", "ConvSigmoid"]


def _norm_padding(padding):
    if isinstance(padding, int):
        return (padding, padding, padding, padding)
    if len(padding) == 2:
        return (padding[0], padding[1], padding[0], padding[1])
    return tuple(padding)


def conv2d(x, w, strides, padding, pet=None):
    """The one conv entry point (autodiff gradients — deliberately).

    Round-5 measurement (scripts/bwd_experiments.py +
    scripts/step_ab.py, interleaved round-robin chains on the v5e):
    jax-autodiff's conv gradients already run at ~190 TF/s at the
    AlexNet shapes — near the bf16 MXU peak — and a hand-scheduled
    custom VJP (dgrad as lhs-dilated conv, wgrad as batch-as-
    contraction via ("CHWN", "IHWO", "HWNC")) is numerically exact
    but changes the whole fused train step by 0.1 % (A/B speedup
    1.001).  Stock autodiff keeps forward-mode AD usable; the scripts
    keep the receipts."""
    from jax import lax
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=pet)


class Conv(ForwardBase):
    """y = activation(conv2d(x, W) + b).

    With the ``VELES_PALLAS_BWD`` knob on (docs/kernels.md), ``apply``
    routes through the ``ops.conv_vjp.conv_act`` custom_vjp: the
    forward HLO is bit-identical (same conv + bias + activation
    composition), but the backward the fused step differentiates is
    the hand-scheduled family — fused activation-backward/bias-grad
    epilogue in the Pallas wgrad tiles, dgrad as the explicit
    lhs-dilated conv.  ``ACTIVATION`` names the epilogue.
    """

    MAPPING = "conv"
    ACTIVATION = "linear"

    def __init__(self, workflow, **kwargs):
        super(Conv, self).__init__(workflow, **kwargs)
        self.n_kernels = kwargs["n_kernels"]
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        self.padding = _norm_padding(kwargs.get("padding", 0))

    @staticmethod
    def _activate(z):
        return z

    @classmethod
    def apply(cls, params, x, *, padding=(0, 0, 0, 0), sliding=(1, 1),
              pallas_bwd=None):
        import jax.numpy as jnp
        W = params["weights"]
        if x.ndim == 3:
            x = x[..., None]
        if pallas_bwd is None:
            from veles_tpu.ops.common import pallas_bwd_enabled
            pallas_bwd = pallas_bwd_enabled()
        if pallas_bwd:
            # forward-identical custom_vjp carrying the hand-scheduled
            # backward (ops/conv_vjp.py); pallas_bwd=False restores
            # the stock autodiff path below bit-exactly
            from veles_tpu.ops.conv_vjp import conv_act
            return conv_act(x, W, params.get("bias"),
                            activation=cls.ACTIVATION, padding=padding,
                            sliding=sliding)
        left, top, right, bottom = padding
        sx, sy = sliding
        # preferred_element_type=f32 + cast breaks the conv transpose
        # rule for bf16 (mixed-dtype cotangent); the MXU accumulates
        # bf16 convs in f32 in hardware regardless, so only request a
        # wider output when the input is already f32.
        pet = jnp.float32 if x.dtype == jnp.float32 else None
        z = conv2d(x, W, (sy, sx), ((top, bottom), (left, right)),
                   pet)
        if params.get("bias") is not None:
            z = z + params["bias"]
        return cls._activate(z).astype(x.dtype)

    def static_config(self):
        return {"padding": self.padding, "sliding": self.sliding}

    def output_spatial(self, in_h, in_w):
        left, top, right, bottom = self.padding
        sx, sy = self.sliding
        out_h = (in_h + top + bottom - self.ky) // sy + 1
        out_w = (in_w + left + right - self.kx) // sx + 1
        return out_h, out_w

    def create_params(self):
        if not self.input or self.input.sample_size == 0:
            raise AttributeError(
                "%s: input shape unknown at initialize" % self.name)
        shape = self.input.shape
        if len(shape) == 3:
            batch, in_h, in_w, in_ch = shape + (1,)
        else:
            batch, in_h, in_w, in_ch = shape
        fan_in = self.kx * self.ky * in_ch
        if not self.output:
            out_h, out_w = self.output_spatial(in_h, in_w)
            self.output.mem = numpy.zeros(
                (batch, out_h, out_w, self.n_kernels), numpy.float32)
        if self.weights:
            return
        weights = numpy.zeros(
            (self.ky, self.kx, in_ch, self.n_kernels), numpy.float32)
        self.fill_array(weights, self.weights_filling, self.weights_stddev,
                        fan_in)
        self.weights.mem = weights
        if self.include_bias:
            bias = numpy.zeros((self.n_kernels,), numpy.float32)
            self.fill_array(bias, self.bias_filling, self.bias_stddev,
                            fan_in)
            self.bias.mem = bias


class ConvTanh(Conv):
    MAPPING = "conv_tanh"
    ACTIVATION = "tanh"
    _activate = staticmethod(All2AllTanh._activate)


class ConvRELU(Conv):
    MAPPING = "conv_relu"
    ACTIVATION = "relu_log"
    _activate = staticmethod(All2AllRELU._activate)


class ConvStrictRELU(Conv):
    MAPPING = "conv_str"
    ACTIVATION = "strict_relu"
    _activate = staticmethod(All2AllStrictRELU._activate)


class ConvSigmoid(Conv):
    MAPPING = "conv_sigmoid"
    ACTIVATION = "sigmoid"
    _activate = staticmethod(All2AllSigmoid._activate)
