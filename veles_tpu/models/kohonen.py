"""Kohonen self-organising map units
(manualrst_veles_algorithms.rst: Kohonen maps; Znicz kohonen.py
capability, submodule empty — fresh design).

KohonenForward computes the winner neuron per sample; KohonenTrainer
applies the SOM update  w += alpha * neigh(dist_to_winner) * (x - w)
with time-decayed learning rate and gaussian neighbourhood over the
(rows, cols) grid.  Both paths are single jitted calls: the winner
search is one matmul-shaped distance computation on the MXU, the
update one masked outer accumulation.
"""

import numpy

from veles_tpu import prng as prng_module
from veles_tpu.memory import Array
from veles_tpu.units import Unit

__all__ = ["KohonenForward", "KohonenTrainer"]


def _grid_coords(rows, cols):
    import jax.numpy as jnp
    r = jnp.arange(rows)
    c = jnp.arange(cols)
    rr, cc = jnp.meshgrid(r, c, indexing="ij")
    return jnp.stack([rr.ravel(), cc.ravel()], axis=1).astype(
        jnp.float32)


class KohonenBase(Unit):
    def __init__(self, workflow, **kwargs):
        super(KohonenBase, self).__init__(workflow, **kwargs)
        self.shape = tuple(kwargs.get("shape", (8, 8)))  # (rows, cols)
        self.input = None
        self.weights = Array()
        self.prng = kwargs.get("prng", prng_module.get())
        self.device = None
        self._jit_fn_ = None
        self.demand("input")

    def init_unpickled(self):
        super(KohonenBase, self).init_unpickled()
        self._jit_fn_ = None

    @property
    def neurons_number(self):
        return self.shape[0] * self.shape[1]

    def initialize(self, device=None, **kwargs):
        self.device = device
        super(KohonenBase, self).initialize(**kwargs)
        if not self.input or self.input.sample_size == 0:
            raise AttributeError("%s: input shape unknown" % self.name)
        if not self.weights:
            w = numpy.zeros(
                (self.neurons_number, self.input.sample_size),
                numpy.float32)
            self.prng.fill(w, -0.5, 0.5)
            self.weights.mem = w
        self.weights.initialize(device)
        return True


class KohonenForward(KohonenBase):
    """output = winner index per sample (argmin distance)."""

    def __init__(self, workflow, **kwargs):
        super(KohonenForward, self).__init__(workflow, **kwargs)
        self.output = Array()

    @staticmethod
    def winners(weights, x):
        import jax.numpy as jnp
        x2 = x.reshape(x.shape[0], -1)
        # |x-w|^2 = |x|^2 - 2 x.w + |w|^2 ; |x|^2 constant per row
        cross = jnp.dot(x2, weights.T,
                        preferred_element_type=jnp.float32)
        w_norm = jnp.sum(weights * weights, axis=1)
        return jnp.argmin(w_norm - 2.0 * cross, axis=1).astype(
            jnp.int32)

    def run(self):
        import jax

        from veles_tpu.backends import host_compute_context
        if self._jit_fn_ is None:
            self._jit_fn_ = jax.jit(KohonenForward.winners)
        self.input.map_read()
        self.weights.map_read()
        # SOM units work on host arrays; pin the jit to the host CPU
        # so a numpy-backend run never round-trips a remote default
        # device per minibatch
        with host_compute_context(self.device):
            out = self._jit_fn_(self.weights.mem, self.input.mem)
        self.output.map_invalidate()
        self.output.mem = numpy.asarray(out)


class KohonenTrainer(KohonenBase):
    """SOM update with gaussian neighbourhood + decaying radius/alpha."""

    def __init__(self, workflow, **kwargs):
        super(KohonenTrainer, self).__init__(workflow, **kwargs)
        self.alpha = kwargs.get("alpha", 0.3)
        self.alpha_decay = kwargs.get("alpha_decay", 0.995)
        self.radius = kwargs.get("radius", max(self.shape) / 2.0)
        self.radius_decay = kwargs.get("radius_decay", 0.995)
        self.time = 0

    @staticmethod
    def update(weights, x, coords, alpha, radius):
        import jax.numpy as jnp
        coords = jnp.asarray(coords)
        x2 = x.reshape(x.shape[0], -1)
        winners = KohonenForward.winners(weights, x2)
        win_coords = coords[winners]                     # (B, 2)
        d2 = jnp.sum(
            (coords[None, :, :] - win_coords[:, None, :]) ** 2, axis=2)
        neigh = jnp.exp(-d2 / (2.0 * radius * radius))   # (B, N)
        diff = x2[:, None, :] - weights[None, :, :]      # (B, N, F)
        delta = alpha * jnp.einsum("bn,bnf->nf", neigh, diff) / \
            x2.shape[0]
        return weights + delta.astype(weights.dtype)

    def run(self):
        import functools

        import jax
        if self._jit_fn_ is None:
            rows, cols = self.shape
            coords = numpy.asarray(_grid_coords(rows, cols))
            self._jit_fn_ = jax.jit(functools.partial(
                KohonenTrainer.update, coords=coords))
        self.time += 1
        alpha = self.alpha * (self.alpha_decay ** self.time)
        radius = max(self.radius * (self.radius_decay ** self.time),
                     0.5)
        from veles_tpu.backends import host_compute_context
        self.input.map_read()
        self.weights.map_read()
        with host_compute_context(self.device):
            new_w = self._jit_fn_(
                self.weights.mem, self.input.mem,
                alpha=numpy.float32(alpha),
                radius=numpy.float32(radius))
        self.weights.map_invalidate()
        self.weights.mem = numpy.asarray(new_w)
