"""Web frontend command composer (reference veles/__main__.py:258-332:
a tornado page that builds a ``veles`` command line from every
registered CLI argument and launches it).

The form is generated straight from the argparse parser that
:func:`veles_tpu.cmdline.build_parser` aggregates from the per-class
registry, so any unit/service that contributes a flag shows up here
automatically.  POST /run executes the composed command as a child
``python -m veles_tpu`` process; GET /status reports it.
"""

import html
import json
import shlex
import subprocess
import sys
import uuid

from veles_tpu.http_util import BackgroundHTTPServer, RequestTimer

__all__ = ["FrontendServer"]

_PAGE = """<!DOCTYPE html>
<html><head><title>veles-tpu composer</title><style>
body { font: 14px system-ui, sans-serif; margin: 24px; max-width: 760px; }
label { display: block; margin-top: 8px; }
input[type=text] { width: 24em; }
#cmd { background: #f3f3f1; padding: 8px; display: block;
       margin-top: 16px; word-break: break-all; }
.help { color: #52514e; font-size: 12px; }
</style></head><body>
<h1>compose a veles-tpu run</h1>
<form id="form">
<label>workflow file <input type="text" name="workflow" data-pos="1">
</label>
<label>config file <input type="text" name="config" data-pos="2"></label>
%s
</form>
<code id="cmd"></code>
<p><button onclick="run()">run</button> <span id="status"></span></p>
<script>
var EXE = %s, TOKEN = %s;
function compose() {
  var head = [EXE, "-m", "veles_tpu"];
  var tail = [];
  var form = document.getElementById("form");
  var positional = [];
  Array.prototype.forEach.call(form.elements, function (el) {
    if (!el.name) return;
    if (el.dataset.pos) {
      if (el.value) positional[+el.dataset.pos - 1] = el.value;
    } else if (el.type === "checkbox") {
      if (el.checked) tail.push(el.name);
    } else if (el.value) {
      tail.push(el.name, el.value);
    }
  });
  var parts = head.concat(positional.filter(Boolean)).concat(tail);
  document.getElementById("cmd").textContent = parts.join(" ");
  return parts;
}
document.getElementById("form").addEventListener("input", compose);
function run() {
  fetch("/run", {method: "POST",
                 body: JSON.stringify({argv: compose().slice(1),
                                       token: TOKEN})})
    .then(function (r) { return r.json(); })
    .then(function (d) {
      document.getElementById("status").textContent =
        d.error || ("started pid " + d.pid);
    });
}
compose();
</script></body></html>
"""


def _field(action):
    name = action.option_strings[-1] if action.option_strings else None
    if name in (None, "--help", "--frontend"):
        return ""
    help_text = html.escape(action.help or "")
    if action.nargs == 0 or action.const is True:
        control = "<input type='checkbox' name='%s'>" % name
    else:
        control = "<input type='text' name='%s'>" % name
    return ("<label>%s %s <span class='help'>%s</span></label>"
            % (html.escape(name), control, help_text))


class FrontendServer(object):
    """Serves the composer; launched by ``--frontend [PORT]``."""

    def __init__(self, parser, port=0):
        import tornado.web

        fields = "".join(_field(a) for a in parser._actions)
        # per-session token: a cross-origin page can POST to localhost
        # without a CORS preflight, but it cannot read this page to
        # learn the token
        self.token = uuid.uuid4().hex
        page = _PAGE % (fields, json.dumps(sys.executable),
                        json.dumps(self.token))
        server_self = self

        # RequestTimer: perf_counter request timing (tornado's own
        # request_time() is time.time-based; docs/observability.md)
        class PageHandler(RequestTimer, tornado.web.RequestHandler):
            def get(self):
                self.write(page)

        class RunHandler(RequestTimer, tornado.web.RequestHandler):
            def post(self):
                payload = json.loads(self.request.body or b"{}")
                argv = payload.get("argv") or []
                if not isinstance(argv, list) or \
                        any(not isinstance(a, str) for a in argv):
                    self.write({"error": "argv must be a string list"})
                    return
                if payload.get("token") != server_self.token:
                    self.write({"error": "bad or missing token"})
                    return
                if argv[:2] != ["-m", "veles_tpu"]:
                    # only veles_tpu runs may be composed
                    self.write({"error":
                                "argv must start with -m veles_tpu"})
                    return
                if server_self.process is not None and \
                        server_self.process.poll() is None:
                    self.write({"error": "a run is already active "
                                "(pid %d)" % server_self.process.pid})
                    return
                try:
                    server_self.process = subprocess.Popen(
                        [sys.executable] + argv)
                except OSError as exc:
                    self.write({"error": str(exc)})
                    return
                server_self.command = " ".join(shlex.quote(a)
                                               for a in argv)
                self.write({"pid": server_self.process.pid})

        class StatusHandler(RequestTimer, tornado.web.RequestHandler):
            def get(self):
                proc = server_self.process
                self.write({
                    "command": server_self.command,
                    "running": proc is not None and
                    proc.poll() is None,
                    "returncode": None if proc is None
                    else proc.poll()})

        self.app = tornado.web.Application([
            (r"/", PageHandler),
            (r"/run", RunHandler),
            (r"/status", StatusHandler),
        ])
        self.process = None
        self.command = None
        self._server = BackgroundHTTPServer(self.app, port=port)

    @property
    def port(self):
        return self._server.port

    def start_background(self):
        return self._server.start()

    def stop(self):
        self._server.stop()
