"""Recurrent sequence classification on real handwritten digits
(reference algorithm family: manualrst_veles_algorithms.rst RNN/LSTM,
which the reference shipped untested — here the path is exercised end
to end): each 8x8 digit is fed as a sequence of 8 row-vectors, an LSTM
consumes the rows, and a softmax head classifies the final state.

    python -m veles_tpu examples/sequence.py
"""

from veles_tpu.config import root
from veles_tpu.datasets import DigitsLoader
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.sequence.update({
    "hidden": 48,
    "learning_rate": 0.05,
    "gradient_moment": 0.9,
    "minibatch_size": 48,
    "max_epochs": 60,
    "fail_iterations": 15,
})


class DigitsRowsLoader(DigitsLoader):
    """Serves digits reshaped (batch, 8, 8): a sequence of 8 rows."""

    def load_data(self):
        super(DigitsRowsLoader, self).load_data()
        data = self.original_data.mem
        self.original_data = data.reshape(len(data), 8, 8)


def build(launcher):
    cfg = root.sequence
    return StandardWorkflow(
        launcher,
        layers=[
            {"type": "lstm", "hidden_size": cfg.hidden,
             "return_sequences": False,
             "learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment},
        ],
        loader_factory=lambda w: DigitsRowsLoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("sequence", seed=21)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
