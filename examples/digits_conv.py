"""Convolutional digit classification — the offline conv
*classification* quality anchor (round-2 verdict: conv quality was
anchored only by reconstruction RMSE; the reference's conv numbers are
classification errors, manualrst_veles_algorithms.rst:50).

Runs the real 8x8 handwritten digits through the conv/pool stack into
a softmax readout: conv, max-pooling, dense, and dropout-free GD
trainers exercising the same unit set the CIFAR-10 workflow uses, on
data available offline.

    python -m veles_tpu examples/digits_conv.py
"""

from veles_tpu.config import root
from veles_tpu.datasets import DigitsLoader, digits_arrays
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.digits_conv.update({
    "minibatch_size": 48,
    "learning_rate": 0.03,
    "gradient_moment": 0.9,
    "weights_decay": 1e-4,
    "max_epochs": 60,
    "fail_iterations": 20,
})


class DigitsImageLoader(DigitsLoader):
    """Digits reshaped (batch, 8, 8, 1) for the conv stack."""

    def get_arrays(self):
        train_x, train_y, valid_x, valid_y = digits_arrays(
            self.validation_count, self.split_seed)
        return (train_x.reshape(-1, 8, 8, 1), train_y,
                valid_x.reshape(-1, 8, 8, 1), valid_y)


def build(launcher):
    cfg = root.digits_conv
    hyper = {"learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay}
    return StandardWorkflow(
        launcher,
        layers=[
            dict(type="conv_relu", n_kernels=16, kx=3, ky=3,
                 padding=1, **hyper),
            dict(type="max_pooling", kx=2, ky=2),
            dict(type="conv_relu", n_kernels=32, kx=3, ky=3,
                 padding=1, **hyper),
            dict(type="max_pooling", kx=2, ky=2),
            dict(type="all2all_relu", output_sample_shape=64, **hyper),
            dict(type="softmax", output_sample_shape=10, **hyper),
        ],
        loader_factory=lambda w: DigitsImageLoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("digits_conv", seed=5)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
