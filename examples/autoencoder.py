"""Digits MLP autoencoder — the offline stand-in for the reference's
MNIST-autoencoder quality anchor (validation RMSE 0.5478,
manualrst_veles_algorithms.rst:69; MNIST itself needs network access,
absent here, so the 8x8 digits reconstruct instead).

    python -m veles_tpu examples/autoencoder.py
"""


from veles_tpu.config import root
from veles_tpu.datasets import digits_arrays
from veles_tpu.datasets import _SplitLoaderMSE
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.models.zoo import autoencoder_layers
from veles_tpu.prng import RandomGenerator

root.digits_ae.update({
    "bottleneck": 12,
    "hidden": 48,
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "minibatch_size": 48,
    "max_epochs": 60,
    "fail_iterations": 15,
})


class DigitsAELoader(_SplitLoaderMSE):
    """Reconstruction task: targets ARE the inputs (reference
    autoencoder workflows fed image->same-image MSE pairs); the
    [valid|train] layout comes from the shared split-loader base."""

    def __init__(self, workflow, validation_count=360, seed=4,
                 **kwargs):
        super(DigitsAELoader, self).__init__(workflow, **kwargs)
        self.validation_count = validation_count
        self.split_seed = seed

    def get_arrays(self):
        return digits_arrays(self.validation_count, self.split_seed)


def build(launcher):
    cfg = root.digits_ae
    return StandardWorkflow(
        launcher,
        layers=autoencoder_layers(
            bottleneck=cfg.bottleneck, hidden=cfg.hidden,
            out_features=64, lr=cfg.learning_rate,
            moment=cfg.gradient_moment),
        loss="mse",
        loader_factory=lambda w: DigitsAELoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("digits_ae", seed=11)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
