"""Bernoulli RBM pretraining on real handwritten digits (reference
algorithm family: manualrst_veles_algorithms.rst "RBM"): CD-k training
drives reconstruction error down on the train split, then the readout
reports held-out reconstruction error — the unsupervised pretraining
quality signal.

    python -m veles_tpu examples/rbm.py
"""

import numpy

from veles_tpu.config import root
from veles_tpu.datasets import digits_arrays
from veles_tpu.memory import Array
from veles_tpu.models.rbm import RBM
from veles_tpu.plumbing import EpochCounter, Repeater
from veles_tpu.prng import RandomGenerator
from veles_tpu.workflow import Workflow

root.rbm.update({
    "hidden": 64,
    "epochs": 60,
    "learning_rate": 0.1,
    "cd_k": 1,
})


class RBMWorkflow(Workflow):
    """start -> repeater -> rbm(CD-k) -> counter -> (loop | end)."""

    def __init__(self, launcher, **kwargs):
        super(RBMWorkflow, self).__init__(launcher, **kwargs)
        cfg = root.rbm
        train_x, _, valid_x, _ = digits_arrays(360, 4)
        self.valid_x = valid_x  # already scaled to [0, 1]
        self.holdout_error = None

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.rbm = RBM(self, hidden_size=cfg.hidden,
                       learning_rate=cfg.learning_rate, cd_k=cfg.cd_k,
                       prng=RandomGenerator("rbm", seed=13))
        self.rbm.input = Array(train_x)
        self.rbm.link_from(self.repeater)

        self.counter = EpochCounter(self, int(cfg.epochs))
        self.counter.link_from(self.rbm)

        self.repeater.link_from(self.counter)
        self.end_point.link_from(self.counter)
        self.end_point.gate_block = ~self.counter.complete

    def on_workflow_finished(self):
        self.holdout_error = self.rbm.reconstruct_error(
            self.valid_x)
        self.info("RBM holdout reconstruction error: %.4f "
                  "(train-side final %.4f, %d epochs)",
                  self.holdout_error, self.rbm.reconstruction_error,
                  self.counter.passes)
        super(RBMWorkflow, self).on_workflow_finished()


def run(load, main):
    load(RBMWorkflow)
    main()
