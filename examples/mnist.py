"""MNIST fully-connected workflow — the BASELINE config-1 parity model.

Reference anchor: 784-100-10 fully-connected softmax network, 1.48 %
validation error (/root/reference/docs/source/
manualrst_veles_algorithms.rst:31).  Run:

    python -m veles_tpu examples/mnist.py [examples/mnist_config.py]

Needs the MNIST idx files under ``$VELES_DATA`` (downloaded
automatically when the network allows; see veles_tpu/datasets.py).
"""

from veles_tpu.config import root
from veles_tpu.datasets import MnistLoader
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.mnist.update({
    "hidden": 100,
    "minibatch_size": 100,
    "learning_rate": 0.1,
    "gradient_moment": 0.9,
    "weights_decay": 5e-5,
    "max_epochs": 100,
    "fail_iterations": 25,       # early stop when validation stalls
})


def build(launcher):
    cfg = root.mnist
    return StandardWorkflow(
        launcher,
        layers=[
            {"type": "all2all_tanh",
             "output_sample_shape": cfg.hidden,
             "learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay},
        ],
        loader_factory=lambda w: MnistLoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("mnist", seed=1)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
