"""Transformer sequence classification on real handwritten digits
(the post-recurrent sibling of examples/sequence.py): each 8x8 digit
is fed as a sequence of 8 row-vectors, a stack of pre-LN transformer
blocks (flash-attention Pallas kernel when VELES_PALLAS_BWD resolves
on, docs/kernels.md) mixes the rows, and a softmax head classifies the
flattened sequence.

    python -m veles_tpu examples/transformer.py
"""

from veles_tpu.config import root
from veles_tpu.datasets import DigitsLoader
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.transformer.update({
    "blocks": 2,
    "heads": 2,
    "hidden": 32,
    "learning_rate": 0.05,
    "gradient_moment": 0.9,
    "minibatch_size": 48,
    "max_epochs": 60,
    "fail_iterations": 15,
})


class DigitsRowsLoader(DigitsLoader):
    """Serves digits reshaped (batch, 8, 8): a sequence of 8 rows
    (the same presentation examples/sequence.py feeds its LSTM)."""

    def load_data(self):
        super(DigitsRowsLoader, self).load_data()
        data = self.original_data.mem
        self.original_data = data.reshape(len(data), 8, 8)


def build(launcher):
    cfg = root.transformer
    layers = [
        {"type": "transformer", "heads": cfg.heads,
         "hidden": cfg.hidden, "learning_rate": cfg.learning_rate,
         "gradient_moment": cfg.gradient_moment}
        for _ in range(cfg.blocks)
    ]
    layers.append({"type": "softmax", "output_sample_shape": 10,
                   "learning_rate": cfg.learning_rate,
                   "gradient_moment": cfg.gradient_moment})
    return StandardWorkflow(
        launcher,
        layers=layers,
        loader_factory=lambda w: DigitsRowsLoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("transformer", seed=21)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
