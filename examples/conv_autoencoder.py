"""Convolutional autoencoder on real handwritten digits (reference
algorithm family: manualrst_veles_algorithms.rst "autoencoders
(incl. convolutional)"): conv+avg-pool encode each 8x8 digit down to a
4x4 bottleneck, depooling+deconv decode it back, trained end to end
through the MSE path — conv, pooling, depooling, and deconv units in
one workflow.

    python -m veles_tpu examples/conv_autoencoder.py
"""

from veles_tpu.config import root
from veles_tpu.datasets import _SplitLoaderMSE, digits_arrays
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.conv_ae.update({
    "channels": 8,
    "learning_rate": 0.002,
    "gradient_moment": 0.5,
    "minibatch_size": 48,
    "max_epochs": 40,
    "fail_iterations": 12,
})


class DigitsImageAELoader(_SplitLoaderMSE):
    """Digits reshaped (batch, 8, 8, 1); targets are the inputs."""

    def __init__(self, workflow, validation_count=360, seed=4,
                 **kwargs):
        super(DigitsImageAELoader, self).__init__(workflow, **kwargs)
        self.validation_count = validation_count
        self.split_seed = seed

    def get_arrays(self):
        train_x, train_y, valid_x, valid_y = digits_arrays(
            self.validation_count, self.split_seed)
        return (train_x.reshape(-1, 8, 8, 1), train_y,
                valid_x.reshape(-1, 8, 8, 1), valid_y)


def build(launcher):
    cfg = root.conv_ae
    ch = cfg.channels
    hyper = {"learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment}
    return StandardWorkflow(
        launcher,
        layers=[
            # encode: (8,8,1) -> conv tanh -> (8,8,ch) -> pool (4,4,ch)
            dict(type="conv_tanh", n_kernels=ch, kx=3, ky=3,
                 padding=1, **hyper),
            dict(type="avg_pooling", kx=2, ky=2, **hyper),
            # decode: upsample back to 8x8, deconv to one channel
            dict(type="depooling", kx=2, ky=2, **hyper),
            dict(type="deconv", n_output_channels=1, kx=3, ky=3,
                 padding=1, **hyper),
        ],
        loss="mse",
        loader_factory=lambda w: DigitsImageAELoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("conv_ae", seed=17)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
