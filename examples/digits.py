"""Handwritten-digits workflow — the OFFLINE real-data quality anchor.

1,797 real 8x8 handwritten digits (UCI, bundled with scikit-learn) so
the full loader->workflow->decision->snapshotter quality path runs on
genuine data in environments without network access or cached MNIST.
The repo's committed quality number (QUALITY.json) comes from this
workflow; tests/test_quality.py asserts it stays reached.

    python -m veles_tpu examples/digits.py
"""

from veles_tpu.config import root
from veles_tpu.datasets import DigitsLoader
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.digits.update({
    "hidden": 64,
    "minibatch_size": 48,
    "learning_rate": 0.08,
    "gradient_moment": 0.9,
    "weights_decay": 1e-4,
    "max_epochs": 60,
    "fail_iterations": 20,
})


def build(launcher):
    cfg = root.digits
    return StandardWorkflow(
        launcher,
        layers=[
            {"type": "all2all_tanh",
             "output_sample_shape": cfg.hidden,
             "learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay},
        ],
        loader_factory=lambda w: DigitsLoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("digits", seed=2)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
