"""Kohonen self-organising map over real handwritten digits
(reference algorithm family: manualrst_veles_algorithms.rst "Kohonen
maps"): the SOM clusters the 64-feature digits onto a 2-D neuron grid
without labels, then reports how cleanly the grid separates the true
classes (winner-purity on the validation split).

    python -m veles_tpu examples/kohonen.py
"""

import numpy

from veles_tpu.config import root
from veles_tpu.datasets import digits_arrays
from veles_tpu.memory import Array
from veles_tpu.models.kohonen import KohonenForward, KohonenTrainer
from veles_tpu.prng import RandomGenerator
from veles_tpu.plumbing import EpochCounter, Repeater
from veles_tpu.workflow import Workflow

root.kohonen.update({
    "shape": (8, 8),
    "epochs": 100,
    "alpha": 0.3,
})


def purity(winners, labels, neurons):
    """Fraction of samples whose winning neuron's majority label
    matches their own — the SOM quality readout."""
    correct = 0
    for neuron in range(neurons):
        mask = winners == neuron
        if not mask.any():
            continue
        correct += numpy.bincount(labels[mask]).max()
    return correct / len(labels)


class KohonenWorkflow(Workflow):
    """start -> repeater -> trainer -> counter -> (loop | end); the
    forward/purity readout runs once after the loop ends."""

    def __init__(self, launcher, **kwargs):
        super(KohonenWorkflow, self).__init__(launcher, **kwargs)
        cfg = root.kohonen
        shape = tuple(cfg.shape)
        train_x, _, valid_x, valid_y = digits_arrays(360, 4)
        self.valid_labels = valid_y.astype(numpy.int64)
        self.purity = None

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.trainer = KohonenTrainer(
            self, shape=shape, alpha=cfg.alpha,
            prng=RandomGenerator("kohonen", seed=9))
        self.trainer.input = Array(train_x)
        self.trainer.link_from(self.repeater)

        self.counter = EpochCounter(self, int(cfg.epochs))
        self.counter.link_from(self.trainer)

        self.repeater.link_from(self.counter)
        self.end_point.link_from(self.counter)
        self.end_point.gate_block = ~self.counter.complete

        self.forward = KohonenForward(self, shape=shape)
        self.forward.input = Array(valid_x)
        self.forward.weights = self.trainer.weights

    def on_workflow_finished(self):
        # readout: winners on the held-out split -> purity (the
        # forward unit was initialized with the rest of the graph)
        self.forward.run()
        self.forward.output.map_read()
        self.purity = purity(
            numpy.asarray(self.forward.output.mem),
            self.valid_labels, self.trainer.neurons_number)
        self.info("SOM validation purity: %.1f%% "
                  "(%d neurons, %d epochs)",
                  100.0 * self.purity, self.trainer.neurons_number,
                  self.counter.passes)
        super(KohonenWorkflow, self).on_workflow_finished()


def run(load, main):
    load(KohonenWorkflow)
    main()
