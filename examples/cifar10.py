"""CIFAR-10 convolutional workflow — BASELINE quality target 17.21 %
validation error (/root/reference/docs/source/
manualrst_veles_algorithms.rst:50; the reference's conv config).

    python -m veles_tpu examples/cifar10.py

Needs the CIFAR-10 python batches under ``$VELES_DATA``
(cifar-10-batches-py/); see veles_tpu/datasets.py.
"""

from veles_tpu.config import root
from veles_tpu.datasets import Cifar10Loader
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.cifar.update({
    "minibatch_size": 100,
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "weights_decay": 4e-5,
    "dropout": 0.5,
    "max_epochs": 80,
    "fail_iterations": 20,
})


def _conv(n, k, act="conv_relu", stride=1, pad=1):
    cfg = root.cifar
    return {"type": act, "n_kernels": n, "kx": k, "ky": k,
            "sliding": (stride, stride), "padding": pad,
            "learning_rate": cfg.learning_rate,
            "gradient_moment": cfg.gradient_moment,
            "weights_decay": cfg.weights_decay}


def build(launcher):
    cfg = root.cifar
    dense = {"learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay}
    return StandardWorkflow(
        launcher,
        layers=[
            _conv(32, 3), _conv(32, 3),
            {"type": "max_pooling", "kx": 2, "ky": 2},
            _conv(64, 3), _conv(64, 3),
            {"type": "max_pooling", "kx": 2, "ky": 2},
            _conv(128, 3),
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "all2all_relu", "output_sample_shape": 256, **dense},
            {"type": "dropout", "dropout_ratio": cfg.dropout},
            {"type": "softmax", "output_sample_shape": 10, **dense},
        ],
        loader_factory=lambda w: Cifar10Loader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("cifar", seed=3)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
