"""Digits via the epoch-scan turbo path — the whole epoch as ONE
XLA dispatch per class (compiler.build_train_epoch/build_eval_epoch).

The standard workflow (examples/digits.py) drives the unit graph:
loader -> fused trainer -> decision, one dispatch per minibatch.  This
example trades the per-minibatch decision gates for raw speed: train
and validation passes each compile to a single scanned program, so a
dispatch-bound model spends its wall time on compute alone (measured
17.7 us/step on the MNIST-784 MLP over a tunneled v5e — 24x the
per-minibatch fused path).  Early stopping happens between epochs.

Run it directly (no CLI wrapper: the turbo path IS the loop):

    python examples/digits_turbo.py [--epochs 40] [--backend tpu]
"""

import argparse
import os
import sys

import numpy

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=40)
    parser.add_argument("--backend", default=None,
                        help="tpu | cpu | auto (default: auto)")
    parser.add_argument("--batch", type=int, default=48)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from veles_tpu.backends import Device
    from veles_tpu.compiler import (build_eval_epoch,
                                    build_train_epoch)
    from veles_tpu.datasets import digits_arrays
    from veles_tpu.models.zoo import build_plans_and_state

    Device(backend=args.backend)  # resolve + init backend/caches

    # same deterministic split the standard digits anchor trains on;
    # the epoch scans run sub-batch tails as masked steps, so the
    # full validation set participates
    train_x, train_y, valid_x, valid_y = digits_arrays()
    data = numpy.concatenate([train_x, valid_x])
    labels = numpy.concatenate([train_y, valid_y])
    train_idx = numpy.arange(len(train_x))
    valid_idx = numpy.arange(len(train_x), len(data))
    rng = numpy.random.RandomState(2)

    specs = [
        {"type": "all2all_tanh", "output_sample_shape": 64,
         "learning_rate": 0.08, "gradient_moment": 0.9,
         "weights_decay": 1e-4},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": 0.08, "gradient_moment": 0.9,
         "weights_decay": 1e-4},
    ]
    plans, state, _ = build_plans_and_state(specs, (64,), seed=2)
    state = jax.tree.map(
        lambda l: None if l is None else jnp.asarray(l),
        state, is_leaf=lambda x: x is None)

    dataset = jax.device_put(data)
    labels_dev = jax.device_put(labels.astype(numpy.int32))
    valid_order = jax.device_put(valid_idx.astype(numpy.int32))

    from veles_tpu.compiler import step_compiler_options
    opts = step_compiler_options()  # per-chip tuned XLA options
    train = build_train_epoch(plans, args.batch, compiler_options=opts)
    evaluate = build_eval_epoch(plans, args.batch,
                                compiler_options=opts)

    best_err, best_epoch = float("inf"), -1
    for epoch in range(args.epochs):
        train_order = jax.device_put(
            rng.permutation(train_idx).astype(numpy.int32))
        state, totals = train(state, dataset, labels_dev, train_order)
        params = [{"weights": s["weights"], "bias": s["bias"]}
                  for s in state]
        m = evaluate(params, dataset, labels_dev, valid_order)
        err_pct = 100.0 * int(m["n_err"]) / int(m["samples"])
        if err_pct < best_err:
            best_err, best_epoch = err_pct, epoch
        print("epoch %2d: train loss %.4f  valid err %.2f%%" % (
            epoch, float(totals["loss_mean"]), err_pct))
    print("best validation error %.2f%% (epoch %d)" % (
        best_err, best_epoch))
    return best_err


if __name__ == "__main__":
    main()
