"""MNIST autoencoder — BASELINE quality target RMSE 0.5478
(/root/reference/docs/source/manualrst_veles_algorithms.rst:69; the
reference's MNIST autoencoder sample).

    python -m veles_tpu examples/mnist_autoencoder.py

Needs the MNIST idx files under ``$VELES_DATA`` (the offline
stand-in reconstructing 8x8 digits is examples/autoencoder.py).
"""

from veles_tpu.config import root
from veles_tpu.datasets import _SplitLoaderMSE, mnist_arrays
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.mnist_ae.update({
    "hidden": 100,
    "minibatch_size": 100,
    "learning_rate": 0.05,
    "gradient_moment": 0.9,
    "max_epochs": 80,
    "fail_iterations": 20,
})


class MnistAELoader(_SplitLoaderMSE):
    """MNIST images as both input and target."""

    def get_arrays(self):
        train_x, train_y, test_x, test_y = mnist_arrays()
        return train_x, train_y, test_x, test_y


def build(launcher):
    cfg = root.mnist_ae
    hyper = {"learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment}
    return StandardWorkflow(
        launcher,
        layers=[
            {"type": "all2all_tanh",
             "output_sample_shape": cfg.hidden, **hyper},
            {"type": "all2all", "output_sample_shape": 784, **hyper},
        ],
        loss="mse",
        loader_factory=lambda w: MnistAELoader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("mnist_ae", seed=8)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
