"""STL-10 convolutional workflow — BASELINE quality target 35.10 %
validation error (/root/reference/docs/source/
manualrst_veles_algorithms.rst:51; the reference's conv config).

    python -m veles_tpu examples/stl10.py

Needs the STL-10 binary files under ``$VELES_DATA``
(stl10_binary/train_X.bin ...); see veles_tpu/datasets.py.
STL-10: 96x96x3, only 5,000 labeled train images — heavier
augmentation-free regularization (dropout + weight decay) than
CIFAR-10.
"""

from veles_tpu.config import root
from veles_tpu.datasets import Stl10Loader
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator

root.stl10.update({
    "minibatch_size": 50,
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 1e-4,
    "dropout": 0.5,
    "max_epochs": 120,
    "fail_iterations": 25,
})


def _conv(n, k, stride=1, pad=1):
    cfg = root.stl10
    return {"type": "conv_relu", "n_kernels": n, "kx": k, "ky": k,
            "sliding": (stride, stride), "padding": pad,
            "learning_rate": cfg.learning_rate,
            "gradient_moment": cfg.gradient_moment,
            "weights_decay": cfg.weights_decay}


def build(launcher):
    cfg = root.stl10
    dense = {"learning_rate": cfg.learning_rate,
             "gradient_moment": cfg.gradient_moment,
             "weights_decay": cfg.weights_decay}
    return StandardWorkflow(
        launcher,
        layers=[
            # 96 -> 48 -> 24 -> 12 -> 6 spatial
            _conv(32, 3), {"type": "max_pooling", "kx": 2, "ky": 2},
            _conv(64, 3), {"type": "max_pooling", "kx": 2, "ky": 2},
            _conv(128, 3), {"type": "max_pooling", "kx": 2, "ky": 2},
            _conv(128, 3), {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "all2all_relu", "output_sample_shape": 256,
             **dense},
            {"type": "dropout", "dropout_ratio": cfg.dropout},
            {"type": "softmax", "output_sample_shape": 10, **dense},
        ],
        loader_factory=lambda w: Stl10Loader(
            w, minibatch_size=cfg.minibatch_size,
            prng=RandomGenerator("stl10", seed=6)),
        decision_config=dict(max_epochs=cfg.max_epochs,
                             fail_iterations=cfg.fail_iterations),
        result_file=root.common.get("result_file"),
    )


def run(load, main):
    load(build)
    main()
