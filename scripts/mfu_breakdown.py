"""Per-layer MFU/roofline attribution for a conv-family training step
(AlexNet / VGG; round-3 verdict item 3: say WHERE the non-MXU time
goes).

Method: the full fused train step is measured once on the real chip
(same machinery as bench.py), and XLA's own cost analysis supplies the
program-level FLOP count and HBM bytes accessed.  Attribution across
layers is ANALYTIC — per-layer forward FLOPs from the conv/dense
shapes (backward ~= 2x forward), per-layer HBM traffic from activation
+ parameter + optimizer-state sizes — then each layer's roofline time
is max(flops / MXU peak, bytes / HBM bandwidth).  The analytic total
is compared against the measured step so the attribution's credibility
is visible in the record (see "model_vs_measured_ratio").

Writes MFU.json:  {measured: {...}, layers: [...], conclusion: "..."}

    python scripts/mfu_breakdown.py [--batch 256] [--dtype bfloat16]

Pass filtering (the weather methodology, docs/kernels.md): every
timing median — the measured step, the forward-only split — rides the
jitter-FILTERED passes: a pass whose chain slope comes out
non-positive measured the tunnel's weather, not the program (one such
pass contaminated the published 48.8% capture, see MFU.json's
weather_note), and is auto-discarded by ``bench._filter_passes``.
The spread block records ``passes`` (raw), ``passes_used``
(retained) and the per-pass ``slopes`` so the filter's effect is
auditable from the committed record alone.
"""

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# v5e public spec numbers; other chips fall back to bench.py's table
PEAK_BF16_TFLOPS = 197.0
HBM_GBPS = 819.0


def layer_shapes(plans, state, input_shape, batch):
    """Fold the forward per layer with jax.eval_shape, returning
    [(name, in_shape, out_shape, param_bytes)]."""
    import jax

    from veles_tpu.models.all2all import All2All, All2AllSoftmax
    from veles_tpu.models.dropout import DropoutForward

    rows = []
    h = jax.ShapeDtypeStruct((batch,) + tuple(input_shape), "bfloat16")
    for i, (plan, p) in enumerate(zip(plans, state)):
        name = "%d_%s" % (i, plan.forward_cls.__name__)
        param_bytes = sum(
            v.size * 2 for v in (p or {}).values()
            if v is not None and hasattr(v, "size"))

        def apply(h, plan=plan, p=p):
            params = {k: jax.numpy.asarray(v, "bfloat16")
                      for k, v in (p or {}).items() if v is not None}
            if plan.forward_cls is All2AllSoftmax:
                return All2All.apply(params, h)
            if issubclass(plan.forward_cls, DropoutForward):
                return h
            return plan.forward_cls.apply(params, h, **plan.static)

        out = jax.eval_shape(apply, h)
        rows.append((name, tuple(h.shape), tuple(out.shape),
                     param_bytes))
        h = out
    return rows


def schedule_provenance(plan, params, ish, osh, dtype):
    """Tuned-vs-static provenance of the layer's backward kernel
    schedule (docs/kernels.md "Autotuning"): "tuned" when the schedule
    cache holds an entry the kernel's consult would serve for this
    exact (padded shape, dtype, precision, device) — so a future
    MFU.json regression is attributable to the schedule that actually
    ran.  "autodiff" marks shapes the Pallas backward falls back on
    (many-tap convs, overlapping-pool VMEM overflows have their own
    plan); None = the layer has no Pallas-scheduled kernel (dense
    layers run XLA's own dot inside the fused step)."""
    from veles_tpu.tune.cache import provenance
    from veles_tpu.tune.spec import conv_vjp_spec, pool_bwd_spec

    name = plan.forward_cls.__name__
    if "Conv" in name:
        w = (params or {}).get("weights")
        if w is None or len(getattr(w, "shape", ())) != 4:
            return None
        ky, kx = int(w.shape[0]), int(w.shape[1])
        from veles_tpu.ops.conv_vjp import MAX_FUSED_TAPS
        if ky * kx > MAX_FUSED_TAPS:
            return "autodiff"
        # precision_level 0 = what the fused step's gd units pass
        spec = conv_vjp_spec(ish, ky, kx, osh[-1], osh[1:3], dtype, 0,
                             plan.static.get("padding", (0, 0, 0, 0)),
                             plan.static.get("sliding", (1, 1)))
    elif ("Max" in name and "Abs" not in name
          and "window" in plan.static):
        spec = pool_bwd_spec(ish, osh[1:3], plan.static["window"],
                             plan.static["sliding"], dtype)
    else:
        return None
    return provenance(spec["op"], spec["shape"], spec["dtype"],
                      spec["precision_level"], spec["extra"])


def analytic_layer(name, in_shape, out_shape, param_bytes):
    """Forward FLOPs + training-step HBM traffic for one layer.

    FLOPs: conv = 2*B*OH*OW*K (K = kernel volume * Cin, recovered from
    the weight size); dense = 2*B*fan_in*fan_out; pool/dropout ~ 0.
    Training multiplies forward FLOPs by 3 (dgrad + wgrad each cost
    about one forward).

    Traffic model (bf16 = 2 bytes): activations in+out each touched
    ~3x across fwd+bwd (fwd read/write, bwd read grad + read saved
    activation / write dinput), parameters + momentum touched ~4x
    (fwd read W; bwd write dW; solver read accum, write accum+W).
    XLA fusion saves some of this, so the roofline is an upper-ish
    bound per layer; the committed ratio vs the measured step shows
    how tight it is.
    """
    bpe = 2.0
    in_elems = float(math.prod(in_shape))
    out_elems = float(math.prod(out_shape))
    # param_bytes counts weights+bias+accum_weights+accum_bias, so the
    # weight tensor alone holds about half the state elements
    weights_only = param_bytes / bpe / 2.0
    if "Conv" in name and param_bytes:
        # weights are (KH*KW*Cin, Cout): kernel_volume*Cin =
        # w_elems / Cout, and fwd flops = 2 * out_elems * that
        cout = out_shape[-1]
        kvol_cin = weights_only / cout
        flops_fwd = 2.0 * out_elems * kvol_cin
    elif ("All2All" in name or "Softmax" in name) and param_bytes:
        fan_in = in_elems / in_shape[0]
        fan_out = out_elems / out_shape[0]
        flops_fwd = 2.0 * in_shape[0] * fan_in * fan_out
    else:
        flops_fwd = 0.0
    flops_train = 3.0 * flops_fwd
    traffic = (3.0 * (in_elems + out_elems) * bpe
               + 2.0 * param_bytes)  # param_bytes already has accums
    return flops_train, traffic


def _measure_forward_only(plans, state, batch, peak_flops,
                          input_shape):
    """Slope-time the inference-only program: isolates how much of the
    train step's MFU gap lives in forward vs backward+update."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu.compiler import build_forward

    rng = numpy.random.RandomState(0)
    params = [{k: jnp.asarray(v, jnp.bfloat16)
               for k, v in (s or {}).items() if v is not None}
              for s in state]
    x = jax.device_put(
        (rng.rand(batch, *input_shape) * 0.5).astype(numpy.float32)
    ).astype(jnp.bfloat16)
    fwd = build_forward(plans)

    @jax.jit
    def fstep(params, x):
        return fwd(params, x).sum().astype(jnp.float32)

    float(fstep(params, x))  # compile + first exec

    def aval(t):
        return jax.ShapeDtypeStruct(t.shape, t.dtype)
    cost = fstep.lower(jax.tree.map(aval, params),
                       aval(x)).compile().cost_analysis()
    flops = float(cost.get("flops", 0)) if cost else 0.0

    def chain(k):
        start = time.perf_counter()
        v = None
        for _ in range(k):
            v = fstep(params, x)
        float(v)
        return time.perf_counter() - start

    from bench import _filter_passes, _spread
    slopes = []
    for _ in range(5):
        t1, t2 = chain(4), chain(24)
        slopes.append((t2 - t1) / 20)
    # the published median rides the jitter-filtered passes; the spread
    # block records passes_used + every per-pass slope (see main())
    per = float(numpy.median(_filter_passes(slopes)))
    row = {"step_ms": round(per * 1e3, 3),
           "images_per_sec": round(batch / per, 1),
           "spread": _spread(slopes)}
    if flops:
        row["xla_flops_per_step_g"] = round(flops / 1e9, 2)
        row["tflops"] = round(flops / per / 1e12, 1)
        row["mfu_pct"] = round(100.0 * flops / per / peak_flops, 1)
    return row


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="alexnet",
                        choices=("alexnet", "vgg16", "vgg11"),
                        help="model family from the zoo")
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--out", default=None,
                        help="report path; defaults to MFU.json for "
                             "alexnet, MFU_<MODEL>.json otherwise so "
                             "a VGG run can't clobber the committed "
                             "AlexNet record")
    parser.add_argument("--skip-measure", action="store_true",
                        help="analytic table only (no chip)")
    parser.add_argument("--fwd-split", action="store_true",
                        help="also measure the forward-only program "
                             "(one extra ~60 s server compile) to "
                             "attribute the MFU gap between forward "
                             "and backward+update")
    args = parser.parse_args()
    if args.out is None:
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        name = ("MFU.json" if args.model == "alexnet"
                else "MFU_%s.json" % args.model.upper())
        args.out = os.path.join(repo, name)

    from veles_tpu.models.zoo import (alexnet_layers,
                                      build_plans_and_state,
                                      vgg_layers)

    if args.model == "alexnet":
        specs, input_shape = alexnet_layers(classes=1000), (227, 227, 3)
    else:
        config = "D" if args.model == "vgg16" else "A"
        specs, input_shape = (vgg_layers(classes=1000, config=config),
                              (224, 224, 3))
    plans, state, _ = build_plans_and_state(specs, input_shape, seed=1)
    rows = layer_shapes(plans, state, input_shape, args.batch)

    peak_flops = PEAK_BF16_TFLOPS * 1e12
    bw = HBM_GBPS * 1e9
    # a populated schedule cache means tuned tiles may be serving some
    # layers' backward kernels: annotate each row with the schedule's
    # provenance so a future MFU regression is attributable to the
    # schedule that actually ran (docs/kernels.md "Autotuning")
    from veles_tpu.tune.cache import cache_for
    schedule_cache = cache_for()
    annotate = len(schedule_cache) > 0
    layers = []
    for (name, ish, osh, pbytes), plan, params in zip(
            rows, plans, state):
        fl, tr = analytic_layer(name, ish, osh, pbytes)
        t_mxu = fl / peak_flops
        t_hbm = tr / bw
        row = {
            "layer": name, "in": list(ish), "out": list(osh),
            "train_gflops": round(fl / 1e9, 2),
            "hbm_mbytes": round(tr / 1e6, 1),
            "t_mxu_us": round(t_mxu * 1e6, 1),
            "t_hbm_us": round(t_hbm * 1e6, 1),
            "bound": ("mxu" if t_mxu > t_hbm else "hbm"),
            "roofline_us": round(max(t_mxu, t_hbm) * 1e6, 1),
        }
        if annotate:
            prov = schedule_provenance(plan, params, ish, osh,
                                       args.dtype)
            if prov is not None:
                row["schedule"] = prov
        layers.append(row)
    total_roofline = sum(l["roofline_us"] for l in layers) / 1e6

    report = {
        "config": {"model": args.model, "batch": args.batch,
                   "dtype": args.dtype,
                   "peak_bf16_tflops": PEAK_BF16_TFLOPS,
                   "hbm_gbps": HBM_GBPS},
        "layers": layers,
        "roofline_total_ms": round(total_roofline * 1e3, 2),
    }
    if annotate:
        report["config"]["schedule_cache"] = schedule_cache.path

    if not args.skip_measure:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from bench import _train_step_images_per_sec
        dataset_size = max(1024, args.batch * 2)
        per_step, ips, flops, spread = _train_step_images_per_sec(
            specs, input_shape, args.batch, dataset_size, args.dtype,
            (4, 24) if args.batch > 128 else (4, 44), classes=1000)
        measured = {
            "step_ms": round(per_step * 1e3, 3),
            "images_per_sec": round(ips, 1),
            "spread": spread,
        }
        if flops:
            measured["xla_flops_per_step_g"] = round(flops / 1e9, 2)
            measured["tflops"] = round(flops / per_step / 1e12, 2)
            measured["mfu_pct"] = round(
                100.0 * flops / per_step / peak_flops, 1)
        report["measured"] = measured
        report["model_vs_measured_ratio"] = round(
            total_roofline / per_step, 3)

        if args.fwd_split:
            report["forward_only"] = _measure_forward_only(
                plans, state, args.batch, peak_flops, input_shape)
            fwd = report["forward_only"]
            bwd_ms = measured["step_ms"] - fwd["step_ms"]
            fwd_g = fwd.get("xla_flops_per_step_g")
            bwd_flops = (flops - fwd_g * 1e9
                         if flops and fwd_g else None)
            split = {"bwd_plus_update_ms": round(bwd_ms, 3)}
            if bwd_flops:
                split["bwd_tflops"] = round(
                    bwd_flops / (bwd_ms / 1e3) / 1e12, 1)
                split["bwd_mfu_pct"] = round(
                    100.0 * bwd_flops / (bwd_ms / 1e3) / peak_flops, 1)
            report["backward_attribution"] = split

    # the story the table tells, computed so it can't go stale
    hbm_us = sum(l["roofline_us"] for l in layers
                 if l["bound"] == "hbm")
    mxu_us = sum(l["roofline_us"] for l in layers
                 if l["bound"] == "mxu")
    top = sorted(layers, key=lambda l: -l["roofline_us"])[:3]
    top_txt = ", ".join("%s (%.0fus %s)" % (
        l["layer"], l["roofline_us"], l["bound"]) for l in top)
    hbm_share = hbm_us / max(hbm_us + mxu_us, 1e-9)
    attainable = None
    if not args.skip_measure and report.get("measured", {}).get(
            "xla_flops_per_step_g"):
        # MFU the roofline permits: XLA's own FLOP count over the
        # roofline time at chip peak
        attainable = round(
            100.0 * report["measured"]["xla_flops_per_step_g"] * 1e9
            / (total_roofline * peak_flops), 1)
        report["roofline_attainable_mfu_pct"] = attainable
    if hbm_share > 0.5:
        report["conclusion"] = (
            "%.0f%% of roofline time sits in HBM-bound layers "
            "(%.0fus hbm vs %.0fus mxu); top costs: %s.  The non-MXU "
            "share of the step is memory traffic — raising MFU means "
            "cutting activation traffic (fusion/remat), not faster "
            "matmuls." % (100 * hbm_share, hbm_us, mxu_us, top_txt))
    else:
        split = ""
        fwd = report.get("forward_only")
        bwd = report.get("backward_attribution")
        if fwd and bwd and fwd.get("mfu_pct"):
            split = (
                "  Measured split: forward %.0f%% MFU, "
                "backward+update %.0f%%."
                % (fwd["mfu_pct"], bwd.get("bwd_mfu_pct", 0)))
        alexnet_note = (
            "  Round-5 attribution (interleaved A/B receipts in "
            "scripts/bwd_experiments.py, step_ab.py, "
            "pool_bwd_experiment.py): isolated conv gradients run at "
            "~190 TF/s (near peak) under plain autodiff, an exact "
            "hand-scheduled conv VJP changes the whole step by 0.1%, "
            "pool select-and-scatter beats a patches formulation 6x, "
            "and plain-SGD vs product step differ by 0.3 ms — the "
            "gap between a congested-run backward MFU and forward "
            "MFU is congestion arithmetic plus composition slack, "
            "not any one op's schedule."
            if args.model == "alexnet" else "")
        report["conclusion"] = (
            "The roofline is MXU-bound (%.0fus mxu vs %.0fus hbm; "
            "top costs: %s)%s.%s%s  Caveat: tunnel/chip congestion "
            "swings whole-run throughput ~1.4x between runs with "
            "tight within-run spreads, so cross-run MFU deltas below "
            "that band are weather, not code." % (
                mxu_us, hbm_us, top_txt,
                ("; the roofline would permit ~%.0f%% MFU"
                 % attainable) if attainable else "", split,
                alexnet_note))

    with open(args.out, "w") as fout:
        json.dump(report, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print(json.dumps(report.get("measured", {})))
    print("roofline total %.2f ms; wrote %s" % (
        total_roofline * 1e3, args.out))


if __name__ == "__main__":
    main()
