"""Elastic-mesh soak: live-reshard a ZeRO-1 training mesh through a
seeded shrink/grow/swap schedule and prove the elastic-mesh contract
with receipts (ELASTIC_MESH.json; docs/distributed.md, "Elastic mesh
contract").

The driver trains the chaos-suite MLP through a
:class:`veles_tpu.parallel.mesh.MeshManager` over 8 virtual CPU
devices (``--xla_force_host_platform_device_count``; the protocol
under test — consistent-hash ownership, slot-table repack, digest-
keyed compile cache — is device-agnostic) and receipts:

- **fixed-mesh bit-identity**: the ZeRO-1 step (reduce-scatter +
  all-gather, sharded optimizer state) produces bit-identical params
  AND solver accumulators to the flat all-reduce SPMD step
  (``grad_bucket_mb=inf``) on a fixed mesh;
- **ZeRO-1 memory**: per-device optimizer-state bytes shrink ~1/N
  versus the replicated flat path (measured from the live arrays'
  ``addressable_shards``; ``device_memory_gauges`` rides along);
- **soaked convergence**: final weights after the seeded
  shrink->coalesced-shrink->grow->swap schedule stay within the TP
  ULP contract (<= 1e-3 max rel, docs/parallel.md) of the fault-free
  fixed-mesh run — reshards move rows, never values, so the only
  drift is the reduce association order changing with N;
- **minimal movement**: every reshard's ``bytes_moved`` equals the
  changed-owner fraction of the state and stays strictly under the
  full-gather reference (``n_shards`` rows) the receipt carries;
- **warm rejoin**: growing back to a previously-seen device set hits
  the digest-keyed compile cache (no recompile in the recovery path);
- **exactly-once minibatches**: the soak and the crash leg consume
  every minibatch index exactly once — nothing lost, nothing
  double-applied across reshard or crash-recovery boundaries;
- **crash-mid-reshard recovery**: ``mesh.reshard=crash`` dies after
  the safety snapshot, before destructive movement;
  ``MeshManager.resume`` (the ``--resume auto`` path) rebuilds from
  the manifest-verified snapshot and the finished run is bit-identical
  to the uninterrupted elastic run.

    python scripts/mesh_soak.py --out ELASTIC_MESH.json \
        [--steps 12] [--seed 42]

Exit code 0 only when every gate holds.  The tier-1 equivalents live
in tests/test_mesh.py (``mesh`` marker).
"""

import argparse
import json
import os
import random
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy  # noqa: E402

#: global batch — divisible by every mesh size the schedule can reach
BATCH = 48
FAN_IN, HIDDEN, CLASSES = 16, 32, 4
#: the TP ULP contract bound the soaked run must stay inside
#: (docs/parallel.md: association order changes with N, values don't)
ULP_BOUND = 1e-3


def _plans():
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    hyper = {"learning_rate": 0.1, "gradient_moment": 0.9}
    return [LayerPlan(All2AllTanh, hyper=hyper),
            LayerPlan(All2AllSoftmax, hyper=hyper)]


def _state(seed):
    rng = numpy.random.RandomState(seed)
    out = []
    for fi, fo in ((FAN_IN, HIDDEN), (HIDDEN, CLASSES)):
        out.append({
            "weights": rng.randn(fi, fo).astype(numpy.float32) * 0.1,
            "bias": numpy.zeros(fo, numpy.float32),
            "accum_weights": numpy.zeros((fi, fo), numpy.float32),
            "accum_bias": numpy.zeros(fo, numpy.float32),
            "accum2_weights": None, "accum2_bias": None})
    return out


def _data(seed, steps):
    rng = numpy.random.RandomState(seed + 1)
    xs = [rng.randn(BATCH, FAN_IN).astype(numpy.float32)
          for _ in range(steps)]
    ys = [(rng.randint(0, CLASSES, BATCH)).astype(numpy.int32)
          for _ in range(steps)]
    return xs, ys


def _schedule(seed, steps):
    """The seeded membership schedule: (step -> list of device-set
    builders).  Two sets submitted at one boundary prove coalescing;
    the grow back to the full set proves the warm rejoin; the swap
    proves ownership follows device identity, not position."""
    rnd = random.Random(seed)
    # four event boundaries spread over the run, in order, >= 1 apart
    marks = sorted(rnd.sample(range(2, steps - 1), 4))
    return {
        marks[0]: [lambda d: d[:6]],                 # shrink 8 -> 6
        marks[1]: [lambda d: d[:5], lambda d: d[:4]],  # coalesce -> 4
        marks[2]: [lambda d: d],                     # grow 4 -> 8 (warm)
        marks[3]: [lambda d: d[2:8]],                # swap to 6 others
    }


def _final(mgr):
    return mgr.canonical_state()


def _max_rel(a, b):
    worst = 0.0
    for pa, pb in zip(a, b):
        for key in ("weights", "bias", "accum_weights", "accum_bias"):
            x = numpy.asarray(pa[key], numpy.float64)
            y = numpy.asarray(pb[key], numpy.float64)
            denom = numpy.maximum(numpy.abs(y), 1e-12)
            worst = max(worst, float(numpy.max(numpy.abs(x - y) / denom)))
    return worst


def _bit_identical(a, b, keys=("weights", "bias", "accum_weights",
                               "accum_bias")):
    return all(numpy.array_equal(numpy.asarray(pa[k]),
                                 numpy.asarray(pb[k]))
               for pa, pb in zip(a, b) for k in keys)


def _accum_device_bytes(state, devices):
    """Per-device bytes of optimizer state measured from the live
    arrays' addressable shards (works for replicated AND sharded
    placements; host-numpy leaves count as fully replicated)."""
    per_device = {d.id: 0 for d in devices}
    for entry in state:
        for key in ("accum_weights", "accum_bias", "accum2_weights",
                    "accum2_bias"):
            arr = entry.get(key)
            if arr is None:
                continue
            shards = getattr(arr, "addressable_shards", None)
            if shards is None:
                for d in per_device:
                    per_device[d] += int(arr.nbytes)
                continue
            for shard in shards:
                per_device[shard.device.id] += int(shard.data.nbytes)
    return per_device


def leg_fixed_identity(steps, seed):
    """Flat all-reduce vs ZeRO-1 on the SAME fixed 8-device mesh:
    bit-identical state, and the per-device optimizer bytes ratio."""
    import jax

    from veles_tpu import compiler
    from veles_tpu.observe.xla_introspect import device_memory_gauges
    from veles_tpu.parallel.mesh import MeshManager, auto_mesh
    devices = sorted(jax.devices(), key=lambda d: d.id)
    xs, ys = _data(seed, steps)
    mesh = auto_mesh("data", devices)

    flat_step = compiler.build_train_step(
        _plans(), mesh=mesh, grad_bucket_mb=float("inf"), donate=False)
    flat_state = _state(seed)
    for i in range(steps):
        flat_state, flat_metrics = flat_step(
            flat_state, xs[i], ys[i], numpy.float32(BATCH))
    flat_bytes = _accum_device_bytes(flat_state, devices)

    mgr = MeshManager(_plans(), _state(seed), devices=devices,
                      n_shards=16, donate=False)
    for i in range(steps):
        zero_metrics = mgr.step(xs[i], ys[i])
    zero_bytes = _accum_device_bytes(mgr._state, devices)

    flat_final = [{k: numpy.asarray(v) for k, v in e.items()
                   if v is not None} for e in flat_state]
    identical = _bit_identical(flat_final, _final(mgr))
    ratio = (max(zero_bytes.values()) / max(flat_bytes.values())
             if max(flat_bytes.values()) else None)
    return {
        "steps": steps,
        "flat_vs_zero_bit_identical": bool(identical),
        "loss_last": {"flat": float(flat_metrics["loss"]),
                      "zero": float(zero_metrics["loss"])},
        "grad_norm_last": {"flat": float(flat_metrics["grad_norm"]),
                           "zero": float(zero_metrics["grad_norm"])},
        "zero1_memory": {
            "n_devices": len(devices),
            "n_shards": mgr.n_shards,
            "flat_per_device_opt_bytes": max(flat_bytes.values()),
            "zero_per_device_opt_bytes": max(zero_bytes.values()),
            "per_device_ratio": None if ratio is None
            else round(ratio, 4),
            # ~1/N plus the ceil-division pad on each tensor
            "bound": round(1.5 / len(devices), 4),
            "device_memory_gauges": device_memory_gauges(),
        },
    }


def _run_elastic(steps, seed, schedule, snapshot_dir=None, crash=False):
    """One elastic run over the seeded schedule; returns (manager,
    ledger of minibatch indices consumed, crash/resume count)."""
    import jax

    from veles_tpu import chaos
    from veles_tpu.parallel.mesh import MeshManager
    devices = sorted(jax.devices(), key=lambda d: d.id)
    xs, ys = _data(seed, steps)
    mgr = MeshManager(_plans(), _state(seed), devices=devices,
                      n_shards=16, snapshot_dir=snapshot_dir,
                      donate=False)
    if crash:
        chaos.install(chaos.FaultPlan.from_spec("mesh.reshard=crash:n1"))
    ledger = []
    resumes = 0
    last_devices = devices
    try:
        while mgr.applied_steps < steps:
            for build in schedule.get(mgr.applied_steps, ()):
                last_devices = mgr._order(build(devices))
                mgr.submit_membership(last_devices)
            i = mgr.applied_steps
            try:
                mgr.step(xs[i], ys[i])
            except chaos.ChaosCrash:
                # "process died" mid-reshard: the --resume auto path
                resumes += 1
                mgr = MeshManager.resume(snapshot_dir, _plans(),
                                         devices=last_devices,
                                         donate=False)
                continue
            ledger.append(i)
    finally:
        if crash:
            chaos.uninstall()
    return mgr, ledger, resumes


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="ELASTIC_MESH.json")
    parser.add_argument("--steps", type=int, default=12)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args(argv)
    t0 = time.time()

    print("== fixed-mesh flat-vs-ZeRO identity + memory ==")
    fixed = leg_fixed_identity(args.steps, args.seed)
    mem = fixed["zero1_memory"]
    print("   bit_identical=%s, per-device opt bytes %d -> %d (%.3fx)"
          % (fixed["flat_vs_zero_bit_identical"],
             mem["flat_per_device_opt_bytes"],
             mem["zero_per_device_opt_bytes"],
             mem["per_device_ratio"] or 0))

    print("== fault-free fixed-mesh reference ==")
    ref, ref_ledger, _ = _run_elastic(args.steps, args.seed, {})
    ref_state = _final(ref)

    print("== elastic soak: seeded shrink/coalesce/grow/swap ==")
    schedule = _schedule(args.seed, args.steps)
    soak, soak_ledger, _ = _run_elastic(args.steps, args.seed, schedule)
    soak_state = _final(soak)
    max_rel = _max_rel(soak_state, ref_state)
    sizes = [ev["to_size"] for ev in soak.reshard_log]
    print("   reshards %s, max_rel vs fault-free %.3g" % (sizes, max_rel))

    print("== crash-mid-reshard recovery (mesh.reshard=crash) ==")
    with tempfile.TemporaryDirectory() as snapdir:
        crashed, crash_ledger, resumes = _run_elastic(
            args.steps, args.seed, schedule, snapshot_dir=snapdir,
            crash=True)
        crash_state = _final(crashed)
    crash_identical = _bit_identical(crash_state, soak_state)
    print("   resumes=%d, bit_identical to uninterrupted soak: %s"
          % (resumes, crash_identical))

    want_ledger = list(range(args.steps))
    movement_ok = all(
        ev["bytes_moved"] == round(
            ev["changed_fraction"] * ev["full_gather_bytes"])
        and ev["bytes_moved"] < ev["full_gather_bytes"]
        for ev in soak.reshard_log)
    from veles_tpu.observe.metrics import registry as _registry
    gates = {
        "flat_vs_zero_bit_identical":
            fixed["flat_vs_zero_bit_identical"],
        "zero1_memory_1_over_n":
            mem["per_device_ratio"] is not None
            and mem["per_device_ratio"] <= mem["bound"],
        "soak_within_ulp_bound": max_rel <= ULP_BOUND,
        "minibatch_ledger_exact":
            ref_ledger == want_ledger and soak_ledger == want_ledger
            and crash_ledger == want_ledger,
        "movement_minimal": movement_ok,
        "coalesced_event_seen":
            _registry.counter("mesh.coalesced_events").value >= 1,
        "warm_rejoin_compile_cached": any(
            ev["compile_cached"] for ev in soak.reshard_log
            if ev["to_size"] == 8),
        "crash_recovery_bit_identical": bool(crash_identical),
        "crash_resumed_once": resumes == 1,
    }
    receipt = {
        "schema": "elastic-mesh-soak-v1",
        "generated_unix": int(time.time()),
        "platform": "cpu (JAX_PLATFORMS=cpu, 8 virtual devices — the "
                    "ownership/repack/compile-cache protocol under "
                    "test is device-agnostic; TPU-pod receipt is the "
                    "outstanding ROADMAP item)",
        "seed": args.seed,
        "config": {
            "steps": args.steps, "batch": BATCH,
            "layers": "all2all_tanh(%d)+softmax(%d), momentum 0.9"
                      % (HIDDEN, CLASSES),
            "n_shards": 16, "ulp_bound": ULP_BOUND,
        },
        "fixed_identity": fixed,
        "soak": {
            "schedule_sizes": sizes,
            "reshard_events": soak.reshard_log,
            "applied_steps": soak.applied_steps,
            "max_rel_vs_fault_free": max_rel,
            "bytes_moved_total": sum(
                ev["bytes_moved"] for ev in soak.reshard_log),
            "full_gather_total": sum(
                ev["full_gather_bytes"] for ev in soak.reshard_log),
        },
        "crash_recovery": {
            "resumes": resumes,
            "bit_identical_to_uninterrupted": bool(crash_identical),
            "applied_steps": crashed.applied_steps,
            "minibatches_lost": len(set(want_ledger) -
                                    set(crash_ledger)),
            "minibatches_double_applied": len(crash_ledger) -
            len(set(crash_ledger)),
        },
        "wall_s": round(time.time() - t0, 1),
        "gates": gates,
        "pass": all(gates.values()),
    }
    with open(args.out, "w") as fh:
        json.dump(receipt, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print("wrote %s: %d reshards, pass=%s (%s)" % (
        args.out, len(soak.reshard_log), receipt["pass"],
        ", ".join(k for k, v in gates.items() if not v) or "all gates"))
    return 0 if receipt["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
