"""Freshness-loop chaos soak -> FRESH.json receipt.

The acceptance proof of the train-to-serve loop (docs/serving.md
"Freshness loop", ROADMAP "close the loop"): a trainer continuously
publishing manifest-verified snapshots and a multi-replica serve fleet
picking them up through the canary state machine, with chaos faults on
BOTH sides:

- trainer: ``snapshot.write=crash`` (die mid-export, torn ``.tmp``,
  no final file — the trainer "restarts" and re-exports) and
  ``freshness.publish=truncate`` (a torn NON-atomic copy lands at the
  final published path — the watcher must skip-and-retry, then
  TTL-reject, and the re-publish supersedes it);
- servers: ``serve.stall`` (a replica's worker stalls mid-soak);
- poison: one snapshot with NaN params (must die at the finite gate /
  watcher — ``poisoned``) and one with finite-but-garbage weights
  (the failure a static check CANNOT see: must be caught by the
  mirrored canary comparator and auto-ROLLED BACK with **zero new
  compiles**, never promoted).

Closed-loop clients hammer the pool the whole time; the receipt
asserts **zero dropped requests** across every cutover, that no
poisoned/garbage snapshot ever reached full-fleet cutover, and that
rollback restored the last-good weights (value-digest checked) without
compiling anything.

Usage::

    python scripts/freshness_soak.py --out FRESH.json          # full
    python scripts/freshness_soak.py --fast --out /tmp/F.json  # smoke

The fast profile is the tier-1 smoke (tests/test_freshness.py); the
full profile is the committed FRESH.json receipt.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy  # noqa: E402


def _mlp_spec(seed=0, fan_in=16, hidden=16, classes=4):
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    rng = numpy.random.RandomState(seed)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": rng.rand(hidden).astype(numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": rng.rand(classes).astype(numpy.float32)},
    ]
    return plans, params


def _perturb(params, scale, seed):
    rng = numpy.random.RandomState(seed)
    out = []
    for entry in params:
        out.append({
            key: None if leaf is None else
            (leaf + scale * rng.randn(*leaf.shape).astype(leaf.dtype))
            for key, leaf in entry.items()})
    return out


def _poison(params, value=float("nan")):
    return [{key: None if leaf is None else
             numpy.full_like(leaf, value) for key, leaf in entry.items()}
            for entry in params]


def _garbage(params):
    """Finite but WRONG: the classifier head's output classes permuted
    — a model that confidently answers the wrong question.  Invisible
    to the finite gate (every value is healthy), undetectable by any
    static check; catching this on mirrored traffic is exactly the
    canary comparator's job."""
    out = [dict(entry) for entry in params]
    head = params[-1]
    out[-1] = {key: None if leaf is None else
               numpy.roll(leaf, 1, axis=leaf.ndim - 1)
               for key, leaf in head.items()}
    return out


def _schedule(good_cycles, fast):
    """Cycle plan: 'good' promotes interleaved with the two poison
    shapes.  The nan case lands early (prove the gate before investing
    in promotes), the garbage case after at least one promote (so the
    rollback has a non-initial last-good to restore)."""
    sched = ["good"] * good_cycles
    sched.insert(1, "nan")
    if not fast:
        sched.insert(3, "garbage")
    else:
        sched.append("garbage")
    return sched


def _wait_cycle(controller, ordinal, timeout):
    """Block until the controller verdicts `ordinal` (history entry) or
    the watcher TTL-rejects it; returns the history entry or None."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for entry in controller.history:
            if entry["ordinal"] == ordinal:
                return entry
        if ordinal in controller.watcher._rejected:
            return None
        time.sleep(0.02)
    raise TimeoutError("no verdict for publish #%d within %.1fs" %
                       (ordinal, timeout))


def run_soak(good_cycles=6, replicas=3, clients=4, fast=False,
             seed=7, publish_keep=8, out=None):
    from veles_tpu import chaos
    from veles_tpu.observe.metrics import registry
    from veles_tpu.serve import (
        FreshnessController, ReplicaPool, export_model_spec,
        value_digest)
    from veles_tpu.snapshotter import publish_snapshot

    workdir = tempfile.mkdtemp(prefix="freshness_soak_")
    publish_dir = os.path.join(workdir, "publish")
    train_dir = os.path.join(workdir, "train")
    os.makedirs(train_dir)
    # the poison cycles dump the flight ring on purpose: keep the
    # dumps with the soak artifacts, not in the caller's cwd
    from veles_tpu.observe.flight import flight
    flight.base_path = os.path.join(workdir, "veles_flight")
    ladder = (8,) if fast else (8, 32)

    plans, params = _mlp_spec(seed=seed)
    pool = ReplicaPool(plans, params, (16,), replicas=replicas,
                       ladder=ladder, max_delay_s=0.001,
                       max_queue=4096,
                       cache_root=os.path.join(workdir, "cache"))
    pool.compile()
    pool.start()
    controller = FreshnessController(
        pool, publish_dir, poll_s=0.02, invalid_ttl_s=0.6,
        mirror_fraction=0.5, min_mirrors=4 if fast else 8,
        divergence_limit=0.5, breach_budget=2,
        verdict_timeout_s=20.0, seed=seed).start()

    # chaos on both sides: the 2nd spec export crashes mid-write, the
    # 3rd publish lands torn at the final path, replicas stall at
    # random throughout (param well under the comparator's latency
    # floor so a stall never fakes a quality regression)
    plan = (chaos.FaultPlan(seed=seed)
            .add("snapshot.write", "crash", nth=2)
            .add("freshness.publish", "truncate", nth=3)
            .add("serve.stall", "stall", probability=0.02,
                 param=0.03))
    chaos.install(plan)

    stop = threading.Event()
    ok_count = [0] * clients
    dropped = []

    def client(k):
        rng = numpy.random.RandomState(100 + k)
        x = rng.rand(16).astype(numpy.float32)
        while not stop.is_set():
            try:
                pool.infer(x, timeout=15.0)
                ok_count[k] += 1
            except Exception as exc:  # EVERY failure is a drop
                dropped.append("%s: %s" % (type(exc).__name__, exc))
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,),
                                name="soak-client-%d" % k)
               for k in range(clients)]
    for t in threads:
        t.start()

    cycles = []
    trainer_crashes = 0
    republishes = 0
    seq = 0
    last_promoted = value_digest(params)
    try:
        for kind in _schedule(good_cycles, fast):
            seq += 1
            if kind == "good":
                cand = _perturb(params, 0.02 * seq, seed + seq)
            elif kind == "nan":
                cand = _poison(params)
            else:
                cand = _garbage(params)
            entry = None
            attempts = 0
            while entry is None:
                attempts += 1
                if attempts > 6:
                    raise RuntimeError(
                        "cycle %d (%s) burned %d attempts" %
                        (seq, kind, attempts))
                path = os.path.join(train_dir,
                                    "spec_%03d_%d.pickle" %
                                    (seq, attempts))
                try:
                    export_model_spec(path, plans, cand, (16,))
                except chaos.ChaosCrash:
                    trainer_crashes += 1  # "trainer restarts", re-export
                    continue
                try:
                    receipt = publish_snapshot(path, publish_dir,
                                               keep=publish_keep)
                except chaos.ChaosCrash:
                    trainer_crashes += 1  # LATEST never flipped
                    continue
                entry = _wait_cycle(controller, receipt["ordinal"],
                                    timeout=60.0)
                if entry is None:
                    republishes += 1  # torn publish TTL-rejected
            expected = value_digest(cand) if kind == "good" else None
            cycles.append({
                "kind": kind, "attempts": attempts,
                "ordinal": entry["ordinal"],
                "verdict": entry["verdict"],
                "mirrors": entry.get("mirrors"),
                "new_compiles": entry.get("new_compiles"),
                "reason": entry.get("reason"),
            })
            if kind == "good" and entry["verdict"] == "promoted":
                last_promoted = expected
        time.sleep(0.3)  # a little steady-state traffic post-cutovers
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        controller.stop()
        chaos.uninstall()
        pool.stop()

    promoted = [c for c in cycles
                if c["kind"] == "good" and c["verdict"] == "promoted"]
    poison_cases = [c for c in cycles if c["kind"] in ("nan", "garbage")]
    poison_contained = [c for c in poison_cases
                        if c["verdict"] in ("poisoned", "rolled_back")]
    rollbacks = [c for c in cycles if c["verdict"] == "rolled_back"]
    served_digest = value_digest(pool.engine.params)
    receipt = {
        "schema": 1,
        "mode": "fast" if fast else "full",
        "replicas": replicas,
        "ladder": list(ladder),
        "clients": clients,
        "cycles": cycles,
        "chaos": {
            "plan": "snapshot.write=crash:n2; "
                    "freshness.publish=truncate:n3; "
                    "serve.stall=stall:p0.02:0.03",
            "trainer_crashes": trainer_crashes,
            "torn_publishes_rejected": republishes,
            "replica_stalls": plan.fired("serve.stall"),
        },
        "requests_served": sum(ok_count),
        "requests_dropped": len(dropped),
        "dropped_detail": dropped[:5],
        "counters": {
            name.rsplit(".", 1)[1]: registry.counter(name).value
            for name in (
                "serve.freshness.published",
                "serve.freshness.candidates",
                "serve.freshness.promotions",
                "serve.freshness.rollbacks",
                "serve.freshness.poisoned_rejected")},
        "checks": {
            "promote_cycles": len(promoted),
            "zero_dropped_requests": not dropped,
            "poison_cases": len(poison_cases),
            "poison_contained": len(poison_contained),
            "poison_never_promoted": (
                len(poison_contained) == len(poison_cases)),
            "rollback_zero_new_compiles": all(
                c["new_compiles"] == 0 for c in rollbacks),
            "fleet_serves_last_promoted": (
                served_digest == last_promoted),
        },
    }
    passed = (receipt["checks"]["zero_dropped_requests"] and
              receipt["checks"]["poison_never_promoted"] and
              receipt["checks"]["rollback_zero_new_compiles"] and
              receipt["checks"]["fleet_serves_last_promoted"] and
              len(promoted) >= (2 if fast else 5))
    receipt["passed"] = passed
    if out:
        with open(out, "w") as fout:
            json.dump(receipt, fout, indent=1, sort_keys=True)
            fout.write("\n")
    print("freshness soak %s: %d promotes, %d rollbacks, %d poisoned "
          "rejected, %d served, %d dropped, trainer crashes %d, torn "
          "publishes %d" %
          ("PASSED" if passed else "FAILED", len(promoted),
           len(rollbacks),
           receipt["counters"]["poisoned_rejected"],
           receipt["requests_served"], len(dropped), trainer_crashes,
           republishes))
    return receipt


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--cycles", type=int, default=6,
                        help="good (promote) cycles; nan/garbage "
                        "poison cycles are added on top")
    parser.add_argument("--replicas", type=int, default=3)
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--fast", action="store_true",
                        help="smoke profile: 2 promote cycles, "
                        "single-rung ladder (the tier-1 test)")
    parser.add_argument("--out", default="FRESH.json")
    args = parser.parse_args(argv)
    receipt = run_soak(
        good_cycles=2 if args.fast else args.cycles,
        replicas=args.replicas, clients=args.clients, fast=args.fast,
        seed=args.seed, out=args.out)
    return 0 if receipt["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
